"""tpu-inference: the rebuild's new pipeline stage (the north star).

"A new tpu-inference tenant-engine microservice sits between
inbound-processing and event-management on the bus, micro-batching
DeviceMeasurement events into JAX/XLA pjit calls on a TPU pod"
(BASELINE.json north_star; no reference counterpart — SURVEY.md §2.3).

Dataflow per scoring cycle (the zero-copy columnar feed path —
docs/PERFORMANCE.md has the full stage walkthrough):

  inbound-events[tenant_i] ─┐  MeasurementBatch (struct-of-arrays)
  inbound-events[tenant_j] ─┼→ lane RINGS[(slot, data_shard)]: rows are
          ...              ─┘  written into preallocated numpy segments
                                AT ENQUEUE │ flush on deadline_ms OR full
                                     ▼
              reusable staging buffers u16/bf16[T, D·B] (slice copies,
              two rotating sets per (family, bucket) — no fresh arrays)
                                     ▼
              stage_inputs — ASYNC h2d onto the step's shardings;
              overlaps the previous flush's device compute
                                     ▼
              ShardedScorer.step_counts — ONE jit call, every tenant
                                     ▼
              gather_rows — device-side compaction: only the flushed
              rows' scores leave the chip (wire dtype; d2h bytes are
              rows-proportional, never the T×lane plane)
                                     ▼ (copy_to_host_async issued at
                                        dispatch — the transfer rides
                                        under the next flush's compute)
              completion REAPER — resolves flushes as transfers land:
              out of order across families, FIFO per family (so every
              tenant's batches publish in order)
                                     ▼
              columnar resolve: scores slice-assign back into each
              batch's ``scores`` column; completed batches →
              tpu-scored-events[tenant]

Three latency-hiding moves matter here (SURVEY.md §7 hard parts):
- the host side never touches per-event Python objects — rows move as
  numpy slices end to end, and a flush is slice+pad into reusable
  staging, never ``np.asarray`` over freshly built lists
  (tools/check_hotpath.py lints this invariant);
- the staged device put is issued BEFORE dispatch and is asynchronous,
  so flush N+1's host→device transfer rides under flush N's compute
  (``tpu_inference.h2d_overlapped`` / ``h2d_staged`` expose the ratio);
- the readback is the mirror image: a device-side gather returns only
  the flushed rows (``ShardedScorer.gather_rows``), its d2h copy is
  started asynchronously at dispatch, and a completion reaper resolves
  up to ``max_inflight`` in-flight flushes as their transfers land
  (``tpu_inference.d2h_overlapped`` counts transfers that landed before
  the reaper asked). One device round-trip never stalls the collect
  loop; p99 still lands in the ``tpu_inference.latency`` histogram.

Tenant start/stop flips the scorer's active mask — no recompile; batch-size
buckets keep XLA at a handful of compiled shapes.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from sitewhere_tpu.core.batch import MeasurementBatch
from sitewhere_tpu.core.events import DeviceMeasurement
from sitewhere_tpu.models import get_model, make_config
from sitewhere_tpu.parallel.mesh import MeshManager
from sitewhere_tpu.parallel.sharded import ShardedScorer
from sitewhere_tpu.parallel.tenant_router import TenantRouter
from sitewhere_tpu.runtime.bus import (
    CircuitBreaker,
    EventBus,
    publish_at_least_once,
)
from sitewhere_tpu.runtime.config import TenantEngineConfig
from sitewhere_tpu.runtime.lifecycle import (
    LifecycleState,
    SupervisedTask,
    cancel_and_wait,
)
from sitewhere_tpu.runtime.metrics import (
    D2H_OVERLAP_EPS_S as _D2H_OVERLAP_EPS_S,
    MetricsRegistry,
)
from sitewhere_tpu.runtime.tenant import MultitenantService, TenantEngine


def _profiler_annotation(enabled: bool, family: str):
    """A ``jax.profiler.TraceAnnotation`` around the scoring dispatch when
    the instance is capturing a profile (InstanceConfig.profile_dir), so
    per-family device time is attributable inside the trace; a cheap
    nullcontext otherwise — and on any profiler fault (the profiler is
    process-global and can be owned elsewhere)."""
    import contextlib

    if not enabled:
        return contextlib.nullcontext()
    try:
        import jax

        return jax.profiler.TraceAnnotation(f"tpu_scoring/{family}")
    except Exception:  # noqa: BLE001 - never let profiling break scoring
        return contextlib.nullcontext()


class StreamRegistry:
    """Per-tenant map (device_token, name) → (data_shard, local_id).

    Streams are pinned to a data shard at first sight (least-loaded wins),
    so window updates for a stream always land on the same device and the
    scoring step needs no collectives (see ``parallel.sharded``).
    """

    def __init__(self, n_data_shards: int, local_capacity: int) -> None:
        self.n_data_shards = n_data_shards
        self.local_capacity = local_capacity
        self._map: Dict[Tuple[str, str], Tuple[int, int]] = {}
        self._next: List[int] = [0] * n_data_shards

    def lookup_or_assign(
        self, device_token: str, name: str
    ) -> Optional[Tuple[int, int]]:
        key = (device_token, name)
        hit = self._map.get(key)
        if hit is not None:
            return hit
        shard = min(range(self.n_data_shards), key=lambda d: self._next[d])
        if self._next[shard] >= self.local_capacity:
            return None  # capacity exhausted; caller passes event through unscored
        local_id = self._next[shard]
        self._next[shard] += 1
        self._map[key] = (shard, local_id)
        return shard, local_id

    def lookup_or_assign_bulk(
        self, batch: MeasurementBatch
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Vectorized per-row (data_shard, local_id): one dict lookup per
        UNIQUE (token, name) pair; rows inherit via inverse indices. Rows
        that can't get a slot come back with shard == -1. Group indices
        come from the batch's cached token/name index (integer codes — no
        string sorts here)."""
        _, first, inverse = np.unique(
            batch.pair_codes(), return_index=True, return_inverse=True
        )
        tokens, names = batch.device_tokens, batch.names
        d_u = np.empty((len(first),), np.int32)
        l_u = np.empty((len(first),), np.int32)
        lookup = self.lookup_or_assign
        for j, fi in enumerate(first.tolist()):
            assigned = lookup(str(tokens[fi]), str(names[fi]))
            if assigned is None:
                d_u[j] = -1
                l_u[j] = 0
            else:
                d_u[j], l_u[j] = assigned
        return d_u[inverse], l_u[inverse]

    @property
    def n_streams(self) -> int:
        return len(self._map)


class _LaneRing:
    """Pending rows for one (slot, data_shard): a preallocated numpy ring.

    Rows are written into fixed-dtype ring segments at enqueue time
    (``push`` — slice assignment, no per-row Python, no per-enqueue
    allocation) and leave either straight into a flush's reusable staging
    buffers (``pop_into``) or as fresh arrays on the cold paths (``pop``:
    drain / park / breaker / failover). Capacity doubles when an intake
    burst overshoots — the per-tenant lane watermark bounds steady-state
    depth, so growth is rare and amortized.
    """

    COLS = ("ids", "vals", "seqs", "rows")
    __slots__ = COLS + ("head", "count")

    def __init__(self, capacity: int = 4096) -> None:
        cap = max(64, int(capacity))
        self.ids = np.empty((cap,), np.int32)   # local stream ids
        self.vals = np.empty((cap,), np.float32)
        self.seqs = np.empty((cap,), np.int64)  # batch sequence numbers
        self.rows = np.empty((cap,), np.int32)  # row index inside the batch
        self.head = 0
        self.count = 0

    @property
    def capacity(self) -> int:
        return len(self.ids)

    def _grow(self, need: int) -> None:
        cap = self.capacity
        new_cap = cap
        while new_cap < need:
            new_cap *= 2
        k = self.count
        first = min(k, cap - self.head)
        for name in self.COLS:
            old = getattr(self, name)
            new = np.empty((new_cap,), old.dtype)
            new[:first] = old[self.head : self.head + first]
            new[first:k] = old[: k - first]
            setattr(self, name, new)
        self.head = 0

    def push(self, ids, vals, seq, rows) -> None:
        """Append rows. ``seq`` may be a scalar (the per-enqueue common
        case — broadcast into the ring, no per-batch full() array)."""
        n = len(ids)
        if self.count + n > self.capacity:
            self._grow(self.count + n)
        cap = self.capacity
        tail = (self.head + self.count) % cap
        first = min(n, cap - tail)
        second = n - first
        self.ids[tail : tail + first] = ids[:first]
        self.vals[tail : tail + first] = vals[:first]
        self.rows[tail : tail + first] = rows[:first]
        if np.ndim(seq):
            self.seqs[tail : tail + first] = seq[:first]
        else:
            self.seqs[tail : tail + first] = seq
        if second:
            self.ids[:second] = ids[first:]
            self.vals[:second] = vals[first:]
            self.rows[:second] = rows[first:]
            self.seqs[:second] = seq[first:] if np.ndim(seq) else seq
        self.count += n

    def pop_into(
        self, k: int, ids_row, vals_row, col0: int, seqs_out, rows_out, off: int
    ) -> None:
        """Move k rows FIFO off the front, straight into one slot's
        staging views (``ids_row``/``vals_row`` at column ``col0`` — the
        dtype cast to the scorer's wire happens inside the slice write)
        and the flush's bookkeeping arrays at offset ``off``. At most two
        slice copies per column; zero intermediate arrays."""
        h, cap = self.head, self.capacity
        first = min(k, cap - h)
        second = k - first
        ids_row[col0 : col0 + first] = self.ids[h : h + first]
        vals_row[col0 : col0 + first] = self.vals[h : h + first]
        seqs_out[off : off + first] = self.seqs[h : h + first]
        rows_out[off : off + first] = self.rows[h : h + first]
        if second:
            ids_row[col0 + first : col0 + k] = self.ids[:second]
            vals_row[col0 + first : col0 + k] = self.vals[:second]
            seqs_out[off + first : off + k] = self.seqs[:second]
            rows_out[off + first : off + k] = self.rows[:second]
        self.head = (h + k) % cap
        self.count -= k

    def pop(self, n: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Take up to n rows off the front as fresh arrays (cold paths)."""
        k = min(int(n), self.count)
        h, cap = self.head, self.capacity
        first = min(k, cap - h)
        out = []
        for name in self.COLS:
            a = getattr(self, name)
            dst = np.empty((k,), a.dtype)
            dst[:first] = a[h : h + first]
            if k > first:
                dst[first:] = a[: k - first]
            out.append(dst)
        self.head = (h + k) % cap
        self.count -= k
        return tuple(out)


class _StagingSet:
    """One reusable flush staging set: ids/vals ``[T, D*B]`` in the
    scorer's wire dtypes, lane counts ``[T, D]``, and a cached column
    arange. A flush packs lanes into these buffers in place (no fresh
    ``np.zeros`` per flush) and ``jax.device_put``s them; ``staged``
    pins the device arrays from this set's LAST put — the async h2d copy
    reads the host buffers, so reuse must wait on it (two sets rotating
    per (family, bucket) normally hides that wait entirely)."""

    __slots__ = ("ids", "vals", "counts", "arange", "staged")

    def __init__(self, scorer, b_lane: int) -> None:
        t, d = scorer.n_slots, scorer.mm.n_data_shards
        self.ids = np.zeros((t, d * b_lane), scorer.ids_np_dtype)
        self.vals = np.zeros((t, d * b_lane), scorer.vals_np_dtype)
        self.counts = np.zeros((t, d), np.int32)
        self.arange = np.arange(d * b_lane, dtype=np.int32)
        self.staged = None

    def ensure_reusable(self, metrics) -> None:
        """Block until this set's previous device copy finished (counted;
        with overlap working the transfer is long done by recycle time)."""
        staged = self.staged
        if staged is None:
            return
        self.staged = None
        try:
            if all(a.is_ready() for a in staged):
                return
            metrics.counter("tpu_inference.stage_reuse_waits").inc()
            for a in staged:
                a.block_until_ready()
        except Exception:  # noqa: BLE001 - non-jax arrays (tests) or a
            # dead device buffer (failover mid-rotation): treat as free
            pass


class _PendingFlush:
    """One dispatched flush awaiting its device→host score transfer.

    ``scores`` is either the device-gathered row vector (``gathered``
    True — slice ``[:moved]`` is the picks, already in pack order) or
    the full score plane (fallback for scorers without ``gather_rows``,
    e.g. monkeypatched test doubles — the host then picks
    ``scores[slots, cols]``). The d2h copy was started at dispatch
    (``copy_to_host_async``); outputs that can't copy asynchronously
    get an eager executor materialization instead (``host_future``), so
    fallback flushes still overlap each other like the old per-flush
    deliver tasks did."""

    __slots__ = (
        "family", "scores", "taken", "moved", "gathered", "t_dispatch",
        "nbytes", "plane_nbytes", "host_future", "t_wait", "poisoned",
        "flops", "rec", "sketch", "shadow", "slot_override",
    )

    def __init__(
        self, family: str, scores, taken, moved: int, gathered: bool,
        nbytes: int, plane_nbytes: int, poisoned: bool = False,
        flops: float = 0.0, rec: Optional[dict] = None,
        sketch=None, shadow=None,
    ) -> None:
        self.family = family
        self.scores = scores
        self.taken = taken
        self.moved = moved
        self.gathered = gathered
        self.t_dispatch = time.perf_counter()
        self.nbytes = nbytes
        self.plane_nbytes = plane_nbytes
        self.host_future = None
        self.t_wait = None  # when the reaper first started waiting on us
        # a flush whose DISPATCH failed (no scores, no transfer): it
        # rides the FIFO so its unscored resolution can't overtake an
        # earlier in-flight flush of the same family
        self.poisoned = poisoned
        # device-time attribution: FLOPs this flush's padded plane
        # executes (scorer.flops_per_flush) and the flight-recorder
        # record completed in place when the flush resolves
        self.flops = flops
        self.rec = rec
        # score-quality payloads riding the same reaper slot: the step's
        # per-slot score sketch (i32[T, D, NBINS] — runtime.scorehealth)
        # and the canary's shadow-scored row vector (previous-variant
        # divergence). Their async host copies start at dispatch like the
        # scores'; by the time the scores land these few-KB transfers
        # have long since followed — no extra round-trip.
        self.sketch = sketch
        self.shadow = shadow
        # the single-used-slot fallback slice zeroes the pack-order slot
        # indices (rows then index row 0 of the slice); this remembers
        # the real slot so NaN attribution survives that path
        self.slot_override: Optional[int] = None

    def _materialize(self):
        """Worker-thread materialization of every device output riding
        this flush — one executor hop for scores + sketch + shadow."""
        return (
            np.asarray(self.scores),
            None if self.sketch is None else np.asarray(self.sketch),
            None if self.shadow is None else np.asarray(self.shadow),
        )

    def landed(self) -> bool:
        """Probably-complete signal used to PRIORITIZE heads: a finished
        executor materialization, or (for jax arrays) ``is_ready`` —
        which only proves the device COMPUTE finished, not that the
        async host copy crossed the link. Honest overlap accounting is
        therefore measured at materialize time (see ``_resolve_flush``),
        never inferred from this."""
        if self.poisoned:
            return True  # nothing to wait for — resolvable immediately
        if self.host_future is not None:
            return self.host_future.done()
        try:
            return bool(self.scores.is_ready())
        except Exception:  # noqa: BLE001 - non-jax doubles: never "landed"
            return False

    def ensure_host_future(self, loop, pool):
        """Lazily start (and cache) an executor materialization — used
        when the reaper must wait on several families' heads at once.
        Resolves to the (scores, sketch, shadow) host triple."""
        if self.host_future is None:
            self.host_future = loop.run_in_executor(
                pool, self._materialize
            )
        return self.host_future


class _ReapQueue(list):
    """Per-family FIFO of in-flight flush completions. Depth is bounded
    by the ``max_inflight`` semaphore (acquired before rows are popped
    from lanes) and observable via the ``tpu_inference_deliver_inflight``
    gauge + ``tpu_inference.deliver_backpressure`` counter
    (tools/check_queues.py registry). FIFO per family is what gives
    per-tenant in-order delivery: a tenant lives in exactly one family,
    and the reaper never resolves past an unfinished head."""

    __slots__ = ()

    def popleft(self) -> _PendingFlush:
        return self.pop(0)


class TpuInferenceEngine(TenantEngine):
    """Per-tenant engine: placement on the mesh + stream registry."""

    def __init__(self, config: TenantEngineConfig, service: "TpuInferenceService") -> None:
        super().__init__("tpu-inference", config)
        self.service = service
        self.placement = None
        self.streams: Optional[StreamRegistry] = None

    async def on_start(self) -> None:
        svc = self.service
        self.placement = svc.router.place(self.tenant, family=self.config.model)
        scorer = svc.scorer_for_family(self.config.model, self.config)
        self.streams = StreamRegistry(
            svc.mm.n_data_shards, scorer.max_streams // svc.mm.n_data_shards
        )
        svc.bus.subscribe(svc.bus.naming.inbound_events(self.tenant), svc.group)
        # fair-queue registration: this tenant's intake is rationed by
        # its OverloadPolicy weight from the first poll
        svc.fair.configure(self.tenant, self.config.overload.weight)
        params = None
        if svc.checkpoints is not None:
            # resume this tenant's trained weights (possibly onto a
            # DIFFERENT slot/shard than before — mesh re-placement)
            params = await asyncio.get_running_loop().run_in_executor(
                None, svc.checkpoints.load_params,
                self.tenant, self.config.model,
            )
        scorer.activate(
            svc.router.global_slot(self.placement), params=params,
            trainable=self.config.training.enabled,
            lr=self.config.training.lr,
        )
        # score-health registration: bind this tenant to its stacked slot
        # so the resolve path can attribute device sketches, and start a
        # FRESH drift baseline — an engine (re)start activates params
        # explicitly, so the reference must re-learn the current model's
        # output distribution (docs/OBSERVABILITY.md "re-baseline")
        svc.scorehealth.register(
            self.tenant, self.config.model,
            svc.router.global_slot(self.placement),
            getattr(scorer, "sketch_edges", []),
            variant={
                "fused": bool(getattr(scorer, "fused", False)),
                "k_steps": int(getattr(scorer, "k_steps", 1)),
                "param_dtype": getattr(scorer, "param_dtype", "f32"),
                "wire_dtype": getattr(scorer, "wire_dtype", "f32"),
            },
        )
        svc.scorehealth.rebaseline(self.tenant)
        # a tenant lifecycle event is the unpark signal for its family —
        # and clears the family breaker's failure history with it
        svc._parked.discard(self.config.model)
        svc._failover_rounds.pop(self.config.model, None)
        breaker = svc.breakers.get(self.config.model)
        if breaker is not None:
            breaker.reset()

    async def on_stop(self) -> None:
        svc = self.service
        if self.placement is not None:
            slot = svc.router.global_slot(self.placement)
            scorer = svc.scorers.get(self.config.model)
            if scorer is not None and svc.checkpoints is not None:
                # save this tenant's (possibly trained) weights BEFORE the
                # slot wipe below destroys them. Materialize to numpy ON
                # THIS (loop) thread: the reset_slot below DONATES the
                # stacked params buffer, and a worker-thread zero-copy view
                # into it would be a use-after-free (see host_copy_params)
                from sitewhere_tpu.runtime.checkpoint import host_copy_params

                params = host_copy_params(scorer.slot_params(slot))
                await asyncio.get_running_loop().run_in_executor(
                    None, svc.checkpoints.save_params,
                    self.tenant, self.config.model, params,
                )
            if scorer is not None:
                # full wipe: a recycled slot must not leak this tenant's
                # window history or params to the next occupant
                scorer.reset_slot(slot)
            # drain pending lanes keyed by the freed slot: the bus cursor
            # already advanced past these rows, so dropping them would lose
            # them from the store on every tenant restart — resolve them
            # unscored (NaN) instead
            lanes = svc._lanes.get(self.config.model)
            if lanes is not None:
                drained = svc.metrics.counter("tpu_inference.drained_on_stop")
                for key in [k for k in lanes if k[0] == slot]:
                    lane = lanes.pop(key)
                    n = lane.count
                    if n:
                        _ids, _vals, seqs, rows = lane.pop(n)
                        await svc._resolve_rows(
                            seqs, rows, None, publish_nowait=True,
                            family=self.config.model,
                        )
                        drained.inc(n)
            svc.router.remove(self.tenant)
            self.placement = None
        svc.fair.remove(self.tenant)
        svc.scorehealth.remove(self.tenant)
        svc._gates.pop(self.tenant, None)


class TpuInferenceService(MultitenantService):
    """Hosts the scorers + the scoring loop across all tenant engines."""

    def __init__(
        self,
        bus: EventBus,
        mm: Optional[MeshManager] = None,
        metrics: Optional[MetricsRegistry] = None,
        slots_per_shard: int = 8,
        poll_batch: int = 64,
        max_inflight: int = 8,
        checkpoints=None,
        tracer=None,
        overload=None,
        fair_quantum: int = 4096,
        staging_slots: int = 2,
        flightrec=None,
        scorehealth=None,
    ) -> None:
        super().__init__("tpu-inference", bus, self._make_engine)
        self.mm = mm or MeshManager()
        self.metrics = metrics or MetricsRegistry()
        self.checkpoints = checkpoints  # CheckpointManager | None
        # overload control: per-tenant deficit-round-robin intake (bus →
        # lanes is the shared chokepoint every tenant contends on), a
        # per-tenant deadline gate so expired work never reaches a
        # ShardedScorer flush, and degradation-mode sampling
        self.overload = overload
        from sitewhere_tpu.runtime.overload import DeficitRoundRobin

        self.fair = DeficitRoundRobin(quantum=fair_quantum)
        self._gates: Dict[str, object] = {}
        # tracing + scoring profile hooks: per-tenant inference spans, a
        # compile-count per (family, bucket) shape (the first flush at a
        # shape IS the XLA compile — a mid-traffic recompile is the p99
        # cliff SURVEY §7 warns about), and optional jax.profiler
        # annotations so device time shows up in profile_dir traces
        self.tracer = tracer
        # flight recorder (runtime.flightrec): always-on per-flush
        # blackbox records + dump-on-incident (breaker trip) snapshots;
        # None (direct service construction in tests) = fully guarded out
        self.flightrec = flightrec
        # score-quality health (runtime.scorehealth): per-tenant drift
        # windows fed by the device-side score sketches the reaper
        # materializes, plus shadow-canary divergence — always on (the
        # per-flush host cost is one 64-bin add per touched slot)
        if scorehealth is None:
            from sitewhere_tpu.runtime.scorehealth import ScoreHealth

            scorehealth = ScoreHealth(self.metrics)
        self.scorehealth = scorehealth
        # live device-time/MFU attribution per family (runtime.metrics
        # .MfuAccount; fed by resolved flushes, decayed by refresh_mfu)
        self._mfu: Dict[str, object] = {}
        self._stage_timers: Dict[str, object] = {}
        self._seen_shapes: set = set()
        self._last_flush: Dict[str, dict] = {}
        self.profile_annotations = False
        self.slots_per_shard = slots_per_shard
        self.poll_batch = poll_batch  # bus items (batches) per poll
        self.router = TenantRouter(self.mm.n_tenant_shards, slots_per_shard)
        self.scorers: Dict[str, ShardedScorer] = {}
        # per-family circuit breaker over scorer dispatch+materialization
        # (the first tenant's FaultTolerancePolicy pins it, like wire_dtype)
        self.breakers: Dict[str, CircuitBreaker] = {}
        self._lanes: Dict[str, Dict[Tuple[int, int], _LaneRing]] = {}
        # reusable flush staging: (family, bucket) → [next_idx, sets];
        # ``staging_slots`` sets rotate so flush N+1 packs host buffers
        # while flush N's async h2d copy is still in flight
        self.staging_slots = max(2, int(staging_slots))
        self._staging: Dict[Tuple[str, int], list] = {}
        # per-family last dispatch output — the overlap probe (next
        # flush's staging "overlapped" ⇔ this is still computing). With
        # the device-side gather it holds the GATHERED rows (a few KB),
        # never the score plane, and the reaper drops it when the
        # family's in-flight queue drains so an idle family pins nothing
        self._last_scores: Dict[str, object] = {}
        self._first_pending_ts: Dict[str, float] = {}
        self._loop_super: Optional[SupervisedTask] = None
        # batch registry: seq → [batch, rows_awaiting_scores]
        self._batches: Dict[int, list] = {}
        self._next_seq = 0
        # live-training cadence: per-family {slot: flush-tick} + last losses
        self._train_ticks: Dict[str, Dict[int, int]] = {}
        self.last_train_losses: Dict[str, object] = {}  # device arrays
        # auto-failover: consecutive scorer errors per family; at the
        # threshold every tenant of the family re-places onto a different
        # mesh shard (SURVEY.md §5: "tenant-engine failover to a different
        # mesh shard")
        self.failover_threshold = 3
        self._consec_errors: Dict[str, int] = {}
        # escalation: failover rounds without an intervening healthy
        # delivery; past max_failover_rounds the family PARKS — events
        # flow through unscored (degraded, never lost) until a tenant
        # lifecycle event clears it
        self.max_failover_rounds = 3
        self._failover_rounds: Dict[str, int] = {}
        self._parked: set = set()
        self._inflight = asyncio.Semaphore(max_inflight)
        self.max_inflight = max_inflight
        self._deliver_pool = None  # created on start, shut down on stop
        # result path: per-family FIFOs of in-flight flush completions,
        # drained by the reaper task as d2h transfers land (out of order
        # across families, in order per tenant)
        self._reap: Dict[str, _ReapQueue] = {}
        self._reap_event = asyncio.Event()
        self._reaper_super: Optional[SupervisedTask] = None
        # per-family resolve task in flight (≤ 1 per family keeps the
        # per-tenant FIFO; separate tasks keep one family's backpressured
        # publish from head-of-line blocking every other family's landed
        # transfers behind the single reaper coroutine)
        self._resolving: Dict[str, asyncio.Task] = {}
        # teardown grace for in-flight transfers before they force-resolve
        # unscored (a dead device must not hang the stop cascade)
        self.deliver_drain_timeout_s = 10.0

    @property
    def group(self) -> str:
        return "tpu-inference"

    def _make_engine(self, cfg: TenantEngineConfig) -> TpuInferenceEngine:
        return TpuInferenceEngine(cfg, self)

    def scorer_for_family(self, family: str, cfg: TenantEngineConfig) -> ShardedScorer:
        scorer = self.scorers.get(family)
        if scorer is not None and scorer.wire_dtype != cfg.wire_dtype:
            # the wire dtype is a property of the FAMILY stack (first
            # tenant wins); a later tenant asking for a different wire
            # would silently score at the stack's precision — surface it
            self._record_error(
                "wire-dtype",
                ValueError(
                    f"tenant '{cfg.tenant}' asked wire_dtype="
                    f"'{cfg.wire_dtype}' but family '{family}' runs "
                    f"'{scorer.wire_dtype}' (first tenant pinned it)"
                ),
            )
            self.metrics.counter("tpu_inference.wire_dtype_conflicts").inc()
        from sitewhere_tpu.models.common import clamp_fuse_k

        # compare CLAMPED asks (fuse_k saturates at window-1): two
        # tenants whose requests compile to the identical kernel must
        # not be reported as a conflict
        _w = getattr(scorer, "window", cfg.microbatch.window) or 1
        if scorer is not None and (
            clamp_fuse_k(getattr(scorer, "fuse_k", 1), _w)
            != clamp_fuse_k(getattr(cfg, "fuse_k", 1), _w)
            or getattr(scorer, "requested_param_dtype", "f32")
            != getattr(cfg, "param_dtype", "f32")
        ):
            # like wire_dtype, the fused-kernel knobs are a property of
            # the FAMILY stack (one compiled step per family) — a later
            # tenant asking for different ones would silently score at
            # the stack's settings, so surface it
            self._record_error(
                "fused-knobs",
                ValueError(
                    f"tenant '{cfg.tenant}' asked fuse_k="
                    f"{getattr(cfg, 'fuse_k', 1)}/param_dtype="
                    f"'{getattr(cfg, 'param_dtype', 'f32')}' but family "
                    f"'{family}' runs fuse_k={getattr(scorer, 'fuse_k', 1)}"
                    f"/param_dtype="
                    f"'{getattr(scorer, 'requested_param_dtype', 'f32')}' "
                    f"(first tenant pinned them)"
                ),
            )
            self.metrics.counter("tpu_inference.fused_knob_conflicts").inc()
        if scorer is None:
            spec = get_model(family)
            mcfg = make_config(family, {
                **cfg.model_config, "window": cfg.microbatch.window,
            })
            scorer = ShardedScorer(
                self.mm,
                spec,
                mcfg,
                slots_per_shard=self.slots_per_shard,
                max_streams=cfg.max_streams,
                window=cfg.microbatch.window,
                wire_dtype=cfg.wire_dtype,
                fuse_k=getattr(cfg, "fuse_k", 1),
                param_dtype=getattr(cfg, "param_dtype", "f32"),
            )
            # shadow-canary fraction: family-pinned like the fused knobs
            # (first tenant wins; one shadow step per family stack)
            scorer.canary_frac = float(getattr(cfg, "canary_frac", 0.0) or 0.0)
            self.scorers[family] = scorer
            self._lanes[family] = {}
            # the failover→park escalation is the scorer's first-line
            # healing; by default the breaker must not open mid-escalation
            # and starve it of failure outcomes (parked families stop
            # flushing), so its verdict window is floored at the park
            # budget. Chaos/testing configs set breaker_defer_to_failover
            # False to let the breaker act first.
            from dataclasses import replace as _replace

            ft = cfg.fault_tolerance
            park_budget = (
                self.failover_threshold * (self.max_failover_rounds + 1) + 1
            )
            if (
                ft.breaker_defer_to_failover
                and ft.breaker_min_samples < park_budget
            ):
                ft = _replace(ft, breaker_min_samples=park_budget)
            self.breakers[family] = CircuitBreaker(
                f"tpu_inference.{family}",
                policy=ft,
                metrics=self.metrics,
            )
        return scorer

    # -- lifecycle -------------------------------------------------------
    async def on_start(self) -> None:
        await super().on_start()
        # dedicated materialization pool: the default loop executor may have
        # fewer workers than max_inflight, which would serialize the very
        # device→host transfers the semaphore is meant to pipeline
        from concurrent.futures import ThreadPoolExecutor

        self._deliver_pool = ThreadPoolExecutor(
            max_workers=self.max_inflight, thread_name_prefix="tpu-deliver"
        )
        # SUPERVISED scoring loop: a persistent loop error restarts it
        # with backoff instead of silently killing all scoring (the k8s
        # liveness-probe-restart analog, in-process)
        self._loop_super = SupervisedTask(
            "tpu-inference-loop", self._scoring_loop, max_restarts=5
        )
        await self._loop_super.initialize()
        await self._loop_super.start()
        # the completion reaper: resolves in-flight flushes as their d2h
        # transfers land; supervised so a resolve fault can't silently
        # end score delivery (pending queues survive a restart)
        self._reaper_super = SupervisedTask(
            "tpu-inference-reaper", self._reap_loop, max_restarts=5
        )
        await self._reaper_super.initialize()
        await self._reaper_super.start()

    async def on_stop(self) -> None:
        if getattr(self, "_loop_super", None) is not None:
            await self._loop_super.terminate()
            self._loop_super = None
        # let in-flight transfers land and resolve through the reaper
        # (they hold rows already popped from lanes — dropping them would
        # lose events); only give up if the device never answers
        deadline = time.monotonic() + self.deliver_drain_timeout_s
        while any(self._reap.values()) and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        if self._reaper_super is not None:
            await self._reaper_super.terminate()
            self._reaper_super = None
        # cancel per-family resolves still blocked (e.g. a publish against
        # a stopped consumer): their CancelledError path resolves the
        # popped rows unscored via publish_nowait before re-raising
        for task in list(self._resolving.values()):
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._resolving.clear()
        # force-resolve anything still stuck, unscored (zero loss even
        # when a transfer never completes)
        for q in self._reap.values():
            while q:
                pf = q.popleft()
                _s, _c, seqs, rows = pf.taken
                await self._resolve_rows(
                    seqs, rows, None, publish_nowait=True, family=pf.family
                )
                self._inflight.release()
        self._deliver_gauge()
        # final sweep: rows can land in lanes AFTER their engine's own
        # stop-drain (the scoring loop keeps consuming during the stop
        # cascade) — resolve them unscored so no consumed event is lost
        for fam, lanes in self._lanes.items():
            for key in list(lanes):
                lane = lanes.pop(key)
                if lane.count:
                    _i, _v, seqs, rows = lane.pop(lane.count)
                    await self._resolve_rows(
                        seqs, rows, None, publish_nowait=True, family=fam
                    )
        self._last_scores.clear()  # drop any pinned device score memory
        if self.mm.n_devices > 1:
            # cardinality guard (the drop_labeled pattern): a stopped
            # service's device-labeled children must not be exported
            # forever — device labels track the LIVE mesh
            for lbl in self.mm.device_labels():
                self.metrics.drop_labeled(device=lbl)
        if self._deliver_pool is not None:
            self._deliver_pool.shutdown(wait=False)
            self._deliver_pool = None

    # -- ingestion → lanes (columnar) ------------------------------------
    async def _enqueue_batch(
        self,
        engine: TpuInferenceEngine,
        batch: MeasurementBatch,
        sample_rate: float = 1.0,
    ) -> None:
        """Route a MeasurementBatch's rows into scoring lanes. Rows that
        can't get a stream slot resolve immediately as unscored.
        ``sample_rate < 1`` is the ``sample_inference`` degradation mode:
        only a strided sample of rows is scored, the rest resolve
        unscored right away (they still persist — degraded, never lost)
        so the TPU budget shrinks without breaking accounting."""
        family = engine.config.model
        lanes = self._lanes[family]
        slot = self.router.global_slot(engine.placement)
        n = batch.n
        if batch.scores is None:
            batch.scores = np.full((n,), np.nan, np.float32)
        seq = self._next_seq
        self._next_seq += 1
        entry = [batch, n]
        self._batches[seq] = entry
        batch.mark("inference_enqueue")  # inference span start / lane wait

        # per-row (dshard, local_id): one registry lookup per UNIQUE
        # (device, name) series, scattered back via inverse indices — no
        # event objects, no awaits, no per-row Python
        dshards, locals_ = engine.streams.lookup_or_assign_bulk(batch)
        skipped = int((dshards == -1).sum())
        if skipped:
            self.metrics.counter("tpu_inference.skipped_capacity").inc(skipped)
            entry[1] -= skipped
        if sample_rate < 1.0:
            step = max(1, int(round(1.0 / max(sample_rate, 1e-3))))
            sampled_out = np.ones((n,), bool)
            sampled_out[::step] = False
            sampled_out &= dshards != -1  # don't double-count skipped rows
            k = int(sampled_out.sum())
            if k:
                dshards = np.where(sampled_out, -1, dshards)
                entry[1] -= k
                self.metrics.counter("tpu_inference.sampled_out").inc(k)
        if entry[1] <= 0:
            # nothing left awaiting scores (all rows skipped, or an empty
            # batch) — publish now or the registry entry leaks forever
            await self._publish_batch(seq)
            return
        for d in range(self.mm.n_data_shards):
            sel = np.nonzero(dshards == d)[0]
            if sel.size == 0:
                continue
            lane = lanes.get((slot, d))
            if lane is None:
                # sized to the lane watermark (2× max_batch split across
                # data shards) so steady state never reallocates
                lane = lanes[(slot, d)] = _LaneRing(
                    max(
                        4096,
                        2 * engine.config.microbatch.max_batch
                        // max(1, self.mm.n_data_shards),
                    )
                )
            # sel doubles as the row indices inside the batch; seq
            # broadcasts — rows land in the ring right here, at enqueue
            lane.push(locals_[sel], batch.values[sel], seq, sel)
        if family not in self._first_pending_ts:
            self._first_pending_ts[family] = time.monotonic()

    # -- score write-back -------------------------------------------------
    async def _resolve_rows(
        self,
        seqs: np.ndarray,
        rows: np.ndarray,
        scores: Optional[np.ndarray],
        publish_nowait: bool = False,
        family: str = "",
    ) -> int:
        """Columnar score write-back: scatter ``scores`` (or NaN for an
        unscored resolution) into their batches' score columns one
        contiguous run at a time, then publish every batch that became
        complete — in seq (= enqueue) order, so a tenant's batches leave
        in order even when a flush carried several. Returns the number
        of batches published.

        Rows arrive grouped: lanes pop FIFO and flushes pack lanes in
        sorted order, so equal-seq runs are contiguous and their row
        indices ascend — a dense run is a pure slice assignment, a
        sampled/split one a single vectorized scatter. Run count is
        O(lanes × batches per flush), tiny next to row count; no
        per-row Python, no list accumulators (tools/check_hotpath.py
        keeps it that way)."""
        n = len(seqs)
        if n == 0:
            return 0
        if scores is None and family:
            # the poisoned/parked/drain deliveries used to publish NaN
            # rows with NO counter — an operator watching scored_total
            # could not tell a degraded family from a healthy one
            self.metrics.counter(
                "tpu_scores_unscored_total", family=family
            ).inc(n)
        cuts = np.flatnonzero(seqs[1:] != seqs[:-1]) + 1
        done = np.empty((len(cuts) + 1,), np.int64)
        k = 0
        a = 0
        for b in (*cuts.tolist(), n):
            s = int(seqs[a])
            entry = self._batches.get(s)
            if entry is not None:
                dst = entry[0].scores
                run = rows[a:b]
                # dense ⇔ consecutive ascending rows (one lane's FIFO pop
                # — the common case); a run spanning several lanes or a
                # sampled batch falls back to one vectorized scatter
                dense = b - a == 1 or bool((np.diff(run) == 1).all())
                if scores is None:
                    if dense:
                        dst[int(run[0]) : int(run[-1]) + 1] = np.nan
                    else:
                        dst[run] = np.nan
                elif dense:
                    dst[int(run[0]) : int(run[-1]) + 1] = scores[a:b]
                else:
                    dst[run] = scores[a:b]
                if scores is None:
                    # per-tenant delivery-quality accounting (one call
                    # per run, never per row — runtime.scorehealth)
                    self.scorehealth.note_unscored(entry[0].tenant, b - a)
                entry[1] -= b - a
                if entry[1] <= 0:
                    done[k] = s
                    k += 1
            a = b
        if k:
            # publish in ascending seq order (scatter above was
            # await-free, so no batch state moved under us)
            done[:k].sort()
            seq_list = done[:k].tolist()
            for i, s in enumerate(seq_list):
                try:
                    await self._publish_batch(int(s), nowait=publish_nowait)
                except BaseException:
                    # cancelled (teardown) or a publish fault mid-loop:
                    # the remaining completed batches are already out of
                    # the registry's reach of any later resolve — flush
                    # them nowait or they strand in _batches and their
                    # events are lost
                    for s2 in seq_list[i + 1:]:
                        await self._publish_batch(int(s2), nowait=True)
                    raise
        return k

    def _gate(self, tenant: str):
        """Per-tenant inference deadline gate (lazy): expired batches
        route to the expired topic BEFORE any lane/flush work — this is
        the 'no expired event reaches a ShardedScorer flush' guarantee."""
        g = self._gates.get(tenant)
        if g is None:
            from sitewhere_tpu.runtime.overload import DeadlineGate

            g = self._gates[tenant] = DeadlineGate(
                self.bus, tenant, "inference", self.metrics,
                tracer=self.tracer, controller=self.overload,
            )
        return g

    def _stage_timer(self, tenant: str):
        t = self._stage_timers.get(tenant)
        if t is None:
            from sitewhere_tpu.runtime.tracing import StageTimer

            t = self._stage_timers[tenant] = StageTimer(
                self.tracer, self.metrics, tenant, "inference"
            )
        return t

    async def _publish_batch(self, seq: int, nowait: bool = False) -> None:
        batch, _ = self._batches.pop(seq)
        # inference span: start = lane enqueue, queue wait = bus time since
        # the inbound stage published; annotations carry the family's last
        # flush profile (dispatch time, whether it compiled a new shape)
        t_now = time.time() * 1000.0
        enq = batch.trace.get("inference_enqueue", t_now)
        prev = max(
            (v for k, v in batch.trace.items() if k != "inference_enqueue"),
            default=enq,
        )
        engine = self.engines.get(batch.tenant)
        family = engine.config.model if engine is not None else ""
        self._stage_timer(batch.tenant).observe(
            batch, enq, t_now, n_events=batch.n,
            queue_wait_ms=max(0.0, enq - prev),
            **self._last_flush.get(family, {}),
        )
        batch.mark("scored")
        topic = self.bus.naming.scored_events(batch.tenant)
        if nowait:
            # teardown path: the consumer may already be stopped; an
            # awaitable publish against a full topic would never unblock
            self.bus.publish_nowait(topic, batch)
        else:
            # normal path: preserve backpressure toward persistence — a
            # lagging store slows scoring instead of silently evicting
            # whole batches past retention. The batch is already out of
            # the registry, so a transient publish fault must be retried
            # here (nowait fallback) or the whole batch would vanish.
            try:
                await publish_at_least_once(
                    self.bus, topic, batch, metrics=self.metrics
                )
            except asyncio.CancelledError:
                raise  # publish_at_least_once already appended nowait
            except Exception:
                # non-transient fault: same registry-reach argument —
                # append nowait before surfacing, or the batch is lost
                self.bus.publish_nowait(topic, batch)
                raise
        # latency accounting: sample rows (full per-row recording would be
        # a Python loop over 10^5 rows/s). Replayed history carries its
        # ORIGINAL received_ts — hours-old samples would flood the live
        # p99/SLO series for the whole replay, so only live traffic
        # records latency (replay progress has its own metric family).
        if "replay" not in batch.trace:
            lat = self.metrics.histogram("tpu_inference.latency", unit="s")
            now = time.time() * 1000.0
            rts = batch.received_ts[:: max(1, batch.n // 16)]
            lat.record_many(((now - rts) / 1000.0).tolist())
        self.metrics.counter("tpu_inference.scored_total").inc(batch.n)
        self.metrics.meter("tpu_inference.scored").mark(batch.n)

    # -- flush -----------------------------------------------------------
    def _pick_bucket(self, need: int, buckets: Tuple[int, ...], max_batch: int) -> int:
        for b in buckets:
            if need <= b:
                return min(b, max_batch)
        return max_batch

    def _staging_set(self, family: str, scorer, b_lane: int) -> _StagingSet:
        """Next rotating staging set for (family, bucket) — created once,
        reused for the lifetime of the shape."""
        key = (family, b_lane)
        rot = self._staging.get(key)
        if rot is None:
            rot = self._staging[key] = [
                0, [_StagingSet(scorer, b_lane) for _ in range(self.staging_slots)],
            ]
        idx, sets = rot
        rot[0] = (idx + 1) % len(sets)
        st = sets[idx]
        st.ensure_reusable(self.metrics)
        return st

    async def _flush_family(self, engine_cfgs: Dict[int, TenantEngineConfig], family: str) -> int:
        """Pack one family's lane rings into a reusable staging set,
        stage the buffers to device (async h2d — overlaps any in-flight
        flush's dispatch), dispatch the jit step, and hand score
        materialization to a pipelined delivery task."""
        scorer = self.scorers[family]
        lanes = self._lanes[family]
        if family in self._parked:
            # degraded mode: resolve pending rows unscored so events keep
            # flowing to persistence/rules while the scorer is parked
            drained = 0
            for key in list(lanes):
                lane = lanes.pop(key)
                if lane.count:
                    _i, _v, seqs, rows = lane.pop(lane.count)
                    await self._resolve_rows(seqs, rows, None, family=family)
                    drained += len(seqs)
            self._first_pending_ts.pop(family, None)
            return drained
        if not any(l.count for l in lanes.values()):
            self._first_pending_ts.pop(family, None)
            return 0
        breaker = self.breakers.get(family)
        if breaker is not None and not breaker.allow():
            # breaker OPEN: stop hammering the scorer — resolve pending
            # rows unscored (degraded, never lost) until the half-open
            # schedule lets a trial flush probe recovery. Trial failures
            # keep feeding the failover→park escalation below.
            drained = 0
            for key in list(lanes):
                lane = lanes.pop(key)
                if lane.count:
                    _i, _v, seqs, rows = lane.pop(lane.count)
                    await self._resolve_rows(seqs, rows, None, family=family)
                    drained += len(seqs)
            self._first_pending_ts.pop(family, None)
            self.metrics.counter("tpu_inference.breaker_short_circuits").inc()
            return drained
        any_cfg = next(iter(engine_cfgs.values()))
        mb = any_cfg.microbatch
        # acquire the in-flight slot BEFORE popping rows off the lanes:
        # a cancellation while waiting here must not strand popped rows
        # (everything from the pop to the reap enqueue below is
        # await-free).
        t_acq = time.perf_counter()
        if self._inflight.locked():
            # all completion slots busy: the flush backpressures here,
            # where depth is the deliver_inflight gauge (check_queues)
            self.metrics.counter("tpu_inference.deliver_backpressure").inc()
        await self._inflight.acquire()
        self.metrics.histogram("tpu_inference.acquire_wait", unit="s").record(
            time.perf_counter() - t_acq
        )
        # pick the bucket AFTER the (possibly long) acquire wait: rows that
        # accumulated while every slot was busy should ride out in ONE
        # bigger flush, not drain at the stale pre-wait size
        pending_max = max((l.count for l in lanes.values()), default=0)
        b_lane = self._pick_bucket(pending_max, tuple(mb.buckets), mb.max_batch)
        # wire-thin stacked batch: compact id/value dtypes + one count per
        # (slot, data-shard) lane instead of a bool mask — rows fill each
        # lane from the front, so validity is derivable on device (see
        # ShardedScorer.step_counts; h2d bytes are a first-class budget).
        # Assembly is slice copies lane-ring → REUSABLE staging buffers:
        # no fresh flush arrays, no list accumulators, no np.asarray over
        # Python lists (tools/check_hotpath.py enforces this stays true).
        t_asm = time.perf_counter()
        st = self._staging_set(family, scorer, b_lane)
        ids, vals, counts = st.ids, st.vals, st.counts
        counts[:] = 0
        take_total = 0
        for lane in lanes.values():
            take_total += min(lane.count, b_lane)
        slots_cat = np.empty((take_total,), np.int32)
        cols_cat = np.empty((take_total,), np.int32)
        seqs_cat = np.empty((take_total,), np.int64)
        rows_cat = np.empty((take_total,), np.int32)
        moved = 0
        used_slots: set = set()
        # SORTED lane order: the device-side gather compacts valid rows
        # in (slot, data-shard, lane-position) order, so the host-side
        # seqs/rows bookkeeping must pack in exactly that order for
        # gathered[:moved] to line up with seqs_cat/rows_cat
        for (slot, dshard), lane in sorted(lanes.items()):
            k = min(lane.count, b_lane)
            if k == 0:
                continue
            base = dshard * b_lane
            lane.pop_into(k, ids[slot], vals[slot], base, seqs_cat, rows_cat, moved)
            slots_cat[moved : moved + k] = slot
            cols_cat[moved : moved + k] = st.arange[base : base + k]
            counts[slot, dshard] = k
            used_slots.add(slot)
            moved += k
        depth_left = 0
        for lane in lanes.values():
            depth_left += lane.count
        self.metrics.gauge("tpu_inference_lane_rows", family=family).set(
            depth_left
        )
        if depth_left:
            self._first_pending_ts[family] = time.monotonic()
        else:
            self._first_pending_ts.pop(family, None)
        if moved == 0:
            self._inflight.release()
            if breaker is not None:
                breaker.release_trial()  # allowed, but no call was made
            return 0
        assembly_s = time.perf_counter() - t_asm
        self.metrics.histogram("tpu_inference.flush_assembly", unit="s").record(
            assembly_s
        )

        taken = (slots_cat, cols_cat, seqs_cat, rows_cat)
        shape_key = (family, b_lane)
        compiling = shape_key not in self._seen_shapes
        h2d_stage_s: Optional[float] = None  # for the fault record when
        dispatch_s: Optional[float] = None   # the try below dies early
        rec: Optional[dict] = None           # blackbox record, once made
        try:
            # h2d prefetch: issue the ASYNC device copy before dispatch.
            # "Overlapped" is measured honestly: the previous flush's
            # dispatch output is not yet ready ⇔ this staging copy rides
            # under genuinely in-flight device compute (a pending deliver
            # task alone could just be awaiting its publish).
            prev_scores = self._last_scores.get(family)
            try:
                overlapped = (
                    prev_scores is not None and not prev_scores.is_ready()
                )
            except Exception:  # noqa: BLE001 - monkeypatched scorers
                overlapped = bool(any(self._reap.values()))
            t_stage = time.perf_counter()
            stage = getattr(scorer, "stage_inputs", None)
            if stage is not None:
                staged = stage(ids, vals, counts)
                st.staged = staged
            else:  # monkeypatched/minimal scorers (tests)
                staged = (ids, vals, counts)
            h2d_stage_s = time.perf_counter() - t_stage
            self.metrics.histogram("tpu_inference.h2d_stage", unit="s").record(
                h2d_stage_s
            )
            self.metrics.counter("tpu_inference.h2d_staged").inc()
            if overlapped:
                self.metrics.counter("tpu_inference.h2d_overlapped").inc()
            try:
                self.metrics.counter("tpu_inference.staged_bytes").inc(
                    scorer.stage_nbytes(staged)
                )
            except Exception:  # noqa: BLE001 - observability only
                pass
            # shadow-scoring canary: when armed (non-f32/K>1 variant or a
            # recent hot-swap, at the family's canary_frac stride), score
            # this flush ALSO through the previous variant — the legacy
            # f32 step. It must dispatch BEFORE the primary step: it
            # reads the window state the primary is about to donate, and
            # same-queue dispatch order guarantees that read. Shadow
            # FLOPs land in tpu_shadow_flops_total — NEVER the MFU
            # account — so tpu_mfu_pct keeps meaning "serving work".
            shadow_dev = None
            take = getattr(scorer, "canary_take", None)
            if take is not None and take():
                try:
                    shadow_plane = scorer.shadow_step_counts(*staged)
                    shadow_dev = scorer.gather_rows(
                        shadow_plane, staged[2], moved
                    )
                    shadow_dev.copy_to_host_async()
                    self.metrics.counter("tpu_inference.canary_flushes").inc()
                    self.metrics.counter(
                        "tpu_shadow_flops_total", family=family
                    ).inc(float(scorer.shadow_flops_per_flush(b_lane)))
                except Exception as exc:  # noqa: BLE001 - the canary is
                    # advisory: it must never take scoring down with it
                    self._record_error("canary", exc)
                    shadow_dev = None
            t_disp = time.perf_counter()
            with _profiler_annotation(self.profile_annotations, family):
                scores_dev = scorer.step_counts(*staged)  # async dispatch
            dispatch_s = time.perf_counter() - t_disp
            self.metrics.histogram("tpu_inference.dispatch", unit="s").record(
                dispatch_s
            )
            disp_labels = {"family": family}
            if self.mm.n_devices > 1:
                # multichip path: stamp the device so ROADMAP item 1's
                # mesh promotion lands with per-device attribution in
                # place. Cardinality is mesh-bounded (device labels come
                # only from live mesh devices) and the service drops its
                # device children on stop (drop_labeled)
                disp_labels["device"] = getattr(
                    scorer, "device_label", "device:?"
                )
            self.metrics.histogram(
                "tpu_inference_dispatch_seconds", **disp_labels
            ).record(dispatch_s)
            if compiling:
                # first flush at this (family, bucket) shape = XLA compile;
                # a counter bump here is how a mid-traffic recompile (new
                # bucket, missed prewarm) becomes attributable instead of
                # an anonymous p99 cliff
                self._seen_shapes.add(shape_key)
                self.metrics.counter("tpu_inference.compiles").inc()
                self.metrics.counter(
                    "tpu_inference_compiles", family=family,
                    bucket=str(b_lane),
                ).inc()
            self._last_flush[family] = {
                "family": family,
                "dispatch_s": round(dispatch_s, 6),
                "compiled": compiling,
                "bucket": b_lane,
            }
            self.metrics.counter("tpu_inference.flushes").inc()
            self.metrics.counter("tpu_inference.flush_rows").inc(moved)
            if self.flightrec is not None:
                # the blackbox record for this flush — completed in place
                # (d2h/resolve/device timings) when the reaper resolves it
                rec = self.flightrec.record(
                    "flush", family,
                    rows=moved, bucket=b_lane,
                    assembly_s=round(assembly_s, 6),
                    h2d_stage_s=round(h2d_stage_s, 6),
                    dispatch_s=round(dispatch_s, 6),
                    h2d_overlapped=bool(overlapped),
                    compiled=compiling,
                    # kernel variant attribution: which fused-step shape
                    # produced this flush's timings (incident snapshots
                    # must name the variant, not just the family)
                    k_steps=getattr(scorer, "k_steps", 1),
                    param_dtype=getattr(scorer, "param_dtype", "f32"),
                    trace_id=self._flush_trace_id(seqs_cat),
                    status="inflight",
                )
            # device-side gather: compact ONLY the flushed rows out of
            # the [T, D*B] score plane before anything crosses d2h —
            # transfer volume becomes rows-proportional (wire dtype),
            # independent of tenant count. Shapes come from the ladder
            # prewarm compiles (ShardedScorer.gather_ladder).
            plane_nbytes = int(getattr(scores_dev, "nbytes", 0))
            # the step's device-side score sketch (i32[T, D, NBINS]) —
            # a few hundred bytes riding the same async readback; its
            # host copy starts here like the scores' below
            sketch_dev = getattr(scorer, "last_sketch", None)
            if sketch_dev is not None:
                try:
                    sketch_dev.copy_to_host_async()
                except Exception:  # noqa: BLE001 - numpy/test doubles
                    pass
            gathered = False
            gather = getattr(scorer, "gather_rows", None)
            if gather is not None and hasattr(scores_dev, "is_ready"):
                try:
                    scores_dev = gather(scores_dev, staged[2], moved)
                    gathered = True
                except Exception as exc:  # noqa: BLE001 - fall back to
                    # the full-plane readback rather than lose the flush
                    self._record_error("gather", exc)
            slot_override = None
            if not gathered and len(used_slots) == 1 and scorer.n_slots > 1:
                # legacy d2h diet for gather-less scorers (monkeypatched
                # doubles): one used slot → slice that row on device
                only = next(iter(used_slots))
                scores_dev = scores_dev[np.full((1,), only, np.int32)]
                slots_cat[:] = 0  # rows now index row 0 of the slice
                slot_override = only  # keep NaN attribution honest
            # overlap probe for the NEXT flush — now holds the gathered
            # rows (a few KB), not a full flush of plane memory; the
            # reaper drops it when the family goes idle
            self._last_scores[family] = scores_dev
            try:
                # start the d2h copy NOW: it rides under the next
                # flush's compute and is (ideally) done by the time the
                # reaper asks — the mirror image of stage_inputs
                scores_dev.copy_to_host_async()
            except Exception:  # noqa: BLE001 - numpy/test doubles
                pass
        except Exception as exc:  # noqa: BLE001 - a failing scorer must
            # not strand popped rows or kill the loop; repeated failures
            # trigger shard failover
            self._record_error("step", exc)
            if breaker is not None:
                breaker.record_failure()
            err_rec = None
            if self.flightrec is not None:
                if rec is not None:
                    # the flush already has an inflight record (the fault
                    # hit AFTER dispatch, e.g. device-side slicing):
                    # complete IT — appending a second record would leave
                    # a phantom stuck forever at status="inflight" in the
                    # ring and in any breaker-trip snapshot
                    rec["status"] = "error"
                    rec["error"] = repr(exc)
                    err_rec = rec
                else:
                    err_rec = self.flightrec.record(
                        "flush", family,
                        rows=moved, bucket=b_lane,
                        assembly_s=round(assembly_s, 6),
                        h2d_stage_s=(
                            round(h2d_stage_s, 6)
                            if h2d_stage_s is not None else None
                        ),
                        dispatch_s=(
                            round(dispatch_s, 6)
                            if dispatch_s is not None else None
                        ),
                        compiled=compiling,
                        k_steps=getattr(scorer, "k_steps", 1),
                        param_dtype=getattr(scorer, "param_dtype", "f32"),
                        trace_id=self._flush_trace_id(seqs_cat),
                        status="error", error=repr(exc),
                    )
            # resolve the rows unscored THROUGH the reap FIFO, not
            # inline: an earlier flush of this family may still be in
            # flight, and publishing these batches first would hand a
            # tenant its later batch before its earlier one. The permit
            # stays held until the reaper resolves the entry.
            self._reap_enqueue(_PendingFlush(
                family, None, taken, moved, False, 0, 0, poisoned=True,
                rec=err_rec,
            ))
            if (
                self.flightrec is not None
                and breaker is not None
                and breaker.state == "open"
            ):
                # breaker TRIP: freeze the blackbox NOW, with the
                # faulting flush's record (timings + trace_id) already
                # in the ring it snapshots
                self.flightrec.snapshot(
                    f"breaker:{family}", family=family,
                    trace_id=err_rec.get("trace_id") if err_rec else None,
                )
            await self._note_scorer_error(family)
            return moved
        try:
            self._train_tick(family, scorer, engine_cfgs)
        except Exception as exc:  # noqa: BLE001 - a training fault must not
            # leak the inflight permit or strand the step's rows (the
            # scoring step itself succeeded; delivery proceeds below)
            self._record_error("train", exc)
        flops_fn = getattr(scorer, "flops_per_flush", None)
        pf = _PendingFlush(
            family, scores_dev, taken, moved, gathered,
            int(getattr(scores_dev, "nbytes", 0)), plane_nbytes,
            flops=float(flops_fn(b_lane)) if flops_fn is not None else 0.0,
            rec=rec, sketch=sketch_dev, shadow=shadow_dev,
        )
        pf.slot_override = slot_override
        if not hasattr(scores_dev, "copy_to_host_async"):
            # no async copy available (test doubles): materialize eagerly
            # on the pool so fallback flushes still overlap each other
            pf.ensure_host_future(
                asyncio.get_running_loop(), self._deliver_pool
            )
        self._reap_enqueue(pf)
        return moved

    def _flush_trace_id(self, seqs_cat: np.ndarray) -> Optional[str]:
        """The first packed batch's trace id — links a flight-recorder
        flush record to its GET /api/traces/{id} trace (one flush packs
        many batches; the head batch anchors the join)."""
        if not len(seqs_cat):
            return None
        entry = self._batches.get(int(seqs_cat[0]))
        if entry is None:
            return None
        ctx = getattr(entry[0], "trace_ctx", None)
        return getattr(ctx, "trace_id", None)

    def _reap_enqueue(self, pf: _PendingFlush) -> None:
        """Queue one pending flush (normal or poisoned) for the reaper:
        the single definition of the enqueue protocol — FIFO append,
        gauge refresh, reaper wake."""
        q = self._reap.get(pf.family)
        if q is None:
            q = self._reap[pf.family] = _ReapQueue()
        q.append(pf)
        self._deliver_gauge()
        self._reap_event.set()

    # -- auto-failover ----------------------------------------------------
    async def _note_scorer_error(self, family: str) -> None:
        """Count consecutive scorer failures for a family; at the
        threshold, rebuild the scorer runtime (a failed dispatch can
        invalidate the donated state buffer) and fail every tenant of the
        family over to a DIFFERENT mesh shard (reference analog: tenant
        engines restarting on another replica after repeated probe
        failures [U]). Repeated rounds without a healthy delivery PARK
        the family: events pass through unscored rather than churning
        failovers forever — degraded, never lost.

        Scope note: within ONE process the scoring step is a single
        shard_map over the whole mesh, so re-placement heals slot-level
        poisoning; an entire dead device additionally needs the runtime
        rebuild below, and if the fault persists the family parks. In a
        multi-host deployment each host runs its own scorer over its mesh
        slice, and re-placement moves tenants off the sick host."""
        n = self._consec_errors.get(family, 0) + 1
        self._consec_errors[family] = n
        if n < self.failover_threshold or family in self._parked:
            return
        self._consec_errors[family] = 0
        rounds = self._failover_rounds.get(family, 0) + 1
        self._failover_rounds[family] = rounds
        if rounds > self.max_failover_rounds:
            self._parked.add(family)
            self._record_error(
                "park", RuntimeError(
                    f"family '{family}' parked after {rounds - 1} failover "
                    f"rounds; events pass through unscored"
                ),
            )
            self.metrics.counter("tpu_inference.parked").inc()
            return
        self._last_scores.pop(family, None)  # may reference dead buffers
        scorer = self.scorers.get(family)
        if scorer is not None:
            try:
                scorer.rebuild_runtime()
                # the rebuilt jit cache recompiles every shape: reset the
                # family's seen-shape set so the compile counter stays true
                self._seen_shapes = {
                    k for k in self._seen_shapes if k[0] != family
                }
            except Exception as exc:  # noqa: BLE001 - device may be gone
                self._record_error("rebuild", exc)
        for tenant, engine in list(self.engines.items()):
            if (
                isinstance(engine, TpuInferenceEngine)
                and engine.placement is not None
                and engine.config.model == family
            ):
                await self._failover_tenant(engine)

    async def _failover_tenant(self, engine: "TpuInferenceEngine") -> bool:
        """Re-place one tenant onto another shard: carry its params (live
        copy if the old shard still answers, else last checkpoint, else
        pristine), wipe + free the old slot, re-key pending lanes. Stream
        → data-shard assignments are placement-independent, so no rows and
        no window routing are lost."""
        from sitewhere_tpu.parallel.tenant_router import PlacementError
        from sitewhere_tpu.runtime.checkpoint import host_copy_params

        tenant = engine.tenant
        family = engine.config.model
        scorer = self.scorers.get(family)
        if scorer is None:
            return False
        old_slot = self.router.global_slot(engine.placement)
        params = None
        try:  # live params may be unreachable on a sick shard
            params = host_copy_params(scorer.slot_params(old_slot))
        except Exception:  # noqa: BLE001
            if self.checkpoints is not None:
                try:
                    params = await asyncio.get_running_loop().run_in_executor(
                        None, self.checkpoints.load_params, tenant, family
                    )
                except Exception as exc:  # noqa: BLE001
                    self._record_error("failover-params", exc)
        try:
            new_p = self.router.failover(tenant)
        except PlacementError as exc:
            self._record_error("failover", exc)
            return False
        try:
            scorer.reset_slot(old_slot)
        except Exception as exc:  # noqa: BLE001 - the old shard may be dead
            self._record_error("failover-reset", exc)
        engine.placement = new_p
        new_slot = self.router.global_slot(new_p)
        scorer.activate(
            new_slot, params=params,
            trainable=engine.config.training.enabled,
            lr=engine.config.training.lr,
        )
        # slot re-map only: the model didn't change, so the drift
        # reference survives the failover (register keeps same-family
        # history — see ScoreHealth.register)
        self.scorehealth.register(
            tenant, family, new_slot,
            getattr(scorer, "sketch_edges", []),
        )
        # pending rows keyed by the old slot ride over to the new one
        lanes = self._lanes.get(family, {})
        for d in range(self.mm.n_data_shards):
            lane = lanes.pop((old_slot, d), None)
            if lane is not None and lane.count:
                dst = lanes.get((new_slot, d))
                if dst is None:
                    lanes[(new_slot, d)] = lane
                else:
                    li, lv, ls, lr = lane.pop(lane.count)
                    dst.push(li, lv, ls, lr)
        self.metrics.counter("tpu_inference.failovers").inc()
        return True

    def _train_tick(
        self, family: str, scorer: ShardedScorer,
        engine_cfgs: Dict[int, TenantEngineConfig],
    ) -> int:
        """Live training cadence: every Nth scoring flush dispatches ONE
        optimizer step for every active slot on its resident window state
        (zero host<->device traffic — see ShardedScorer.train_resident).
        The jit dispatch is async, so the scoring loop never blocks on the
        gradient computation; tenants in the same family stack with
        training disabled are excluded by the scorer's per-slot train
        mask."""
        enabled = {
            slot: c.training
            for slot, c in engine_cfgs.items()
            if c.training.enabled
        }
        if not enabled:
            return 0
        # per-TENANT cadence: each slot matures on its own every_n_flushes
        # (and trains at its own lr — see ShardedScorer.slot_lr)
        ticks = self._train_ticks.setdefault(family, {})
        mature = []
        for slot, tc in enabled.items():
            n = ticks.get(slot, 0) + 1
            if n >= tc.every_n_flushes:
                mature.append(slot)
                ticks[slot] = 0
            else:
                ticks[slot] = n
        if not mature:
            return 0
        if getattr(scorer, "_train", None) is None:
            scorer.init_optimizer()  # scale_by_adam + per-slot lr
        mask = np.zeros((scorer.n_slots,), bool)
        mask[mature] = True
        self.last_train_losses[family] = scorer.train_resident(mask)
        self.metrics.counter("tpu_inference.train_steps").inc()
        return 1

    def _deliver_gauge(self) -> None:
        self.metrics.gauge("tpu_inference_deliver_inflight").set(
            sum(len(q) for q in self._reap.values())
        )
        # labeled variant beside the legacy aggregate: the reap queues
        # are PER-FAMILY, so per-family depth is where a wedged tenant
        # family actually shows (the aggregate hides it). Separate
        # family name — mixing bare and {family} children under one
        # name would double-count sum() aggregations.
        for family, q in self._reap.items():
            self.metrics.gauge(
                "tpu_inference_deliver_inflight_family", family=family
            ).set(len(q))

    # -- device-time / MFU attribution -----------------------------------
    def _mfu_account(self, family: str):
        acc = self._mfu.get(family)
        if acc is None:
            from sitewhere_tpu.runtime.metrics import MfuAccount

            acc = self._mfu[family] = MfuAccount(self.metrics, family)
        return acc

    def refresh_mfu(self) -> None:
        """Decay idle families' ``tpu_mfu_pct`` gauges from the sliding
        window (called by the instance's 1 s history tick and the
        /metrics scrape — a family that stopped flushing must read 0,
        not its last busy value)."""
        for acc in self._mfu.values():
            acc.refresh()
        # same tick drives the score-health time-based window rotation:
        # a slow stream must still rotate its drift windows instead of
        # waiting hours to fill window_rows
        self.scorehealth.refresh()

    async def _reap_loop(self) -> None:
        """The completion reaper: resolve in-flight flushes as their d2h
        transfers land. Heads that look complete (``landed`` — a cheap
        priority signal) dispatch first; when several families are in
        flight and none does, the reaper waits on ALL their heads and
        takes whichever finishes first — out of order across families,
        strictly FIFO within one (a tenant lives in exactly one family,
        so its batches deliver in order). The reaper itself only WAITS —
        each landed head resolves in a per-family task
        (``_spawn_resolve``), so one tenant's backpressured scored-topic
        publish can't head-of-line block other families' landed
        transfers. Overlap accounting happens at materialize time in
        ``_resolve_flush``: only a transfer whose materialization
        returned without measurable wait (and that the reaper never
        raced on) counts as ``d2h_overlapped``."""
        loop = asyncio.get_running_loop()
        while True:
            # a family with a resolve in flight is ineligible: its next
            # head must wait its turn (per-tenant FIFO)
            heads = [
                q[0] for f, q in self._reap.items()
                if q and f not in self._resolving
            ]
            if not heads:
                # clear-then-wait is race-free on the single-threaded
                # loop: any set() that mattered already showed in heads
                self._reap_event.clear()
                await self._reap_event.wait()
                continue
            pf = next((h for h in heads if h.landed()), None)
            if pf is not None:
                self._spawn_resolve(pf)
                continue
            # no head has landed: race every eligible family's head (plus
            # the enqueue/resolve-done event — a NEW family's flush must
            # be able to join the race and win, or one family's slow
            # transfer would head-of-line block every other family)
            self._reap_event.clear()
            waiter = asyncio.ensure_future(self._reap_event.wait())
            now = time.perf_counter()
            futs = []
            for h in heads:
                if h.t_wait is None:
                    h.t_wait = now
                # one future per in-flight FAMILY (a handful), not per row
                futs.append(h.ensure_host_future(loop, self._deliver_pool))  # hotpath: ok
            try:
                await asyncio.wait(
                    [*futs, waiter], return_when=asyncio.FIRST_COMPLETED
                )
            finally:
                waiter.cancel()
            pf = next((h for h, f in zip(heads, futs) if f.done()), None)
            if pf is not None:
                self._spawn_resolve(pf)

    def _spawn_resolve(self, pf: _PendingFlush) -> None:
        """Resolve one landed flush in a per-family task. At most one
        resolve runs per family (the loop skips families in
        ``_resolving``), which preserves per-tenant in-order delivery;
        separate tasks restore the cross-family isolation the old
        per-flush deliver tasks had — a full scored topic only stalls
        its own family, and only until ``max_inflight`` backpressures
        the scoring loop as a whole."""
        task = asyncio.get_running_loop().create_task(
            self._resolve_flush(pf)
        )
        self._resolving[pf.family] = task

        def _done(t: asyncio.Task, family: str = pf.family) -> None:
            if self._resolving.get(family) is t:
                del self._resolving[family]
            if not t.cancelled() and t.exception() is not None:
                # _resolve_flush handles its own failures; anything
                # escaping would otherwise vanish with the task
                self._record_error("deliver", t.exception())
            # wake the reaper: this family's next head is eligible now
            self._reap_event.set()

        task.add_done_callback(_done)

    # the honest boundary for the d2h_overlapped counter, since jax has
    # no "host copy done" probe — shared with the media readback (see
    # runtime/metrics.py for the rationale)
    D2H_OVERLAP_EPS_S = _D2H_OVERLAP_EPS_S

    # top-k size for the canary's rank-agreement verdict: the rows an
    # alerting/thresholding consumer actually acts on are the highest
    # scores, so rank stability there matters more than mean delta
    CANARY_TOPK = 64

    def _canary_compare(
        self, pf: _PendingFlush, picks: np.ndarray, shadow_np: np.ndarray
    ) -> None:
        """Divergence of the serving scores vs the shadow (previous
        variant) scores for one flush — one shared verdict definition
        (``scorehealth.canary_divergence``, also the bench's canary
        columns); results land in ``score_canary_*`` and the flush's
        blackbox record."""
        from sitewhere_tpu.runtime.scorehealth import canary_divergence

        sp = shadow_np[: pf.moved].astype(np.float32, copy=False)
        verdict = canary_divergence(picks, sp, self.CANARY_TOPK)
        if verdict is None:
            return
        mean_abs, agree, n = verdict
        self.scorehealth.canary_note(pf.family, mean_abs, agree, n)
        if pf.rec is not None:
            pf.rec["canary_mean_abs_delta"] = round(mean_abs, 6)
            pf.rec["canary_topk_agreement"] = round(agree, 4)

    async def _resolve_flush(self, pf: _PendingFlush) -> None:
        """Materialize one flush's (gathered) scores and resolve its rows.

        Materialization ALWAYS happens off the loop (executor) unless an
        earlier race already produced the host array — ``is_ready`` only
        proves device compute finished, so an inline ``np.asarray`` here
        could still stall the loop for the copy's remaining link time.
        Worker-thread materialization is safe because ``pf.scores`` is a
        jit output nothing ever donates — unlike param trees, whose
        buffers later loop-thread calls donate (see
        ``checkpoint.host_copy_params`` for the full invariant)."""
        _slots, _cols, seqs, rows = pf.taken
        scattered = False  # did the (possibly unscored) write-back start?
        try:
            if pf.poisoned:
                # the dispatch itself failed (breaker/failover already
                # recorded at the flush site): no transfer to wait for —
                # resolve the rows unscored, but through this FIFO slot
                # so they can't overtake an earlier in-flight flush
                scattered = True
                await self._resolve_rows(seqs, rows, None, family=pf.family)
                return
            t0 = time.perf_counter()
            scores_np, sketch_np, shadow_np = await pf.ensure_host_future(
                asyncio.get_running_loop(), self._deliver_pool
            )
            now = time.perf_counter()
            # cumulative wait: from the FIRST time the reaper waited on
            # this flush (race rounds included), not just the last await
            waited_s = now - pf.t_wait if pf.t_wait is not None else now - t0
            self.metrics.histogram("tpu_inference.d2h_wait", unit="s").record(
                waited_s
            )
            d2h_overlapped = (
                pf.t_wait is None and waited_s < self.D2H_OVERLAP_EPS_S
            )
            if d2h_overlapped:
                # the transfer had fully landed before the reaper asked —
                # it rode under later compute (raced-on heads never count,
                # however fast their future resolved afterwards)
                self.metrics.counter("tpu_inference.d2h_overlapped").inc()
            t1 = time.perf_counter()
            # wire dtype (bf16/f16) widens back to f32 at the batch edge
            if pf.gathered:
                picks = scores_np[: pf.moved].astype(np.float32, copy=False)
            else:
                picks = scores_np[_slots, _cols].astype(np.float32, copy=False)
            # score-quality accounting: per-flush NaN census + the
            # device sketch folded into the tenant drift windows, all
            # vectorized (runtime.scorehealth; nan attribution rides the
            # pack-order slots — one bincount, never a per-row loop)
            nan_mask = np.isnan(picks)
            nan_rows = int(nan_mask.sum())
            if nan_rows:
                self.metrics.counter(
                    "tpu_scores_nan_total", family=pf.family
                ).inc(nan_rows)
            if sketch_np is not None:
                nan_by_slot = None
                if nan_rows:
                    # picks align with the pack-order slots on BOTH the
                    # gathered and full-plane fallback paths; only the
                    # single-slot slice zeroed them (override carries it)
                    if pf.slot_override is not None:
                        nan_by_slot = np.zeros(
                            (sketch_np.shape[0],), np.int64
                        )
                        nan_by_slot[pf.slot_override] = nan_rows
                    else:
                        nan_by_slot = np.bincount(
                            _slots[nan_mask], minlength=sketch_np.shape[0]
                        )
                self.scorehealth.ingest_sketch(
                    pf.family, sketch_np.sum(axis=1), nan_by_slot
                )
            if shadow_np is not None:
                self._canary_compare(pf, picks, shadow_np)
            # cancellation past this point observes only INSIDE
            # _resolve_rows' publish loop (the scatter is await-free), so
            # scores are written and counts decremented exactly once —
            # the cancel path below must not resolve a second time
            scattered = True
            await self._resolve_rows(seqs, rows, picks)
            resolve_s = time.perf_counter() - t1
            self.metrics.histogram("tpu_inference.resolve", unit="s").record(
                resolve_s
            )
            self.metrics.counter("tpu_inference.reaped").inc()
            self.metrics.counter("tpu_inference.d2h_bytes").inc(pf.nbytes)
            # device-time / MFU attribution: the dispatch was outstanding
            # from issue until its transfer landed — that window times
            # this flush's executed FLOPs (padded plane; see
            # ShardedScorer.flops_per_flush)
            device_s = max(0.0, now - pf.t_dispatch)
            if pf.flops:
                self._mfu_account(pf.family).record(pf.flops, device_s)
            d2h_labels = {"family": pf.family}
            if self.mm.n_devices > 1:
                scorer = self.scorers.get(pf.family)
                d2h_labels["device"] = getattr(
                    scorer, "device_label", "device:?"
                )
            self.metrics.counter(
                "tpu_inference_d2h_bytes_total", **d2h_labels
            ).inc(pf.nbytes)
            if pf.rec is not None:
                # complete the blackbox record in place (see flightrec)
                pf.rec["d2h_wait_s"] = round(waited_s, 6)
                pf.rec["d2h_overlapped"] = d2h_overlapped
                pf.rec["resolve_s"] = round(resolve_s, 6)
                pf.rec["device_s"] = round(device_s, 6)
                pf.rec["status"] = "ok"
                # score-quality fields: incident snapshots can now see
                # WHAT the flush scored, not just how long it took
                pf.rec["nan_rows"] = nan_rows
                finite = picks[~nan_mask]
                pf.rec["score_p99"] = (
                    round(float(np.quantile(finite, 0.99)), 6)
                    if finite.size else None
                )
            if pf.plane_nbytes:
                # what the pre-gather path would have moved — the bench's
                # d2h_plane_reduction column is this ratio
                self.metrics.counter("tpu_inference.d2h_plane_bytes").inc(
                    pf.plane_nbytes
                )
            self._consec_errors.pop(pf.family, None)  # healthy again
            self._failover_rounds.pop(pf.family, None)
            breaker = self.breakers.get(pf.family)
            if breaker is not None:
                breaker.record_success()
        except asyncio.CancelledError:
            # cancelled mid-flight (forced teardown): the rows were already
            # popped from lanes, so resolve them unscored or they're lost.
            # But ONLY if the real-score pass never ran — re-resolving
            # after it would decrement batch row counts a second time
            # (premature NaN publishes) and overwrite written scores
            if not scattered:
                await self._resolve_rows(
                    seqs, rows, None, publish_nowait=True, family=pf.family
                )
            raise
        except Exception as exc:  # noqa: BLE001 - a poisoned transfer
            # must not strand the batches: resolve rows unscored — but
            # only if the write-back never ran (same double-decrement
            # hazard as the cancel path above; a fault AFTER it, e.g. a
            # non-transient publish error, already flushed the remaining
            # completed batches inside _resolve_rows)
            self._record_error("deliver", exc)
            if not scattered:
                await self._resolve_rows(seqs, rows, None, family=pf.family)
            if pf.rec is not None and not pf.poisoned:
                pf.rec["status"] = "error"
                pf.rec["error"] = repr(exc)
            if not pf.poisoned:
                # a poisoned flush's dispatch failure was already counted
                # at the flush site — recording it again here would let a
                # downstream bus hiccup double-pace failover/parking
                breaker = self.breakers.get(pf.family)
                if breaker is not None:
                    breaker.record_failure()
                    if (
                        self.flightrec is not None
                        and breaker.state == "open"
                    ):
                        self.flightrec.snapshot(
                            f"breaker:{pf.family}", family=pf.family,
                            trace_id=(
                                pf.rec.get("trace_id") if pf.rec else None
                            ),
                        )
                await self._note_scorer_error(pf.family)
        finally:
            # the head leaves the queue only once its resolution is DONE
            # (either way) — queue length and the deliver_inflight gauge
            # honestly count unfinished flushes, and the teardown drain
            # can't miss a flush the reaper was cancelled inside
            q = self._reap.get(pf.family)
            if q and q[0] is pf:
                q.popleft()
            self._deliver_gauge()
            self._inflight.release()
            if (
                self._last_scores.get(pf.family) is pf.scores
                and not self._reap.get(pf.family)
            ):
                # family idle: the overlap probe must not pin this
                # flush's device scores until the next (maybe never)
                # flush — by now the probe is ready, so dropping it
                # can't change the next overlap verdict
                self._last_scores.pop(pf.family, None)

    # -- legacy object path (low-volume / tests) --------------------------
    async def _enqueue_events(self, engine: TpuInferenceEngine, events: List) -> List:
        """Object events: wrap measurements into a single-row batch each is
        wasteful — instead convert the poll's measurements into one batch."""
        measurements = [e for e in events if isinstance(e, DeviceMeasurement)]
        passthrough = [e for e in events if not isinstance(e, DeviceMeasurement)]
        if measurements:
            batch = MeasurementBatch.from_events(
                measurements, [0] * len(measurements), tenant=engine.tenant
            )
            batch.assignment_tokens = np.asarray(
                [e.assignment_token for e in measurements], object
            )
            batch.area_tokens = np.asarray(
                [e.area_token for e in measurements], object
            )
            await self._enqueue_batch(engine, batch)
        return passthrough

    # -- main loop -------------------------------------------------------
    async def _scoring_loop(self) -> None:
        iters = self.metrics.counter("tpu_inference.loop_iters")
        throttled = self.metrics.counter("tpu_inference.fair_throttled")
        while True:
            iters.inc()
            moved = 0
            fam_cfgs: Dict[str, Dict[int, TenantEngineConfig]] = {}
            # weighted fair queuing: every pass replenishes each tenant's
            # deficit (quantum × weight); a tenant that overdrew sits out
            # until its deficit refills, so sustained intake converges to
            # the weight ratio and a hostile tenant's backlog stays in
            # ITS bus topic (where lag → credit → receiver shed)
            self.fair.replenish()
            for tenant, engine in list(self.engines.items()):
                if engine.state is not LifecycleState.STARTED:
                    continue
                assert isinstance(engine, TpuInferenceEngine)
                if engine.placement is not None:
                    # register for flush even when throttled below: lanes
                    # already holding this tenant's rows must still drain
                    fam_cfgs.setdefault(engine.config.model, {})[
                        self.router.global_slot(engine.placement)
                    ] = engine.config
                budget = self.fair.budget(tenant)
                if budget <= 0:
                    throttled.inc()
                    continue
                # per-tenant lane watermark: a slow/contended scorer must
                # backpressure intake into the BUS (where depth is a
                # gauge, lag drives the credit signal, and retention
                # bounds memory) instead of buffering unboundedly in
                # lanes. 2× max_batch keeps the next flush fed.
                lanes_now = self._lanes.get(engine.config.model, {})
                slot_now = self.router.global_slot(engine.placement)
                pending_rows = sum(
                    l.count for (s, _d), l in lanes_now.items()
                    if s == slot_now
                )
                if pending_rows >= 2 * engine.config.microbatch.max_batch:
                    self.metrics.counter(
                        "tpu_inference.lane_backpressure"
                    ).inc()
                    continue
                # a tenant in deficit debt polls ONE item at a time so
                # the overshoot past its budget is bounded by one batch
                items = await self.bus.consume(
                    self.bus.naming.inbound_events(tenant),
                    self.group,
                    self.poll_batch if budget >= self.fair.quantum else 1,
                    timeout_s=0,
                )
                # the engine can stop DURING the consume await (stop
                # cascade); its cursor already advanced, so resolve the
                # items unscored instead of crashing on a dead placement
                if engine.state is not LifecycleState.STARTED or engine.placement is None:
                    await self._passthrough(
                        self.bus.naming.scored_events(tenant), items
                    )
                    continue
                if not items:
                    continue
                batches = [i for i in items if isinstance(i, MeasurementBatch)]
                objects = [i for i in items if not isinstance(i, MeasurementBatch)]
                self.fair.charge(
                    tenant, sum(b.n for b in batches) + len(objects)
                )
                gate = self._gate(tenant)
                sample_rate = 1.0
                if self.overload is not None and self.overload.degraded(
                    tenant, "sample_inference"
                ):
                    pol = self.overload.policy_for(tenant)
                    sample_rate = pol.inference_sample_rate if pol else 1.0
                for b in batches:
                    if gate.check(b):
                        continue  # expired: never reaches a scorer flush
                    await self._enqueue_batch(engine, b, sample_rate)
                    moved += b.n
                objects = [o for o in objects if not gate.check(o)]
                if objects:
                    passthrough = await self._enqueue_events(engine, objects)
                    topic = self.bus.naming.scored_events(tenant)
                    for ev in passthrough:
                        await publish_at_least_once(
                            self.bus, topic, ev, metrics=self.metrics
                        )
                    moved += len(objects)
            for family, cfgs in fam_cfgs.items():
                if family not in self.scorers:
                    continue
                mb = next(iter(cfgs.values())).microbatch
                lanes = self._lanes[family]
                full = any(l.count >= mb.max_batch for l in lanes.values())
                if full or self._deadline_reached(family, mb.deadline_ms):
                    moved += await self._flush_family(cfgs, family)
            if moved == 0:
                await asyncio.sleep(0.001)

    async def _passthrough(self, topic: str, items: list) -> None:
        """Forward consumed items downstream unscored. While the service is
        up (e.g. a tenant restart mid-flight) this backpressures like the
        normal path — a lagging persistence consumer must slow us down, not
        have retained batches evicted out from under it. The lossy
        ``publish_nowait`` is reserved for service teardown, when the
        consumer may already be gone and an awaitable publish would never
        unblock. The consume cursor has already advanced past these items,
        so even a cancellation mid-publish must still emit them."""
        pending = list(items)
        try:
            while pending:
                item = pending[0]
                if isinstance(item, MeasurementBatch):
                    item.mark("passthrough_stop")
                if self.state is LifecycleState.STARTED:
                    await publish_at_least_once(
                        self.bus, topic, item, metrics=self.metrics
                    )
                else:
                    self.bus.publish_nowait(topic, item)
                pending.pop(0)
        except asyncio.CancelledError:
            for item in pending:
                if isinstance(item, MeasurementBatch):
                    item.mark("passthrough_stop")
                self.bus.publish_nowait(topic, item)
            raise

    def _deadline_reached(self, family: str, deadline_ms: float) -> bool:
        first = self._first_pending_ts.get(family)
        return first is not None and (time.monotonic() - first) * 1000.0 >= deadline_ms

    def prewarm(self) -> None:
        """Compile every active family's bucket shapes (see
        ShardedScorer.prewarm). Call after tenants are added, before
        latency-sensitive traffic."""
        for tenant, engine in self.engines.items():
            assert isinstance(engine, TpuInferenceEngine)
            scorer = self.scorers.get(engine.config.model)
            if scorer is None:
                continue
            mb = engine.config.microbatch
            sizes = [min(b, mb.max_batch) for b in mb.buckets] + [mb.max_batch]
            scorer.prewarm(sizes)

    def params_source(self, tenant: str):
        """A zero-arg callable yielding the tenant's CURRENT slot params
        (live-trained, or checkpoint-restored after a restart) — the
        CEP→TPU bridge binds ModelUdf evaluation to this so rule verdicts
        track the tenant's actual model, never a fresh init. Returns None
        while the tenant has no placement (caller falls back)."""

        def source():
            engine = self.engines.get(tenant)
            if engine is None or engine.placement is None:
                return None
            scorer = self.scorers.get(engine.config.model)
            if scorer is None:
                return None
            return scorer.slot_params(
                self.router.global_slot(engine.placement)
            )

        return source

    def snapshot_params(self) -> Dict[Tuple[str, str], object]:
        """Live param cut for checkpointing: (tenant, family) → param
        pytree for that tenant's slot. The leaves are jax arrays
        (immutable), so the caller can hand them to an executor thread for
        host transfer + serialization without racing ongoing training."""
        out: Dict[Tuple[str, str], object] = {}
        for tenant, engine in self.engines.items():
            assert isinstance(engine, TpuInferenceEngine)
            if engine.placement is None:
                continue
            scorer = self.scorers.get(engine.config.model)
            if scorer is None:
                continue
            slot = self.router.global_slot(engine.placement)
            out[(tenant, engine.config.model)] = scorer.slot_params(slot)
        return out

    # -- introspection ---------------------------------------------------
    def describe(self) -> dict:
        return {
            "mesh": self.mm.describe(),
            "router": self.router.describe(),
            "families": {
                f: {"n_slots": s.n_slots, "max_streams": s.max_streams}
                for f, s in self.scorers.items()
            },
        }
