"""tpu-inference: the rebuild's new pipeline stage (the north star).

"A new tpu-inference tenant-engine microservice sits between
inbound-processing and event-management on the bus, micro-batching
DeviceMeasurement events into JAX/XLA pjit calls on a TPU pod"
(BASELINE.json north_star; no reference counterpart — SURVEY.md §2.3).

Dataflow per scoring cycle:

  inbound-events[tenant_i] ─┐   (async poll, all active tenants)
  inbound-events[tenant_j] ─┼→ lanes[(slot, data_shard)] pending queues
          ...              ─┘        │ flush on deadline_ms OR full bucket
                                     ▼
              stacked arrays i32/f32[T, D·B] (bucketed static shapes)
                                     ▼
              ShardedScorer.step  — ONE jit call scores every tenant
                                     ▼
              scores → events (score field) → tpu-scored-events[tenant]

Latency accounting is first-class (the p99 < 50 ms budget, BASELINE.json:5):
each event carries trace marks; the ``tpu_inference.latency`` histogram
records received→scored wall time.

Tenant start/stop flips the scorer's active mask — no recompile; batch-size
buckets keep XLA at a handful of compiled shapes (SURVEY.md §7 hard parts).
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from sitewhere_tpu.core.events import DeviceMeasurement
from sitewhere_tpu.models import get_model, make_config
from sitewhere_tpu.parallel.mesh import MeshManager
from sitewhere_tpu.parallel.sharded import ShardedScorer
from sitewhere_tpu.parallel.tenant_router import TenantRouter
from sitewhere_tpu.runtime.bus import EventBus
from sitewhere_tpu.runtime.config import TenantEngineConfig
from sitewhere_tpu.runtime.lifecycle import LifecycleState, cancel_and_wait
from sitewhere_tpu.runtime.metrics import MetricsRegistry
from sitewhere_tpu.runtime.tenant import MultitenantService, TenantEngine


class StreamRegistry:
    """Per-tenant map (device_token, name) → (data_shard, local_id).

    Streams are pinned to a data shard at first sight (least-loaded wins),
    so window updates for a stream always land on the same device and the
    scoring step needs no collectives (see ``parallel.sharded``).
    """

    def __init__(self, n_data_shards: int, local_capacity: int) -> None:
        self.n_data_shards = n_data_shards
        self.local_capacity = local_capacity
        self._map: Dict[Tuple[str, str], Tuple[int, int]] = {}
        self._next: List[int] = [0] * n_data_shards

    def lookup_or_assign(
        self, device_token: str, name: str
    ) -> Optional[Tuple[int, int]]:
        key = (device_token, name)
        hit = self._map.get(key)
        if hit is not None:
            return hit
        shard = min(range(self.n_data_shards), key=lambda d: self._next[d])
        if self._next[shard] >= self.local_capacity:
            return None  # capacity exhausted; caller passes event through unscored
        local_id = self._next[shard]
        self._next[shard] += 1
        self._map[key] = (shard, local_id)
        return shard, local_id

    @property
    def n_streams(self) -> int:
        return len(self._map)


class TpuInferenceEngine(TenantEngine):
    """Per-tenant engine: placement on the mesh + stream registry."""

    def __init__(self, config: TenantEngineConfig, service: "TpuInferenceService") -> None:
        super().__init__("tpu-inference", config)
        self.service = service
        self.placement = None
        self.streams: Optional[StreamRegistry] = None

    async def on_start(self) -> None:
        svc = self.service
        self.placement = svc.router.place(self.tenant, family=self.config.model)
        scorer = svc.scorer_for_family(self.config.model, self.config)
        self.streams = StreamRegistry(
            svc.mm.n_data_shards, scorer.max_streams // svc.mm.n_data_shards
        )
        svc.bus.subscribe(svc.bus.naming.inbound_events(self.tenant), svc.group)
        scorer.activate(svc.router.global_slot(self.placement))

    async def on_stop(self) -> None:
        svc = self.service
        if self.placement is not None:
            slot = svc.router.global_slot(self.placement)
            scorer = svc.scorers.get(self.config.model)
            if scorer is not None:
                # full wipe: a recycled slot must not leak this tenant's
                # window history or params to the next occupant
                scorer.reset_slot(slot)
            # drain pending lanes keyed by the freed slot: a later flush
            # must not zero-score stale events into the removed tenant's
            # topic. The bus cursor already advanced past these events, so
            # dropping them would lose them from the store on every tenant
            # restart — publish them unscored (passthrough) instead.
            lanes = svc._lanes.get(self.config.model)
            if lanes is not None:
                drained = svc.metrics.counter("tpu_inference.drained_on_stop")
                topic = svc.bus.naming.scored_events(self.tenant)
                for key in [k for k in lanes if k[0] == slot]:
                    for _local_id, _value, ev in lanes.pop(key):
                        ev.mark("passthrough_stop")
                        # non-blocking: at instance shutdown the scored-topic
                        # consumer is already stopped, so an awaitable publish
                        # against a full topic would never unblock
                        svc.bus.publish_nowait(topic, ev)
                        drained.inc()
            svc.router.remove(self.tenant)
            self.placement = None


class TpuInferenceService(MultitenantService):
    """Hosts the scorers + the scoring loop across all tenant engines."""

    def __init__(
        self,
        bus: EventBus,
        mm: Optional[MeshManager] = None,
        metrics: Optional[MetricsRegistry] = None,
        slots_per_shard: int = 8,
        poll_batch: int = 8192,
    ) -> None:
        super().__init__("tpu-inference", bus, self._make_engine)
        self.mm = mm or MeshManager()
        self.metrics = metrics or MetricsRegistry()
        self.slots_per_shard = slots_per_shard
        self.poll_batch = poll_batch
        self.router = TenantRouter(self.mm.n_tenant_shards, slots_per_shard)
        self.scorers: Dict[str, ShardedScorer] = {}
        # pending measurement lanes: family → (slot, dshard) → deque of
        # (local_id, value, event)
        self._lanes: Dict[str, Dict[Tuple[int, int], Deque]] = {}
        self._first_pending_ts: Dict[str, float] = {}
        self._loop_task: Optional[asyncio.Task] = None

    @property
    def group(self) -> str:
        return "tpu-inference"

    def _make_engine(self, cfg: TenantEngineConfig) -> TpuInferenceEngine:
        return TpuInferenceEngine(cfg, self)

    def scorer_for_family(self, family: str, cfg: TenantEngineConfig) -> ShardedScorer:
        scorer = self.scorers.get(family)
        if scorer is None:
            spec = get_model(family)
            mcfg = make_config(family, {
                **cfg.model_config, "window": cfg.microbatch.window,
            })
            scorer = ShardedScorer(
                self.mm,
                spec,
                mcfg,
                slots_per_shard=self.slots_per_shard,
                max_streams=cfg.max_streams,
                window=cfg.microbatch.window,
            )
            self.scorers[family] = scorer
            self._lanes[family] = {}
        return scorer

    # -- lifecycle -------------------------------------------------------
    async def on_start(self) -> None:
        await super().on_start()
        self._loop_task = asyncio.create_task(
            self._scoring_loop(), name="tpu-inference-loop"
        )

    async def on_stop(self) -> None:
        await cancel_and_wait(self._loop_task)
        self._loop_task = None

    # -- ingestion → lanes ----------------------------------------------
    def _enqueue(self, engine: TpuInferenceEngine, events: List) -> List:
        """Route a tenant's polled events into scoring lanes; returns the
        pass-through events (non-measurements / over-capacity streams)."""
        family = engine.config.model
        lanes = self._lanes[family]
        slot = self.router.global_slot(engine.placement)
        passthrough = []
        skipped = self.metrics.counter("tpu_inference.skipped_capacity")
        for ev in events:
            if not isinstance(ev, DeviceMeasurement):
                passthrough.append(ev)
                continue
            assigned = engine.streams.lookup_or_assign(ev.device_token, ev.name)
            if assigned is None:
                skipped.inc()
                passthrough.append(ev)
                continue
            dshard, local_id = assigned
            lane = lanes.setdefault((slot, dshard), deque())
            lane.append((local_id, ev.value, ev))
            if family not in self._first_pending_ts:
                self._first_pending_ts[family] = time.monotonic()
        return passthrough

    # -- flush -----------------------------------------------------------
    def _pick_bucket(self, need: int, buckets: Tuple[int, ...], max_batch: int) -> int:
        for b in buckets:
            if need <= b:
                return min(b, max_batch)
        return max_batch

    async def _flush_family(self, engine_cfgs: Dict[int, TenantEngineConfig], family: str) -> int:
        """Build the stacked batch for one family and run the jit step."""
        scorer = self.scorers[family]
        lanes = self._lanes[family]
        pending_max = max((len(q) for q in lanes.values()), default=0)
        if pending_max == 0:
            self._first_pending_ts.pop(family, None)
            return 0
        # all engines of one family share microbatch config by construction
        any_cfg = next(iter(engine_cfgs.values()))
        mb = any_cfg.microbatch
        b_lane = self._pick_bucket(pending_max, tuple(mb.buckets), mb.max_batch)
        t, d = scorer.n_slots, self.mm.n_data_shards
        ids = np.zeros((t, d * b_lane), np.int32)
        vals = np.zeros((t, d * b_lane), np.float32)
        valid = np.zeros((t, d * b_lane), bool)
        taken: List[Tuple[int, int, object]] = []  # (slot, col, event)
        for (slot, dshard), q in lanes.items():
            base = dshard * b_lane
            for i in range(min(len(q), b_lane)):
                local_id, value, ev = q.popleft()
                col = base + i
                ids[slot, col] = local_id
                vals[slot, col] = value
                valid[slot, col] = True
                taken.append((slot, col, ev))
        if any(q for q in lanes.values()):
            self._first_pending_ts[family] = time.monotonic()
        else:
            self._first_pending_ts.pop(family, None)

        scores = scorer.step(ids, vals, valid)
        # device→host sync off the event loop (jax dispatch is async until
        # materialization; don't stall other tenants' polling on it)
        scores_np = await asyncio.get_running_loop().run_in_executor(
            None, np.asarray, scores
        )

        latency = self.metrics.histogram("tpu_inference.latency", unit="s")
        meter = self.metrics.meter("tpu_inference.scored")
        now = time.time() * 1000.0
        scored_ctr = self.metrics.counter("tpu_inference.scored_total")
        by_tenant: Dict[str, List] = {}
        for slot, col, ev in taken:
            ev.score = float(scores_np[slot, col])
            ev.mark("scored")
            latency.record(max(now - ev.received_ts, 0.0) / 1000.0)
            by_tenant.setdefault(ev.tenant, []).append(ev)
        for tenant, evs in by_tenant.items():
            topic = self.bus.naming.scored_events(tenant)
            for ev in evs:
                await self.bus.publish(topic, ev)
        meter.mark(len(taken))
        scored_ctr.inc(len(taken))
        return len(taken)

    def _deadline_reached(self, family: str, deadline_ms: float) -> bool:
        first = self._first_pending_ts.get(family)
        return first is not None and (time.monotonic() - first) * 1000.0 >= deadline_ms

    # -- main loop -------------------------------------------------------
    async def _scoring_loop(self) -> None:
        while True:
            moved = 0
            fam_cfgs: Dict[str, Dict[int, TenantEngineConfig]] = {}
            for tenant, engine in list(self.engines.items()):
                if engine.state is not LifecycleState.STARTED:
                    continue
                assert isinstance(engine, TpuInferenceEngine)
                events = await self.bus.consume(
                    self.bus.naming.inbound_events(tenant),
                    self.group,
                    self.poll_batch,
                    timeout_s=0,
                )
                fam_cfgs.setdefault(engine.config.model, {})[
                    self.router.global_slot(engine.placement)
                ] = engine.config
                if events:
                    passthrough = self._enqueue(engine, events)
                    topic = self.bus.naming.scored_events(tenant)
                    for ev in passthrough:
                        await self.bus.publish(topic, ev)
                    moved += len(events)
            for family, cfgs in fam_cfgs.items():
                if family not in self.scorers:
                    continue
                mb = next(iter(cfgs.values())).microbatch
                lanes = self._lanes[family]
                full = any(len(q) >= mb.max_batch for q in lanes.values())
                if full or self._deadline_reached(family, mb.deadline_ms):
                    moved += await self._flush_family(cfgs, family)
            if moved == 0:
                await asyncio.sleep(0.001)

    # -- introspection ---------------------------------------------------
    def describe(self) -> dict:
        return {
            "mesh": self.mm.describe(),
            "router": self.router.describe(),
            "families": {
                f: {"n_slots": s.n_slots, "max_streams": s.max_streams}
                for f, s in self.scorers.items()
            },
        }
