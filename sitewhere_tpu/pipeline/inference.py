"""tpu-inference: the rebuild's new pipeline stage (the north star).

"A new tpu-inference tenant-engine microservice sits between
inbound-processing and event-management on the bus, micro-batching
DeviceMeasurement events into JAX/XLA pjit calls on a TPU pod"
(BASELINE.json north_star; no reference counterpart — SURVEY.md §2.3).

Dataflow per scoring cycle (the zero-copy columnar feed path —
docs/PERFORMANCE.md has the full stage walkthrough):

  inbound-events[tenant_i] ─┐  MeasurementBatch (struct-of-arrays)
  inbound-events[tenant_j] ─┼→ lane RINGS[(slot, data_shard)]: rows are
          ...              ─┘  written into preallocated numpy segments
                                AT ENQUEUE │ flush on deadline_ms OR full
                                     ▼
              reusable staging buffers u16/bf16[T, D·B] (slice copies,
              two rotating sets per (family, bucket) — no fresh arrays)
                                     ▼
              stage_inputs — ASYNC h2d onto the step's shardings;
              overlaps the previous flush's device compute
                                     ▼
              ShardedScorer.step_counts — ONE jit call, every tenant
                                     ▼
              gather_rows — device-side compaction: only the flushed
              rows' scores leave the chip (wire dtype; d2h bytes are
              rows-proportional, never the T×lane plane)
                                     ▼ (copy_to_host_async issued at
                                        dispatch — the transfer rides
                                        under the next flush's compute)
              completion REAPER — resolves flushes as transfers land:
              out of order across families, FIFO per family (so every
              tenant's batches publish in order)
                                     ▼
              columnar resolve: scores slice-assign back into each
              batch's ``scores`` column; completed batches →
              tpu-scored-events[tenant]

Three latency-hiding moves matter here (SURVEY.md §7 hard parts):
- the host side never touches per-event Python objects — rows move as
  numpy slices end to end, and a flush is slice+pad into reusable
  staging, never ``np.asarray`` over freshly built lists
  (tools/check_hotpath.py lints this invariant);
- the staged device put is issued BEFORE dispatch and is asynchronous,
  so flush N+1's host→device transfer rides under flush N's compute
  (``tpu_inference.h2d_overlapped`` / ``h2d_staged`` expose the ratio);
- the readback is the mirror image: a device-side gather returns only
  the flushed rows (``ShardedScorer.gather_rows``), its d2h copy is
  started asynchronously at dispatch, and a completion reaper resolves
  up to ``max_inflight`` in-flight flushes as their transfers land
  (``tpu_inference.d2h_overlapped`` counts transfers that landed before
  the reaper asked). One device round-trip never stalls the collect
  loop; p99 still lands in the ``tpu_inference.latency`` histogram.

Tenant start/stop flips the scorer's active mask — no recompile; batch-size
buckets keep XLA at a handful of compiled shapes.

Multi-chip serving (docs/PERFORMANCE.md "Multi-chip serving"): the whole
pipeline above is instantiated PER (family, mesh-slice) — the router
places each tenant on a tenant-axis slice, and that slice's scorer,
lane rings, staging pool, in-flight budget, and reap queue are its own.
Slices flush concurrently with zero cross-slice collectives; tenant
moves between slices (failover/rebalance) hold per-tenant FIFO through
``_SliceFence``. A single-slice mesh degenerates to exactly the
single-funnel path described above.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from sitewhere_tpu.core.batch import MeasurementBatch
from sitewhere_tpu.core.events import DeviceMeasurement
from sitewhere_tpu.models import get_model, make_config
from sitewhere_tpu.parallel.mesh import MeshManager
from sitewhere_tpu.parallel.sharded import ShardedScorer
from sitewhere_tpu.parallel.tenant_router import (
    PlacementError,
    TenantPlacement,
    TenantRouter,
)
from sitewhere_tpu.runtime.bus import (
    CircuitBreaker,
    EventBus,
    publish_at_least_once,
)
from sitewhere_tpu.runtime.config import (
    FaultTolerancePolicy,
    TenantEngineConfig,
)
from sitewhere_tpu.runtime.lifecycle import (
    LifecycleState,
    SupervisedTask,
    cancel_and_wait,
)
from sitewhere_tpu.runtime.metrics import (
    D2H_OVERLAP_EPS_S as _D2H_OVERLAP_EPS_S,
    MetricsRegistry,
    RollingQuantile,
)
from sitewhere_tpu.runtime.tenant import MultitenantService, TenantEngine


def _profiler_annotation(enabled: bool, family: str):
    """A ``jax.profiler.TraceAnnotation`` around the scoring dispatch when
    the instance is capturing a profile (InstanceConfig.profile_dir), so
    per-family device time is attributable inside the trace; a cheap
    nullcontext otherwise — and on any profiler fault (the profiler is
    process-global and can be owned elsewhere)."""
    import contextlib

    if not enabled:
        return contextlib.nullcontext()
    try:
        import jax

        return jax.profiler.TraceAnnotation(f"tpu_scoring/{family}")
    except Exception:  # noqa: BLE001 - never let profiling break scoring
        return contextlib.nullcontext()


class StreamRegistry:
    """Per-tenant map (device_token, name) → (data_shard, local_id).

    Streams are pinned to a data shard at first sight (least-loaded wins),
    so window updates for a stream always land on the same device and the
    scoring step needs no collectives (see ``parallel.sharded``).
    """

    def __init__(self, n_data_shards: int, local_capacity: int) -> None:
        self.n_data_shards = n_data_shards
        self.local_capacity = local_capacity
        self._map: Dict[Tuple[str, str], Tuple[int, int]] = {}
        self._next: List[int] = [0] * n_data_shards

    def lookup_or_assign(
        self, device_token: str, name: str
    ) -> Optional[Tuple[int, int]]:
        key = (device_token, name)
        hit = self._map.get(key)
        if hit is not None:
            return hit
        shard = min(range(self.n_data_shards), key=lambda d: self._next[d])
        if self._next[shard] >= self.local_capacity:
            return None  # capacity exhausted; caller passes event through unscored
        local_id = self._next[shard]
        self._next[shard] += 1
        self._map[key] = (shard, local_id)
        return shard, local_id

    def lookup_or_assign_bulk(
        self, batch: MeasurementBatch
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Vectorized per-row (data_shard, local_id): one dict lookup per
        UNIQUE (token, name) pair; rows inherit via inverse indices. Rows
        that can't get a slot come back with shard == -1. Group indices
        come from the batch's cached token/name index (integer codes — no
        string sorts here)."""
        _, first, inverse = np.unique(
            batch.pair_codes(), return_index=True, return_inverse=True
        )
        tokens, names = batch.device_tokens, batch.names
        d_u = np.empty((len(first),), np.int32)
        l_u = np.empty((len(first),), np.int32)
        lookup = self.lookup_or_assign
        for j, fi in enumerate(first.tolist()):
            assigned = lookup(str(tokens[fi]), str(names[fi]))
            if assigned is None:
                d_u[j] = -1
                l_u[j] = 0
            else:
                d_u[j], l_u[j] = assigned
        return d_u[inverse], l_u[inverse]

    @property
    def n_streams(self) -> int:
        return len(self._map)


class _LaneRing:
    """Pending rows for one (slot, data_shard): a preallocated numpy ring.

    Rows are written into fixed-dtype ring segments at enqueue time
    (``push`` — slice assignment, no per-row Python, no per-enqueue
    allocation) and leave either straight into a flush's reusable staging
    buffers (``pop_into``) or as fresh arrays on the cold paths (``pop``:
    drain / park / breaker / failover). Capacity doubles when an intake
    burst overshoots — the per-tenant lane watermark bounds steady-state
    depth, so growth is rare and amortized.
    """

    COLS = ("ids", "vals", "seqs", "rows")
    __slots__ = COLS + ("head", "count")

    def __init__(self, capacity: int = 4096) -> None:
        cap = max(64, int(capacity))
        self.ids = np.empty((cap,), np.int32)   # local stream ids
        self.vals = np.empty((cap,), np.float32)
        self.seqs = np.empty((cap,), np.int64)  # batch sequence numbers
        self.rows = np.empty((cap,), np.int32)  # row index inside the batch
        self.head = 0
        self.count = 0

    @property
    def capacity(self) -> int:
        return len(self.ids)

    def _grow(self, need: int) -> None:
        cap = self.capacity
        new_cap = cap
        while new_cap < need:
            new_cap *= 2
        k = self.count
        first = min(k, cap - self.head)
        for name in self.COLS:
            old = getattr(self, name)
            new = np.empty((new_cap,), old.dtype)
            new[:first] = old[self.head : self.head + first]
            new[first:k] = old[: k - first]
            setattr(self, name, new)
        self.head = 0

    def push(self, ids, vals, seq, rows) -> None:
        """Append rows. ``seq`` may be a scalar (the per-enqueue common
        case — broadcast into the ring, no per-batch full() array)."""
        n = len(ids)
        if self.count + n > self.capacity:
            self._grow(self.count + n)
        cap = self.capacity
        tail = (self.head + self.count) % cap
        first = min(n, cap - tail)
        second = n - first
        self.ids[tail : tail + first] = ids[:first]
        self.vals[tail : tail + first] = vals[:first]
        self.rows[tail : tail + first] = rows[:first]
        if np.ndim(seq):
            self.seqs[tail : tail + first] = seq[:first]
        else:
            self.seqs[tail : tail + first] = seq
        if second:
            self.ids[:second] = ids[first:]
            self.vals[:second] = vals[first:]
            self.rows[:second] = rows[first:]
            self.seqs[:second] = seq[first:] if np.ndim(seq) else seq
        self.count += n

    def pop_into(
        self, k: int, ids_row, vals_row, col0: int, seqs_out, rows_out, off: int
    ) -> None:
        """Move k rows FIFO off the front, straight into one slot's
        staging views (``ids_row``/``vals_row`` at column ``col0`` — the
        dtype cast to the scorer's wire happens inside the slice write)
        and the flush's bookkeeping arrays at offset ``off``. At most two
        slice copies per column; zero intermediate arrays."""
        h, cap = self.head, self.capacity
        first = min(k, cap - h)
        second = k - first
        ids_row[col0 : col0 + first] = self.ids[h : h + first]
        vals_row[col0 : col0 + first] = self.vals[h : h + first]
        seqs_out[off : off + first] = self.seqs[h : h + first]
        rows_out[off : off + first] = self.rows[h : h + first]
        if second:
            ids_row[col0 + first : col0 + k] = self.ids[:second]
            vals_row[col0 + first : col0 + k] = self.vals[:second]
            seqs_out[off + first : off + k] = self.seqs[:second]
            rows_out[off + first : off + k] = self.rows[:second]
        self.head = (h + k) % cap
        self.count -= k

    def pop(self, n: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Take up to n rows off the front as fresh arrays (cold paths)."""
        k = min(int(n), self.count)
        h, cap = self.head, self.capacity
        first = min(k, cap - h)
        out = []
        for name in self.COLS:
            a = getattr(self, name)
            dst = np.empty((k,), a.dtype)
            dst[:first] = a[h : h + first]
            if k > first:
                dst[first:] = a[: k - first]
            out.append(dst)
        self.head = (h + k) % cap
        self.count -= k
        return tuple(out)


class _TrainLaneRing(_LaneRing):
    """Replay-fed train-lane ring: pending TRAINING rows for one
    (slot, data-shard), consumed from the tenant's ``replay-train-feed``
    topic and packed into train microbatches through the same staging →
    h2d wire as scoring flushes. Bounded by the train watermark
    (2 × ``replay_microbatch``): past it the feed consumer stops pulling
    (``tpu_inference.train_feed_backpressure``) and the backlog stays in
    the bus topic, where retention bounds it and the replay pump's own
    overload arbitration already parks the producer. Depth is the
    ``tpu_inference_train_rows{family}`` gauge (tools/check_queues.py).
    Same columnar ring mechanics as the serve lanes — distinct type so
    the bounded-queue lint tracks the train lane as its own queue."""

    __slots__ = ()


def _empty_taken():
    """A train-lane pending entry's ``taken`` placeholder: zero rows, so
    every row-oriented resolve/teardown path (``_resolve_rows`` on the
    seqs/rows columns) is a structural no-op without branching."""
    return (None, None, np.empty((0,), np.int64), np.empty((0,), np.int32))


class _StagingSet:
    """One reusable flush staging set: ids/vals ``[T, D*B]`` in the
    scorer's wire dtypes, lane counts ``[T, D]``, and a cached column
    arange. A flush packs lanes into these buffers in place (no fresh
    ``np.zeros`` per flush) and ``jax.device_put``s them; ``staged``
    pins the device arrays from this set's LAST put — the async h2d copy
    reads the host buffers, so reuse must wait on it (two sets rotating
    per (family, bucket) normally hides that wait entirely)."""

    __slots__ = ("ids", "vals", "counts", "arange", "staged")

    def __init__(self, scorer, b_lane: int) -> None:
        t, d = scorer.n_slots, scorer.mm.n_data_shards
        self.ids = np.zeros((t, d * b_lane), scorer.ids_np_dtype)
        self.vals = np.zeros((t, d * b_lane), scorer.vals_np_dtype)
        self.counts = np.zeros((t, d), np.int32)
        self.arange = np.arange(d * b_lane, dtype=np.int32)
        self.staged = None

    def ensure_reusable(self, metrics) -> None:
        """Block until this set's previous device copy finished (counted;
        with overlap working the transfer is long done by recycle time)."""
        staged = self.staged
        if staged is None:
            return
        self.staged = None
        try:
            if all(a.is_ready() for a in staged):
                return
            metrics.counter("tpu_inference.stage_reuse_waits").inc()
            for a in staged:
                a.block_until_ready()
        except Exception:  # noqa: BLE001 - non-jax arrays (tests) or a
            # dead device buffer (failover mid-rotation): treat as free
            pass


class _PendingFlush:
    """One dispatched flush awaiting its device→host score transfer.

    ``scores`` is either the device-gathered row vector (``gathered``
    True — slice ``[:moved]`` is the picks, already in pack order) or
    the full score plane (fallback for scorers without ``gather_rows``,
    e.g. monkeypatched test doubles — the host then picks
    ``scores[slots, cols]``). The d2h copy was started at dispatch
    (``copy_to_host_async``); outputs that can't copy asynchronously
    get an eager executor materialization instead (``host_future``), so
    fallback flushes still overlap each other like the old per-flush
    deliver tasks did."""

    __slots__ = (
        "family", "sl", "scores", "taken", "moved", "gathered",
        "t_dispatch", "nbytes", "plane_nbytes", "host_future", "t_wait",
        "poisoned", "flops", "rec", "sketch", "shadow", "slot_override",
        "resolved", "lane", "deadline", "retried", "retry_rows",
        "retry_from", "owns_permit",
    )

    def __init__(
        self, family: str, scores, taken, moved: int, gathered: bool,
        nbytes: int, plane_nbytes: int, poisoned: bool = False,
        flops: float = 0.0, rec: Optional[dict] = None,
        sketch=None, shadow=None, sl: int = 0, lane: str = "serve",
    ) -> None:
        self.family = family
        # the mesh slice that ran this flush: reap queues, overlap
        # probes, and device-labeled attribution are all keyed
        # (family, slice) on a multi-chip mesh
        self.sl = sl
        # set when the flush's resolution finished (either way) — the
        # slice-move fence waits on this, never on queue identity
        self.resolved = False
        self.scores = scores
        self.taken = taken
        self.moved = moved
        self.gathered = gathered
        self.t_dispatch = time.perf_counter()
        self.nbytes = nbytes
        self.plane_nbytes = plane_nbytes
        self.host_future = None
        self.t_wait = None  # when the reaper first started waiting on us
        # a flush whose DISPATCH failed (no scores, no transfer): it
        # rides the FIFO so its unscored resolution can't overtake an
        # earlier in-flight flush of the same family
        self.poisoned = poisoned
        # device-time attribution: FLOPs this flush's padded plane
        # executes (scorer.flops_per_flush) and the flight-recorder
        # record completed in place when the flush resolves
        self.flops = flops
        self.rec = rec
        # score-quality payloads riding the same reaper slot: the step's
        # per-slot score sketch (i32[T, D, NBINS] — runtime.scorehealth)
        # and the canary's shadow-scored row vector (previous-variant
        # divergence). Their async host copies start at dispatch like the
        # scores'; by the time the scores land these few-KB transfers
        # have long since followed — no extra round-trip.
        self.sketch = sketch
        self.shadow = shadow
        # the single-used-slot fallback slice zeroes the pack-order slot
        # indices (rows then index row 0 of the slice); this remembers
        # the real slot so NaN attribution survives that path
        self.slot_override: Optional[int] = None
        # which lane dispatched this entry: "serve" (a scoring flush —
        # everything above applies) or "train" (a continual-learning
        # train step riding the same per-slice in-flight window and
        # reaper: ``scores`` holds the per-slot loss vector, ``taken``
        # is empty, and resolution records training metrics instead of
        # publishing batches). One FIFO per (family, slice) keeps the
        # permit accounting and teardown drain uniform across lanes.
        self.lane = lane
        # flush supervision (docs/ROBUSTNESS.md "Device fault domains"):
        # the absolute perf_counter() moment by which this flush's
        # transfer must have landed — past it the reaper force-resolves
        # the rows unscored in this FIFO slot and quarantines the slice.
        # None = unsupervised (flush_deadline_ms knob off, or poisoned
        # entries that land immediately by construction).
        self.deadline: Optional[float] = None
        # poison-batch ejection: this pf IS the one-shot retry of a
        # faulted flush's rows (``retry_from`` = the slice the FIRST
        # failure happened on) — a second failure on a DIFFERENT slice
        # attributes the fault to the data and ships the batches to the
        # scorer-poison DLQ; a second failure on the SAME chip stays a
        # chip signal (unscored resolve + breaker/failover pacing)
        self.retried = False
        self.retry_from: Optional[int] = None
        # host copies of the staged (ids, vals, dshards) rows, kept so a
        # TIMED-OUT flush can retry with the same bytes (the staging set
        # recycles long before a deadline expires); populated only while
        # the family's poison_retry knob is on
        self.retry_rows: Optional[tuple] = None
        # False for ORDERED host-only entries enqueued from inside a
        # resolve task (per-tenant FIFO fallbacks of the poison-retry
        # path): acquiring a permit there can deadlock against the very
        # head whose resolution is enqueueing them, and a host-only
        # poisoned entry holds no device resources for the in-flight
        # window to meter — the resolve/teardown release sites skip it
        self.owns_permit = True

    @property
    def key(self) -> Tuple[str, int]:
        return (self.family, self.sl)

    def overdue(self, now: Optional[float] = None) -> bool:
        """Deadline passed without resolution — the supervisor's
        force-resolve trigger (poisoned entries land instantly and are
        never overdue)."""
        if self.deadline is None or self.poisoned:
            return False
        return (time.perf_counter() if now is None else now) > self.deadline

    def _materialize(self):
        """Worker-thread materialization of every device output riding
        this flush — one executor hop for scores + sketch + shadow."""
        return (
            np.asarray(self.scores),
            None if self.sketch is None else np.asarray(self.sketch),
            None if self.shadow is None else np.asarray(self.shadow),
        )

    def landed(self) -> bool:
        """Probably-complete signal used to PRIORITIZE heads: a finished
        executor materialization, or (for jax arrays) ``is_ready`` —
        which only proves the device COMPUTE finished, not that the
        async host copy crossed the link. Honest overlap accounting is
        therefore measured at materialize time (see ``_resolve_flush``),
        never inferred from this."""
        if self.poisoned:
            return True  # nothing to wait for — resolvable immediately
        if self.host_future is not None:
            return self.host_future.done()
        try:
            return bool(self.scores.is_ready())
        except Exception:  # noqa: BLE001 - non-jax doubles: never "landed"
            return False

    def ensure_host_future(self, loop, pool):
        """Lazily start (and cache) an executor materialization — used
        when the reaper must wait on several families' heads at once.
        Resolves to the (scores, sketch, shadow) host triple."""
        if self.host_future is None:
            self.host_future = loop.run_in_executor(
                pool, self._materialize
            )
        return self.host_future


class _ReapQueue(list):
    """Per-(family, mesh-slice) FIFO of in-flight flush completions —
    the PER-DEVICE drain queues of the multi-chip result path. Depth is
    bounded by the ``max_inflight`` semaphore (acquired before rows are
    popped from lanes) and observable via the
    ``tpu_inference_deliver_inflight`` gauge (+ per-family and
    per-device labeled variants) and the
    ``tpu_inference.deliver_backpressure`` counter
    (tools/check_queues.py registry). FIFO per (family, slice) is what
    gives per-tenant in-order delivery: a tenant lives on exactly one
    slice of one family, the reaper never resolves past an unfinished
    head, and a slice MOVE (failover/rebalance) holds the tenant's rows
    behind a ``_SliceFence`` until the old slice's in-flight flushes
    resolve — so one slow chip's transfers never head-of-line block
    another slice's deliveries, and ordering still survives the move."""

    __slots__ = ()

    def popleft(self) -> _PendingFlush:
        return self.pop(0)


class AmbiguousFamilyError(KeyError):
    """A family-string lookup matched MORE than one mesh slice — the
    caller must key by (family, slice). Distinct from a plain missing
    key so ``get()`` can default only the truly-absent case."""


class _ScorerMap(dict):
    """(family, slice) → ShardedScorer, with family-string convenience
    lookup: ``scorers["lstm_ad"]`` resolves when exactly one slice hosts
    the family (the common single-tenant/operator case); ambiguous
    lookups must name the slice explicitly."""

    def _resolve(self, family: str):
        hits = [k for k in self if k[0] == family]
        if len(hits) == 1:
            return hits[0]
        if not hits:
            raise KeyError(family)
        raise AmbiguousFamilyError(
            f"family '{family}' is served on {len(hits)} mesh slices "
            f"({sorted(k[1] for k in hits)}) — key scorers[(family, slice)]"
        )

    def __getitem__(self, key):
        if isinstance(key, str):
            key = self._resolve(key)
        return dict.__getitem__(self, key)

    def __contains__(self, key) -> bool:
        if isinstance(key, str):
            return any(k[0] == key for k in self)
        return dict.__contains__(self, key)

    def get(self, key, default=None):
        try:
            return self[key]
        except AmbiguousFamilyError:
            # defaulting here would make a multi-slice family look
            # ABSENT at exactly the moment a slice move spread it
            raise
        except KeyError:
            return default

    def family_items(self, family: str):
        return sorted(
            ((k[1], v) for k, v in self.items() if k[0] == family)
        )


class _SliceFence:
    """Holds one re-placed tenant's rows until every flush that was in
    flight on its OLD (family, slice) queue at move time has resolved.

    Without the fence, a tenant moving from slice A to slice B could
    have batch N still riding an unresolved slice-A flush while batch
    N+1 flushes (and lands) on slice B first — breaking the per-tenant
    FIFO guarantee the per-slice reap queues otherwise provide. Rows
    re-keyed off the old lanes AND new bus intake stash here (FIFO
    ``_LaneRing`` per data shard, counted against the tenant's lane
    watermark so a long fence backpressures into the bus); the scoring
    loop lifts the fence when the snapshot drains and pushes the stash
    into the new slice's lanes in arrival order.

    Weight paging reuses the same machinery with ``new_sl=None``: a
    NON-RESIDENT tenant's fence has no landing target yet (its weights
    live host-side as encoded bytes), so rows park indefinitely —
    ``_lift_fences`` skips target-less fences — until a page-in
    activates the tenant and retargets the fence at its new slot."""

    __slots__ = ("tenant", "family", "pending", "stash", "new_sl", "new_slot")

    def __init__(self, tenant: str, family: str, pending: List[_PendingFlush],
                 new_sl: Optional[int], new_slot: Optional[int]) -> None:
        self.tenant = tenant
        self.family = family
        self.pending = pending        # old-slice flushes to outwait
        self.stash: Dict[int, _LaneRing] = {}   # dshard → parked rows
        self.new_sl = new_sl
        self.new_slot = new_slot

    def ready(self) -> bool:
        return all(pf.resolved for pf in self.pending)

    def park(self, dshard: int, ids, vals, seq, rows) -> None:
        ring = self.stash.get(dshard)
        if ring is None:
            ring = self.stash[dshard] = _LaneRing()
        ring.push(ids, vals, seq, rows)

    def depth(self) -> int:
        return sum(r.count for r in self.stash.values())


class TpuInferenceEngine(TenantEngine):
    """Per-tenant engine: placement on the mesh + stream registry."""

    def __init__(self, config: TenantEngineConfig, service: "TpuInferenceService") -> None:
        super().__init__("tpu-inference", config)
        self.service = service
        self.placement = None
        self.streams: Optional[StreamRegistry] = None
        self._feed_subscribed = False  # train-feed group registered

    async def on_start(self) -> None:
        svc = self.service
        try:
            self.placement = svc.router.place(
                self.tenant, family=self.config.model
            )
        except PlacementError:
            if svc.pager is None:
                raise
            # family at physical capacity and weight paging is on: the
            # tenant starts NON-RESIDENT (virtualized slot). Its ghost
            # placement points at a real slice (for scorer/lane lookups)
            # with slot -1 = no device slot held; arriving rows park
            # behind a paging fence and the first demand (or a rising-lag
            # prefetch) pages it in, evicting the LRU victim.
            self.placement = svc._ghost_placement(
                self.tenant, self.config.model
            )
        # the tenant's scorer is its mesh SLICE's scorer: one compiled
        # step per (family, tenant-axis slice), dispatching only to that
        # slice's devices (docs/PERFORMANCE.md "Multi-chip serving")
        scorer = svc.scorer_for_slice(
            self.config.model, self.placement.shard, self.config
        )
        self.streams = StreamRegistry(
            svc.mm.n_data_shards, scorer.max_streams // svc.mm.n_data_shards
        )
        svc.bus.subscribe(svc.bus.naming.inbound_events(self.tenant), svc.group)
        if (
            self.config.training.enabled
            and self.config.training.train_lane
            and getattr(scorer, "train_lane", False)
        ):
            # replay-fed continual learning: scored history published by
            # the replay engine's ``train`` target lands here and the
            # scoring loop's low-priority intake pulls it into the train
            # lane rings. Subscribed ONLY when something will actually
            # consume it — a registered group engages the bus's publish
            # backpressure, so subscribing with the lane off (tenant
            # opt-out / TRAIN_LANE_ENABLED rollback / non-fused family)
            # would wedge a replay train job forever once the topic
            # fills; unsubscribed, the topic keeps its lossy retention
            # tail exactly as before the lane existed.
            svc.bus.subscribe(
                svc.bus.naming.train_feed(self.tenant), svc.group
            )
            self._feed_subscribed = True
        # fair-queue registration: this tenant's intake is rationed by
        # its OverloadPolicy weight from the first poll
        svc.fair.configure(self.tenant, self.config.overload.weight)
        if self.placement.slot >= 0:
            params = None
            if svc.checkpoints is not None:
                # resume this tenant's trained weights (possibly onto a
                # DIFFERENT slot/shard than before — mesh re-placement)
                params = await asyncio.get_running_loop().run_in_executor(
                    None, svc.checkpoints.load_params,
                    self.tenant, self.config.model,
                )
            scorer.activate(
                self.placement.slot, params=params,
                trainable=self.config.training.enabled,
                lr=self.config.training.lr,
            )
            # score-health registration: bind this tenant to its stacked
            # slot so the resolve path can attribute device sketches, and
            # start a FRESH drift baseline — an engine (re)start activates
            # params explicitly, so the reference must re-learn the current
            # model's output distribution (docs/OBSERVABILITY.md
            # "re-baseline")
            svc.scorehealth.register(
                self.tenant, self.config.model,
                self.placement.slot,
                getattr(scorer, "sketch_edges", []),
                mesh_slice=self.placement.shard,
                variant={
                    "fused": bool(getattr(scorer, "fused", False)),
                    "k_steps": int(getattr(scorer, "k_steps", 1)),
                    "param_dtype": getattr(scorer, "param_dtype", "f32"),
                    "wire_dtype": getattr(scorer, "wire_dtype", "f32"),
                },
            )
            svc.scorehealth.rebaseline(self.tenant)
            if svc.pager is not None:
                # residency ledger: this tenant holds a physical slot —
                # it is an LRU eviction candidate from now on
                svc.pager.slice_pager(
                    self.config.model, self.placement.shard,
                    svc.slots_per_shard,
                ).note_resident(self.tenant, self.placement.slot)
        else:
            # NON-RESIDENT start: no device work at all. Install the
            # paging fence so rows arriving before the first page-in
            # park (counted against the lane watermark → backpressure)
            # instead of landing in a slot the tenant doesn't hold.
            svc._install_paging_fence(self)
            svc.metrics.counter(
                "tpu_paging.virtual_starts", family=self.config.model
            ).inc()
        # a tenant lifecycle event is the unpark signal for its family —
        # and clears the family breaker's failure history with it
        svc._parked.discard(self.config.model)
        svc._failover_rounds.pop(self.config.model, None)
        for _sl, breaker in [
            (k[1], v) for k, v in svc.breakers.items()
            if k[0] == self.config.model
        ]:
            breaker.reset()
        # ...and the quarantine ledger: an explicit engine (re)start is
        # the operator's heal signal, the same contract as the breaker
        # resets above — probation probes are for UNATTENDED recovery
        svc.clear_quarantine(self.config.model)

    async def on_stop(self) -> None:
        svc = self.service
        if self.placement is not None:
            sl = self.placement.shard
            slot = self.placement.slot
            scorer = svc.scorers.get((self.config.model, sl))
            if slot >= 0 and scorer is not None and svc.checkpoints is not None:
                # save this tenant's (possibly trained) weights BEFORE the
                # slot wipe below destroys them. Materialize to numpy ON
                # THIS (loop) thread: the reset_slot below DONATES the
                # stacked params buffer, and a worker-thread zero-copy view
                # into it would be a use-after-free (see host_copy_params)
                from sitewhere_tpu.runtime.checkpoint import host_copy_params

                params = host_copy_params(scorer.slot_params(slot))
                await asyncio.get_running_loop().run_in_executor(
                    None, svc.checkpoints.save_params,
                    self.tenant, self.config.model, params,
                )
            if slot >= 0 and scorer is not None:
                # full wipe: a recycled slot must not leak this tenant's
                # window history or params to the next occupant
                scorer.reset_slot(slot)
            if slot < 0 and svc.pager is not None:
                # PAGED-OUT tenant leaving: its only durable state is the
                # host-side segment blob — persist it iff dirty (train-lane
                # tenants mutate weights between page-outs) so the cached
                # training progress survives the engine teardown
                blob = svc.pager.cache.get(self.tenant)
                if (
                    blob is not None
                    and blob[1]
                    and svc.checkpoints is not None
                ):
                    from sitewhere_tpu.runtime.checkpoint import (
                        decode_segment,
                    )

                    def _persist(data=blob[0]):
                        p, _opt = decode_segment(data)
                        svc.checkpoints.save_params(
                            self.tenant, self.config.model, p
                        )

                    await asyncio.get_running_loop().run_in_executor(
                        None, _persist
                    )
            # drain pending lanes keyed by the freed slot: the bus cursor
            # already advanced past these rows, so dropping them would lose
            # them from the store on every tenant restart — resolve them
            # unscored (NaN) instead
            lanes = svc._lanes.get((self.config.model, sl))
            if lanes is not None:
                drained = svc.metrics.counter("tpu_inference.drained_on_stop")
                for key in [k for k in lanes if k[0] == slot]:
                    lane = lanes.pop(key)
                    n = lane.count
                    if n:
                        _ids, _vals, seqs, rows = lane.pop(n)
                        await svc._resolve_rows(
                            seqs, rows, None, publish_nowait=True,
                            family=self.config.model,
                        )
                        drained.inc(n)
            # a tenant removed mid-slice-move: its fenced rows were
            # consumed off the bus, so they resolve unscored too
            fence = svc._fences.pop(self.tenant, None)
            if fence is not None:
                svc.metrics.gauge("tpu_inference_fences").set(
                    len(svc._fences)
                )
                for ring in fence.stash.values():
                    if ring.count:
                        _i, _v, seqs, rows = ring.pop(ring.count)
                        await svc._resolve_rows(
                            seqs, rows, None, publish_nowait=True,
                            family=self.config.model,
                        )
            # the tenant's pending TRAIN rows are droppable — they are
            # replayed history the segment store still holds (a future
            # replay train job re-feeds them); no loss accounting rides
            # on the train lane
            tl = svc._train_lanes.get((self.config.model, sl))
            if tl is not None:
                for key in [k for k in tl if k[0] == slot]:
                    tl.pop(key)
                svc._train_rows_gauge(self.config.model, sl)
            # a recycled slot must not inherit this tenant's mature
            # cadence tick either
            svc._train_ticks.get((self.config.model, sl), {}).pop(
                slot, None
            )
            # the train-feed cursor must leave with the tenant: a stale
            # registered group never advances and would backpressure the
            # topic forever — wedging any LATER replay train job exactly
            # like the never-consumed case the subscribe gate avoids.
            # Gated on the subscribe flag: bus.unsubscribe instantiates
            # absent topics, and a never-subscribed tenant's stop must
            # not litter the bus (and every checkpoint) with empty feeds
            if self._feed_subscribed:
                self._feed_subscribed = False
                svc.bus.unsubscribe(
                    svc.bus.naming.train_feed(self.tenant), svc.group
                )
            svc.router.remove(self.tenant)
            self.placement = None
        if svc.pager is not None:
            # drop every paging artifact (cached blob, queued page-in,
            # residency entry) — a restarted tenant begins cold
            svc.pager.forget(self.tenant)
        svc.fair.remove(self.tenant)
        svc.scorehealth.remove(self.tenant)
        # bounded label cardinality: the per-tenant train-lane ledger
        # tracks LIVE tenants only (scoped sweep — see drop_labeled)
        svc.metrics.drop_labeled(
            families=["tpu_train_steps_total"], tenant=self.tenant
        )
        svc._gates.pop(self.tenant, None)


class TpuInferenceService(MultitenantService):
    """Hosts the scorers + the scoring loop across all tenant engines."""

    def __init__(
        self,
        bus: EventBus,
        mm: Optional[MeshManager] = None,
        metrics: Optional[MetricsRegistry] = None,
        slots_per_shard: int = 8,
        poll_batch: int = 64,
        max_inflight: int = 8,
        checkpoints=None,
        tracer=None,
        overload=None,
        fair_quantum: int = 4096,
        staging_slots: int = 2,
        flightrec=None,
        scorehealth=None,
    ) -> None:
        super().__init__("tpu-inference", bus, self._make_engine)
        self.mm = mm or MeshManager()
        self.metrics = metrics or MetricsRegistry()
        self.checkpoints = checkpoints  # CheckpointManager | None
        # overload control: per-tenant deficit-round-robin intake (bus →
        # lanes is the shared chokepoint every tenant contends on), a
        # per-tenant deadline gate so expired work never reaches a
        # ShardedScorer flush, and degradation-mode sampling
        self.overload = overload
        from sitewhere_tpu.runtime.overload import DeficitRoundRobin

        self.fair = DeficitRoundRobin(quantum=fair_quantum)
        self._gates: Dict[str, object] = {}
        # tracing + scoring profile hooks: per-tenant inference spans, a
        # compile-count per (family, bucket) shape (the first flush at a
        # shape IS the XLA compile — a mid-traffic recompile is the p99
        # cliff SURVEY §7 warns about), and optional jax.profiler
        # annotations so device time shows up in profile_dir traces
        self.tracer = tracer
        # flight recorder (runtime.flightrec): always-on per-flush
        # blackbox records + dump-on-incident (breaker trip) snapshots;
        # None (direct service construction in tests) = fully guarded out
        self.flightrec = flightrec
        # score-quality health (runtime.scorehealth): per-tenant drift
        # windows fed by the device-side score sketches the reaper
        # materializes, plus shadow-canary divergence — always on (the
        # per-flush host cost is one 64-bin add per touched slot)
        if scorehealth is None:
            from sitewhere_tpu.runtime.scorehealth import ScoreHealth

            scorehealth = ScoreHealth(self.metrics)
        self.scorehealth = scorehealth
        # live device-time/MFU attribution per family (runtime.metrics
        # .MfuAccount; fed by resolved flushes, decayed by refresh_mfu)
        self._mfu: Dict[str, object] = {}
        # per-(family, mesh-slice) device-labeled MFU accounts beside
        # the family aggregate (separate metric names — see
        # MfuAccount.DEVICE_NAMES): on a multi-chip mesh, per-chip
        # utilization is what keeps tpu_mfu_pct honest at n_devices>1
        self._mfu_dev: Dict[Tuple[str, int], object] = {}
        self._stage_timers: Dict[str, object] = {}
        self._seen_shapes: set = set()
        self._last_flush: Dict[str, dict] = {}
        self.profile_annotations = False
        self.slots_per_shard = slots_per_shard
        self.poll_batch = poll_batch  # bus items (batches) per poll
        self.router = TenantRouter(self.mm.n_tenant_shards, slots_per_shard)
        # (family, mesh-slice) → ShardedScorer over that slice's
        # sub-mesh: each slice dispatches/stages/reaps independently —
        # the unit of horizontal scale (ROADMAP item 1). String lookup
        # resolves single-slice families for operator/test convenience.
        self.scorers: _ScorerMap = _ScorerMap()
        # first tenant of a family pins the family-wide knobs (wire
        # dtype, fused kernel shape, model config): EVERY slice scorer
        # of the family builds from this config so slices are
        # numerically interchangeable across failover/rebalance moves
        self._family_cfg: Dict[str, TenantEngineConfig] = {}
        # per-(family, slice) circuit breaker over scorer dispatch +
        # materialization (the first tenant's FaultTolerancePolicy pins
        # the policy family-wide, like wire_dtype): breaker scope
        # matches failure scope — one sick chip's open breaker must not
        # short-circuit healthy slices of the family into unscored
        # pass-through. String lookup resolves single-slice families.
        self.breakers: _ScorerMap = _ScorerMap()
        self._lanes: Dict[
            Tuple[str, int], Dict[Tuple[int, int], _LaneRing]
        ] = {}
        # reusable flush staging: (family, slice, bucket) → [next_idx,
        # sets]; ``staging_slots`` sets rotate PER SLICE so every slice
        # packs host buffers while its own previous flush's async h2d
        # copy is still in flight — slices never contend on one pool
        self.staging_slots = max(2, int(staging_slots))
        self._staging: Dict[Tuple[str, int, int], list] = {}
        # per-(family, slice) last dispatch output — the overlap probe
        # (next flush's staging "overlapped" ⇔ this is still computing).
        # With the device-side gather it holds the GATHERED rows (a few
        # KB), never the score plane, and the reaper drops it when the
        # slice's in-flight queue drains so an idle slice pins nothing
        self._last_scores: Dict[Tuple[str, int], object] = {}
        self._first_pending_ts: Dict[Tuple[str, int], float] = {}
        self._loop_super: Optional[SupervisedTask] = None
        # batch registry: seq → [batch, rows_awaiting_scores]
        self._batches: Dict[int, list] = {}
        self._next_seq = 0
        # live-training cadence: per-(family, slice) {slot: flush-tick}.
        # With the async train lane, a LANE slot's tick only accumulates
        # here (maturity is checked — and reset — at lane dispatch, so a
        # throttled slot keeps its mature tick until admitted); inline
        # slots keep the legacy check-and-reset-per-flush semantics.
        self._train_ticks: Dict[Tuple[str, int], Dict[int, int]] = {}
        # continual-learning train lane (docs/PERFORMANCE.md "Continual
        # learning lane"): replay-fed training rows per (family, slice),
        # keyed (slot, data-shard) like the serve lanes; steps since the
        # last weight commit per slice; scratch columns for the packer
        self._train_lanes: Dict[
            Tuple[str, int], Dict[Tuple[int, int], _TrainLaneRing]
        ] = {}
        self._lane_swap: Dict[Tuple[str, int], int] = {}
        # last dispatched lane source per slice ("replay" | "resident")
        # — the alternation token when both sources are pending
        self._lane_last_source: Dict[Tuple[str, int], str] = {}
        self._train_scratch: Optional[tuple] = None
        self.metrics.describe(
            "tpu_train_skipped_total",
            "training work skipped per family and reason (no_trainer/"
            "optimizer_init/parked/throttled/saturated/capacity) — a "
            "misconfigured or starved trainable tenant must not be dark",
        )
        self.metrics.describe(
            "tpu_train_steps_total",
            "train-lane optimizer steps that included the tenant's slot "
            "(the overload arbiter's per-tenant ledger: a saturated "
            "tenant reads exactly 0 while idle tenants train)",
        )
        self.metrics.describe(
            "tpu_train_rows_total",
            "replayed history rows ingested into train microbatches, "
            "per family",
        )
        self.metrics.describe(
            "tpu_train_flops_total",
            "analytic FLOPs executed by train-lane steps per family — "
            "kept OUT of tpu_flops_total/tpu_mfu_pct (serving work); "
            "the bench's overlap-MFU column sums the two",
        )
        self.metrics.describe(
            "tpu_train_swaps_total",
            "train-lane weight commits (kernel-sidecar re-derivation + "
            "canary arm) per family — one every swap_every lane steps",
        )
        # per-(family, slice) last train losses (device arrays; string
        # lookup resolves while one slice serves the family)
        self.last_train_losses: _ScorerMap = _ScorerMap()
        # auto-failover: consecutive scorer errors per (family, slice) —
        # errors are chip-local, so only the sick slice's tenants
        # re-place onto different mesh shards (SURVEY.md §5:
        # "tenant-engine failover to a different mesh shard")
        self.failover_threshold = 3
        self._consec_errors: Dict[Tuple[str, int], int] = {}
        # escalation: failover rounds without an intervening healthy
        # delivery; past max_failover_rounds the family PARKS — events
        # flow through unscored (degraded, never lost) until a tenant
        # lifecycle event clears it
        self.max_failover_rounds = 3
        self._failover_rounds: Dict[str, int] = {}
        self._parked: set = set()
        # slice-move fences: tenant → _SliceFence while a failover/
        # rebalance move outwaits the old slice's in-flight flushes
        self._fences: Dict[str, _SliceFence] = {}
        # in-flight flush budget PER (family, slice): the bound exists
        # to limit concurrent d2h round trips on ONE device queue, so a
        # saturated slice exhausts ITS OWN permits while other slices
        # keep flushing — a global semaphore would let one slow chip
        # starve every other slice's flush admission (the multi-chip
        # analog of the head-of-line blocking the reaper already avoids)
        self._inflight: Dict[Tuple[str, int], asyncio.Semaphore] = {}
        self.max_inflight = max_inflight
        self._deliver_pool = None  # created on start, shut down on stop
        # result path: per-(family, slice) FIFOs of in-flight flush
        # completions — per-DEVICE drain queues, drained by the reaper
        # task as d2h transfers land (out of order across slices and
        # families, in order per tenant)
        self._reap: Dict[Tuple[str, int], _ReapQueue] = {}
        self._reap_event = asyncio.Event()
        self._reaper_super: Optional[SupervisedTask] = None
        # per-(family, slice) resolve task in flight (≤ 1 per slice
        # queue keeps the per-tenant FIFO; separate tasks keep one
        # tenant's backpressured publish from head-of-line blocking
        # other slices' landed transfers behind the reaper coroutine)
        self._resolving: Dict[Tuple[str, int], asyncio.Task] = {}
        # teardown grace for in-flight transfers before they force-resolve
        # unscored (a dead device must not hang the stop cascade)
        self.deliver_drain_timeout_s = 10.0
        # -- fault-domain supervision (docs/ROBUSTNESS.md) ---------------
        # injectable device faults (runtime.faultplan — the chaos layer;
        # None in production). Consulted at every dispatch: serve, train,
        # shadow, and probation-probe lanes.
        self.faultplan = None
        # per-(family, slice) dispatch→transfer-landed history: the
        # flush deadline is max(flush_deadline_ms, flush_deadline_x ×
        # this window's p99) — the same samples the flightrec flush
        # records carry as device_s
        self._flush_p99: Dict[Tuple[str, int], RollingQuantile] = {}
        # quarantined (family, slice)s: SUSPECT after a flush timeout or
        # the failover escalation; the router routes around them, their
        # lanes drain unscored (degraded, never lost), and a background
        # probe re-admits after probation_probes consecutive landings
        self._quarantined: Dict[Tuple[str, int], dict] = {}
        self._probing: Dict[Tuple[str, int], asyncio.Task] = {}
        # poison-batch ejection: batch seqs already granted their one
        # retry — a second failure ships them to the scorer-poison DLQ
        self._retried_seqs: set = set()
        self.metrics.describe(
            "tpu_flush_timeout_total",
            "in-flight flushes force-resolved unscored because their "
            "completion deadline expired, per family and mesh slice — "
            "the flush supervisor's wedged-device signal",
        )
        self.metrics.describe(
            "tpu_inference_quarantined_slices",
            "(family, slice) scorers currently quarantined (SUSPECT) "
            "and under probation probing",
        )
        self.metrics.describe(
            "tpu_flush_latency_p99_ms",
            "rolling dispatch→transfer-landed p99 per (family, mesh "
            "slice) — the flush supervisor's deadline source, surfaced "
            "live for the latency waterfall",
        )
        # -- weight paging (runtime.paging; docs/PERFORMANCE.md "Weight
        # paging") -------------------------------------------------------
        # virtualized slots: tenants beyond a family's physical capacity
        # get a GHOST placement (slot=-1) and page in on demand/prefetch.
        # The kill switch is captured HERE, at build (FUSED_STEP_ENABLED
        # pattern): flip runtime.paging.WEIGHT_PAGING_ENABLED to False
        # before construction and pager is None — every hook below is
        # guarded on it, restoring physical-slot semantics bitwise.
        from sitewhere_tpu.runtime import paging as _paging

        self.paging_enabled = bool(_paging.WEIGHT_PAGING_ENABLED)
        self.pager = (
            _paging.WeightPager(self.metrics) if self.paging_enabled else None
        )
        # ≤ 1 page-in in flight: activation serializes device mutation
        # (set_slot donates the stacked buffer) exactly like failover
        self._pagein_task: Optional[asyncio.Task] = None
        self._paging_next_prefetch = 0.0
        self.metrics.describe(
            "tpu_paging.page_ins",
            "tenant activations from the host byte cache / checkpoint "
            "store per family and origin (demand|prefetch)",
        )
        self.metrics.describe(
            "tpu_paging.page_outs",
            "resident tenants evicted to the host byte cache per family "
            "(LRU weighted by OverloadController traffic)",
        )
        self.metrics.describe(
            "tpu_paging.train_rows_dropped",
            "pending train-lane rows dropped at page-out per family — "
            "replayed history the store still holds (PR 12 round-4 rule)",
        )
        self.metrics.describe(
            "tpu_paging.stalled",
            "page-in attempts that found no evictable victim (every "
            "resident pinned/fenced/quarantined) — the request re-queues "
            "on the next demand touch",
        )

    @property
    def group(self) -> str:
        return "tpu-inference"

    def _inflight_sem(self, key: Tuple[str, int]) -> asyncio.Semaphore:
        sem = self._inflight.get(key)
        if sem is None:
            sem = self._inflight[key] = asyncio.Semaphore(self.max_inflight)
        return sem

    # -- flush supervision -------------------------------------------------
    def _family_ft(self, family: str) -> FaultTolerancePolicy:
        """The family-pinned FaultTolerancePolicy (first tenant wins,
        like every other family knob)."""
        pin = self._family_cfg.get(family)
        return pin.fault_tolerance if pin is not None else (
            FaultTolerancePolicy()
        )

    def _flush_deadline_s(self, family: str, sl: int) -> Optional[float]:
        """Seconds a newly dispatched flush gets before the supervisor
        force-resolves it: max(floor, x × the (family, slice)'s observed
        dispatch→landed p99). None = supervision off for the family
        (``flush_deadline_ms = 0`` — the rollback knob)."""
        ft = self._family_ft(family)
        floor = ft.flush_deadline_ms / 1000.0
        if floor <= 0:
            return None
        rq = self._flush_p99.get((family, sl))
        p99 = rq.quantile() if rq is not None else None
        if p99 is None:
            return floor
        return max(floor, ft.flush_deadline_x * p99)

    def _note_device_s(self, key: Tuple[str, int], device_s: float) -> None:
        rq = self._flush_p99.get(key)
        if rq is None:
            rq = self._flush_p99[key] = RollingQuantile()
        rq.add(device_s)
        p99 = rq.quantile()
        if p99 is not None:
            # the deadline source, surfaced live: per-(family, slice)
            # dispatch→landed p99 used to feed ONLY deadline sizing —
            # the latency waterfall and history sampler read this gauge
            self.metrics.gauge(
                "tpu_flush_latency_p99_ms",
                family=key[0], slice=str(key[1]),
            ).set(round(p99 * 1000.0, 3))

    def _make_engine(self, cfg: TenantEngineConfig) -> TpuInferenceEngine:
        return TpuInferenceEngine(cfg, self)

    def scorer_for_slice(
        self, family: str, sl: int, cfg: TenantEngineConfig
    ) -> ShardedScorer:
        """The (family, mesh-slice) scorer, built lazily over the
        slice's sub-mesh from the FAMILY-PINNED config (first tenant
        wins — every slice of a family must compile the identical
        kernel, or a failover move would change a tenant's numerics)."""
        # knob-conflict checks compare against the family's pinned
        # representative (any existing slice scorer of the family)
        scorer = next(
            (v for (f, _s), v in self.scorers.items() if f == family), None
        )
        if scorer is not None and scorer.wire_dtype != cfg.wire_dtype:
            # the wire dtype is a property of the FAMILY stack (first
            # tenant wins); a later tenant asking for a different wire
            # would silently score at the stack's precision — surface it
            self._record_error(
                "wire-dtype",
                ValueError(
                    f"tenant '{cfg.tenant}' asked wire_dtype="
                    f"'{cfg.wire_dtype}' but family '{family}' runs "
                    f"'{scorer.wire_dtype}' (first tenant pinned it)"
                ),
            )
            self.metrics.counter("tpu_inference.wire_dtype_conflicts").inc()
        from sitewhere_tpu.models.common import clamp_fuse_k

        # compare CLAMPED asks (fuse_k saturates at window-1): two
        # tenants whose requests compile to the identical kernel must
        # not be reported as a conflict
        _w = getattr(scorer, "window", cfg.microbatch.window) or 1
        if scorer is not None and (
            clamp_fuse_k(getattr(scorer, "fuse_k", 1), _w)
            != clamp_fuse_k(getattr(cfg, "fuse_k", 1), _w)
            or getattr(scorer, "requested_param_dtype", "f32")
            != getattr(cfg, "param_dtype", "f32")
        ):
            # like wire_dtype, the fused-kernel knobs are a property of
            # the FAMILY stack (one compiled step per family) — a later
            # tenant asking for different ones would silently score at
            # the stack's settings, so surface it
            self._record_error(
                "fused-knobs",
                ValueError(
                    f"tenant '{cfg.tenant}' asked fuse_k="
                    f"{getattr(cfg, 'fuse_k', 1)}/param_dtype="
                    f"'{getattr(cfg, 'param_dtype', 'f32')}' but family "
                    f"'{family}' runs fuse_k={getattr(scorer, 'fuse_k', 1)}"
                    f"/param_dtype="
                    f"'{getattr(scorer, 'requested_param_dtype', 'f32')}' "
                    f"(first tenant pinned them)"
                ),
            )
            self.metrics.counter("tpu_inference.fused_knob_conflicts").inc()
        if (family, sl) not in self.scorers:
            # build THIS slice's scorer from the family-pinned config so
            # every slice compiles the identical kernel variant
            pin = self._family_cfg.setdefault(family, cfg)
            spec = get_model(family)
            mcfg = make_config(family, {
                **pin.model_config, "window": pin.microbatch.window,
            })
            scorer = ShardedScorer(
                self.mm.slice_manager(sl),
                spec,
                mcfg,
                slots_per_shard=self.slots_per_shard,
                max_streams=pin.max_streams,
                window=pin.microbatch.window,
                wire_dtype=pin.wire_dtype,
                fuse_k=getattr(pin, "fuse_k", 1),
                param_dtype=getattr(pin, "param_dtype", "f32"),
            )
            # shadow-canary fraction: family-pinned like the fused knobs
            # (first tenant wins; one shadow step per family stack)
            scorer.canary_frac = float(getattr(pin, "canary_frac", 0.0) or 0.0)
            self.scorers[(family, sl)] = scorer
            self._lanes[(family, sl)] = {}
            if self.mm.n_devices > 1:
                # how many mesh slices currently serve this family —
                # slice spread is the first thing to read when per-device
                # rows/MFU look uneven (docs/OBSERVABILITY.md)
                self.metrics.gauge(
                    "tpu_inference_slice_scorers", family=family
                ).set(sum(1 for k in self.scorers if k[0] == family))
        else:
            return self.scorers[(family, sl)]
        if (family, sl) not in self.breakers:
            # the failover→park escalation is the scorer's first-line
            # healing; by default the breaker must not open mid-escalation
            # and starve it of failure outcomes (parked families stop
            # flushing), so its verdict window is floored at the park
            # budget. Chaos/testing configs set breaker_defer_to_failover
            # False to let the breaker act first.
            from dataclasses import replace as _replace

            ft = cfg.fault_tolerance
            park_budget = (
                self.failover_threshold * (self.max_failover_rounds + 1) + 1
            )
            if (
                ft.breaker_defer_to_failover
                and ft.breaker_min_samples < park_budget
            ):
                ft = _replace(ft, breaker_min_samples=park_budget)
            self.breakers[(family, sl)] = CircuitBreaker(
                f"tpu_inference.{family}.s{sl}",
                policy=ft,
                metrics=self.metrics,
            )
        return scorer

    # -- lifecycle -------------------------------------------------------
    async def on_start(self) -> None:
        await super().on_start()
        # dedicated materialization pool: the default loop executor may have
        # fewer workers than max_inflight, which would serialize the very
        # device→host transfers the semaphore is meant to pipeline
        from concurrent.futures import ThreadPoolExecutor

        self._deliver_pool = ThreadPoolExecutor(
            # enough workers for every slice's in-flight window to
            # materialize concurrently (per-slice inflight budgets),
            # capped so a wide mesh doesn't spawn a thread army
            max_workers=min(
                32, self.max_inflight * max(1, self.mm.n_slices)
            ),
            thread_name_prefix="tpu-deliver",
        )
        # SUPERVISED scoring loop: a persistent loop error restarts it
        # with backoff instead of silently killing all scoring (the k8s
        # liveness-probe-restart analog, in-process)
        self._loop_super = SupervisedTask(
            "tpu-inference-loop", self._scoring_loop, max_restarts=5
        )
        await self._loop_super.initialize()
        await self._loop_super.start()
        # the completion reaper: resolves in-flight flushes as their d2h
        # transfers land; supervised so a resolve fault can't silently
        # end score delivery (pending queues survive a restart)
        self._reaper_super = SupervisedTask(
            "tpu-inference-reaper", self._reap_loop, max_restarts=5
        )
        await self._reaper_super.initialize()
        await self._reaper_super.start()

    async def on_stop(self) -> None:
        if getattr(self, "_loop_super", None) is not None:
            await self._loop_super.terminate()
            self._loop_super = None
        # an in-flight page-in dies with the loop that launched it; its
        # tenant's parked rows resolve unscored in the fence sweep below
        task = getattr(self, "_pagein_task", None)
        if task is not None and not task.done():
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._pagein_task = None
        # let in-flight transfers land and resolve through the reaper
        # (they hold rows already popped from lanes — dropping them would
        # lose events); only give up if the device never answers
        deadline = time.monotonic() + self.deliver_drain_timeout_s
        while any(self._reap.values()) and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        if self._reaper_super is not None:
            await self._reaper_super.terminate()
            self._reaper_super = None
        # cancel per-family resolves still blocked (e.g. a publish against
        # a stopped consumer): their CancelledError path resolves the
        # popped rows unscored via publish_nowait before re-raising
        for task in list(self._resolving.values()):
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._resolving.clear()
        # force-resolve anything still stuck, unscored (zero loss even
        # when a transfer never completes) — the SAME accounting helper
        # the supervisor's mid-run deadline path uses, so teardown and
        # in-flight force-resolution cannot diverge
        for q in self._reap.values():
            while q:
                pf = q.popleft()
                await self._force_resolve(pf, nowait=True)
                pf.resolved = True
                if pf.owns_permit:
                    self._inflight_sem(pf.key).release()
        self._deliver_gauge()
        # probation probes die with the service; a hung chaos plan must
        # release its blocked worker threads or the deliver pool's
        # shutdown below strands them past interpreter exit
        for task in list(self._probing.values()):
            task.cancel()
        self._probing.clear()
        if self.faultplan is not None:
            self.faultplan.clear()
        pool = getattr(self, "_probe_pool", None)
        if pool is not None:
            # wait=False: a probe thread parked inside a wedged chip's
            # materialization must not hang the stop cascade
            pool.shutdown(wait=False)
            self._probe_pool = None
        # final sweep: rows can land in lanes (or slice-move fences)
        # AFTER their engine's own stop-drain (the scoring loop keeps
        # consuming during the stop cascade) — resolve them unscored so
        # no consumed event is lost
        for (fam, _sl), lanes in self._lanes.items():
            for key in list(lanes):
                lane = lanes.pop(key)
                if lane.count:
                    _i, _v, seqs, rows = lane.pop(lane.count)
                    await self._resolve_rows(
                        seqs, rows, None, publish_nowait=True, family=fam
                    )
        for fence in list(self._fences.values()):
            for ring in fence.stash.values():
                if ring.count:
                    _i, _v, seqs, rows = ring.pop(ring.count)
                    await self._resolve_rows(
                        seqs, rows, None, publish_nowait=True,
                        family=fence.family,
                    )
        self._fences.clear()
        # pending train rows are droppable history (the segment store
        # still holds them; a future replay train job re-feeds) — no
        # unscored-resolve obligation on the train lane. Zero the depth
        # gauges as the rings go: a stopped service must not report
        # phantom pending training rows forever.
        for fam in {f for (f, _sl) in self._train_lanes}:
            self.metrics.gauge(
                "tpu_inference_train_rows", family=fam
            ).set(0)
        self._train_lanes.clear()
        self._last_scores.clear()  # drop any pinned device score memory
        if self.mm.n_devices > 1:
            # cardinality guard (the drop_labeled pattern): a stopped
            # service's device-labeled children must not be exported
            # forever — device labels track the LIVE mesh
            for lbl in self.mm.device_labels():
                self.metrics.drop_labeled(device=lbl)
        if self._deliver_pool is not None:
            self._deliver_pool.shutdown(wait=False)
            self._deliver_pool = None

    # -- ingestion → lanes (columnar) ------------------------------------
    async def _enqueue_batch(
        self,
        engine: TpuInferenceEngine,
        batch: MeasurementBatch,
        sample_rate: float = 1.0,
    ) -> None:
        """Route a MeasurementBatch's rows into scoring lanes. Rows that
        can't get a stream slot resolve immediately as unscored.
        ``sample_rate < 1`` is the ``sample_inference`` degradation mode:
        only a strided sample of rows is scored, the rest resolve
        unscored right away (they still persist — degraded, never lost)
        so the TPU budget shrinks without breaking accounting."""
        family = engine.config.model
        sl = engine.placement.shard
        # setdefault: a GHOST (paged-out) tenant's slice may not have
        # served yet — its rows only ever park behind the paging fence
        lanes = self._lanes.setdefault((family, sl), {})
        slot = engine.placement.slot
        fence = self._fences.get(engine.tenant)
        if self.pager is not None:
            if slot >= 0:
                # resident: LRU refresh + hit-rate / prefetch-accuracy
                # bookkeeping (pure dict ops — stays off check_hotpath's
                # forbidden list)
                self.pager.slice_pager(
                    family, sl, self.slots_per_shard
                ).touch(engine.tenant)
                self.pager.note_touch(engine.tenant, True)
            else:
                # non-resident: rows park behind the paging fence below;
                # queue a DEMAND page-in (always admitted — parked rows
                # must never strand behind an unserviceable fence)
                self.pager.note_touch(engine.tenant, False)
                self.pager.queue.push(
                    engine.tenant, "demand", time.monotonic()
                )
        n = batch.n
        if batch.scores is None:
            batch.scores = np.full((n,), np.nan, np.float32)
        seq = self._next_seq
        self._next_seq += 1
        entry = [batch, n]
        self._batches[seq] = entry
        batch.mark("inference_enqueue")  # inference span start / lane wait

        # per-row (dshard, local_id): one registry lookup per UNIQUE
        # (device, name) series, scattered back via inverse indices — no
        # event objects, no awaits, no per-row Python
        dshards, locals_ = engine.streams.lookup_or_assign_bulk(batch)
        skipped = int((dshards == -1).sum())
        if skipped:
            self.metrics.counter("tpu_inference.skipped_capacity").inc(skipped)
            entry[1] -= skipped
        if sample_rate < 1.0:
            step = max(1, int(round(1.0 / max(sample_rate, 1e-3))))
            sampled_out = np.ones((n,), bool)
            sampled_out[::step] = False
            sampled_out &= dshards != -1  # don't double-count skipped rows
            k = int(sampled_out.sum())
            if k:
                dshards = np.where(sampled_out, -1, dshards)
                entry[1] -= k
                self.metrics.counter("tpu_inference.sampled_out").inc(k)
        if entry[1] <= 0:
            # nothing left awaiting scores (all rows skipped, or an empty
            # batch) — publish now or the registry entry leaks forever
            await self._publish_batch(seq)
            return
        parked = 0
        for d in range(self.mm.n_data_shards):
            sel = np.nonzero(dshards == d)[0]
            if sel.size == 0:
                continue
            if fence is not None:
                # mid-slice-move: the tenant's new rows park behind the
                # fence (FIFO) until the old slice's in-flight flushes
                # resolve — per-tenant delivery order survives the move
                fence.park(d, locals_[sel], batch.values[sel], seq, sel)
                parked += sel.size
                continue
            lane = lanes.get((slot, d))
            if lane is None:
                # sized to the lane watermark (2× max_batch split across
                # data shards) so steady state never reallocates
                lane = lanes[(slot, d)] = _LaneRing(
                    max(
                        4096,
                        2 * engine.config.microbatch.max_batch
                        // max(1, self.mm.n_data_shards),
                    )
                )
            # sel doubles as the row indices inside the batch; seq
            # broadcasts — rows land in the ring right here, at enqueue
            lane.push(locals_[sel], batch.values[sel], seq, sel)
        if fence is not None:
            if parked:
                self.metrics.counter("tpu_inference.fenced_rows").inc(parked)
                if fence.new_sl is None and "paged" not in batch.trace:
                    # cold-start activation SLO (docs/OBSERVABILITY.md):
                    # the batch waited on a page-in — its parked time
                    # folds into lane_wait in the stage ledger, and this
                    # mark keys it out of the hot-path latency columns
                    batch.mark("paged")
            return
        if (family, sl) not in self._first_pending_ts:
            self._first_pending_ts[(family, sl)] = time.monotonic()

    # -- score write-back -------------------------------------------------
    async def _resolve_rows(
        self,
        seqs: np.ndarray,
        rows: np.ndarray,
        scores: Optional[np.ndarray],
        publish_nowait: bool = False,
        family: str = "",
    ) -> int:
        """Columnar score write-back: scatter ``scores`` (or NaN for an
        unscored resolution) into their batches' score columns one
        contiguous run at a time, then publish every batch that became
        complete — in seq (= enqueue) order, so a tenant's batches leave
        in order even when a flush carried several. Returns the number
        of batches published.

        Rows arrive grouped: lanes pop FIFO and flushes pack lanes in
        sorted order, so equal-seq runs are contiguous and their row
        indices ascend — a dense run is a pure slice assignment, a
        sampled/split one a single vectorized scatter. Run count is
        O(lanes × batches per flush), tiny next to row count; no
        per-row Python, no list accumulators (tools/check_hotpath.py
        keeps it that way)."""
        n = len(seqs)
        if n == 0:
            return 0
        if scores is None and family:
            # the poisoned/parked/drain deliveries used to publish NaN
            # rows with NO counter — an operator watching scored_total
            # could not tell a degraded family from a healthy one
            self.metrics.counter(
                "tpu_scores_unscored_total", family=family
            ).inc(n)
        cuts = np.flatnonzero(seqs[1:] != seqs[:-1]) + 1
        done = np.empty((len(cuts) + 1,), np.int64)
        k = 0
        a = 0
        for b in (*cuts.tolist(), n):
            s = int(seqs[a])
            entry = self._batches.get(s)
            if entry is not None:
                dst = entry[0].scores
                run = rows[a:b]
                # dense ⇔ consecutive ascending rows (one lane's FIFO pop
                # — the common case); a run spanning several lanes or a
                # sampled batch falls back to one vectorized scatter
                dense = b - a == 1 or bool((np.diff(run) == 1).all())
                if scores is None:
                    if dense:
                        dst[int(run[0]) : int(run[-1]) + 1] = np.nan
                    else:
                        dst[run] = np.nan
                elif dense:
                    dst[int(run[0]) : int(run[-1]) + 1] = scores[a:b]
                else:
                    dst[run] = scores[a:b]
                if scores is None:
                    # per-tenant delivery-quality accounting (one call
                    # per run, never per row — runtime.scorehealth)
                    self.scorehealth.note_unscored(entry[0].tenant, b - a)
                entry[1] -= b - a
                if entry[1] <= 0:
                    done[k] = s
                    k += 1
            a = b
        if k:
            # publish in ascending seq order (scatter above was
            # await-free, so no batch state moved under us)
            done[:k].sort()
            seq_list = done[:k].tolist()
            for i, s in enumerate(seq_list):
                try:
                    await self._publish_batch(int(s), nowait=publish_nowait)
                except BaseException:
                    # cancelled (teardown) or a publish fault mid-loop:
                    # the remaining completed batches are already out of
                    # the registry's reach of any later resolve — flush
                    # them nowait or they strand in _batches and their
                    # events are lost
                    for s2 in seq_list[i + 1:]:
                        await self._publish_batch(int(s2), nowait=True)
                    raise
        return k

    def _gate(self, tenant: str):
        """Per-tenant inference deadline gate (lazy): expired batches
        route to the expired topic BEFORE any lane/flush work — this is
        the 'no expired event reaches a ShardedScorer flush' guarantee."""
        g = self._gates.get(tenant)
        if g is None:
            from sitewhere_tpu.runtime.overload import DeadlineGate

            g = self._gates[tenant] = DeadlineGate(
                self.bus, tenant, "inference", self.metrics,
                tracer=self.tracer, controller=self.overload,
            )
        return g

    def _stage_timer(self, tenant: str):
        t = self._stage_timers.get(tenant)
        if t is None:
            from sitewhere_tpu.runtime.tracing import StageTimer

            t = self._stage_timers[tenant] = StageTimer(
                self.tracer, self.metrics, tenant, "inference"
            )
        return t

    async def _publish_batch(self, seq: int, nowait: bool = False) -> None:
        batch, _ = self._batches.pop(seq)
        # a retried batch that made it out scored is no longer suspect
        self._retried_seqs.discard(seq)
        # inference span: start = lane enqueue, queue wait = bus time since
        # the inbound stage published; annotations carry the family's last
        # flush profile (dispatch time, whether it compiled a new shape)
        t_now = time.time() * 1000.0
        enq = batch.trace.get("inference_enqueue", t_now)
        prev = max(
            (v for k, v in batch.trace.items() if k != "inference_enqueue"),
            default=enq,
        )
        engine = self.engines.get(batch.tenant)
        family = engine.config.model if engine is not None else ""
        self._stage_timer(batch.tenant).observe(
            batch, enq, t_now, n_events=batch.n,
            queue_wait_ms=max(0.0, enq - prev),
            **self._last_flush.get(family, {}),
        )
        batch.mark("scored")
        topic = self.bus.naming.scored_events(batch.tenant)
        if nowait:
            # teardown path: the consumer may already be stopped; an
            # awaitable publish against a full topic would never unblock
            self.bus.publish_nowait(topic, batch)
        else:
            # normal path: preserve backpressure toward persistence — a
            # lagging store slows scoring instead of silently evicting
            # whole batches past retention. The batch is already out of
            # the registry, so a transient publish fault must be retried
            # here (nowait fallback) or the whole batch would vanish.
            try:
                await publish_at_least_once(
                    self.bus, topic, batch, metrics=self.metrics
                )
            except asyncio.CancelledError:
                raise  # publish_at_least_once already appended nowait
            except Exception:
                # non-transient fault: same registry-reach argument —
                # append nowait before surfacing, or the batch is lost
                self.bus.publish_nowait(topic, batch)
                raise
        # latency accounting: sample rows (full per-row recording would be
        # a Python loop over 10^5 rows/s). Replayed history carries its
        # ORIGINAL received_ts — hours-old samples would flood the live
        # p99/SLO series for the whole replay, so only live traffic
        # records latency (replay progress has its own metric family).
        if "replay" not in batch.trace:
            lat = self.metrics.histogram("tpu_inference.latency", unit="s")
            now = time.time() * 1000.0
            rts = batch.received_ts[:: max(1, batch.n // 16)]
            lat.record_many(((now - rts) / 1000.0).tolist())
        self.metrics.counter("tpu_inference.scored_total").inc(batch.n)
        self.metrics.meter("tpu_inference.scored").mark(batch.n)

    # -- flush -----------------------------------------------------------
    def _pick_bucket(self, need: int, buckets: Tuple[int, ...], max_batch: int) -> int:
        for b in buckets:
            if need <= b:
                return min(b, max_batch)
        return max_batch

    def _staging_set(
        self, family: str, sl: int, scorer, b_lane: int
    ) -> _StagingSet:
        """Next rotating staging set for (family, slice, bucket) —
        created once, reused for the lifetime of the shape. Per-slice
        pools are what let slices pack+stage concurrently instead of
        funneling through one rotation."""
        key = (family, sl, b_lane)
        rot = self._staging.get(key)
        if rot is None:
            rot = self._staging[key] = [
                0, [_StagingSet(scorer, b_lane) for _ in range(self.staging_slots)],
            ]
            # bounded-pool observability (check_queues): total resident
            # staging sets across every (family, slice, bucket) rotation
            self.metrics.gauge("tpu_inference_staging_sets").set(
                sum(len(r[1]) for r in self._staging.values())
            )
        idx, sets = rot
        rot[0] = (idx + 1) % len(sets)
        st = sets[idx]
        st.ensure_reusable(self.metrics)
        return st

    async def _flush_slice(
        self, engine_cfgs: Dict[int, TenantEngineConfig], family: str,
        sl: int,
    ) -> int:
        """Pack one (family, mesh-slice)'s lane rings into the slice's
        reusable staging set, stage the buffers to the SLICE's devices
        (async h2d — overlaps any in-flight flush's dispatch, on this
        slice or any other), dispatch the slice's jit step, and hand
        score materialization to the per-device reap queue. Slices flush
        independently: no cross-slice collectives, no shared staging
        pool, no shared completion stream."""
        scorer = self.scorers[(family, sl)]
        lanes = self._lanes[(family, sl)]
        if family in self._parked or (family, sl) in self._quarantined:
            # degraded mode: resolve pending rows unscored so events keep
            # flowing to persistence/rules while the scorer is parked —
            # or while THIS slice is quarantined and its tenants could
            # not fail over (fleet at capacity): the slice passes its
            # events through unscored until probation re-admits it
            if family not in self._parked:
                self.metrics.counter(
                    "tpu_inference.quarantine_passthrough"
                ).inc()
            drained = 0
            for key in list(lanes):
                lane = lanes.pop(key)
                if lane.count:
                    _i, _v, seqs, rows = lane.pop(lane.count)
                    await self._resolve_rows(seqs, rows, None, family=family)
                    drained += len(seqs)
            self._first_pending_ts.pop((family, sl), None)
            return drained
        if not any(l.count for l in lanes.values()):
            self._first_pending_ts.pop((family, sl), None)
            return 0
        breaker = self.breakers.get((family, sl))
        if breaker is not None and not breaker.allow():
            # breaker OPEN: stop hammering the scorer — resolve pending
            # rows unscored (degraded, never lost) until the half-open
            # schedule lets a trial flush probe recovery. Trial failures
            # keep feeding the failover→park escalation below.
            drained = 0
            for key in list(lanes):
                lane = lanes.pop(key)
                if lane.count:
                    _i, _v, seqs, rows = lane.pop(lane.count)
                    await self._resolve_rows(seqs, rows, None, family=family)
                    drained += len(seqs)
            self._first_pending_ts.pop((family, sl), None)
            self.metrics.counter("tpu_inference.breaker_short_circuits").inc()
            return drained
        any_cfg = next(iter(engine_cfgs.values()))
        mb = any_cfg.microbatch
        # acquire the in-flight slot BEFORE popping rows off the lanes:
        # a cancellation while waiting here must not strand popped rows
        # (everything from the pop to the reap enqueue below is
        # await-free).
        t_acq = time.perf_counter()
        sem = self._inflight_sem((family, sl))
        if sem.locked():
            # all of THIS slice's completion slots busy: the flush
            # backpressures here, where depth is the deliver_inflight
            # gauge (check_queues) — other slices' budgets are untouched
            self.metrics.counter("tpu_inference.deliver_backpressure").inc()
        await sem.acquire()
        self.metrics.histogram("tpu_inference.acquire_wait", unit="s").record(
            time.perf_counter() - t_acq
        )
        # pick the bucket AFTER the (possibly long) acquire wait: rows that
        # accumulated while every slot was busy should ride out in ONE
        # bigger flush, not drain at the stale pre-wait size
        pending_max = max((l.count for l in lanes.values()), default=0)
        b_lane = self._pick_bucket(pending_max, tuple(mb.buckets), mb.max_batch)
        # wire-thin stacked batch: compact id/value dtypes + one count per
        # (slot, data-shard) lane instead of a bool mask — rows fill each
        # lane from the front, so validity is derivable on device (see
        # ShardedScorer.step_counts; h2d bytes are a first-class budget).
        # Assembly is slice copies lane-ring → REUSABLE staging buffers:
        # no fresh flush arrays, no list accumulators, no np.asarray over
        # Python lists (tools/check_hotpath.py enforces this stays true).
        t_asm = time.perf_counter()
        st = self._staging_set(family, sl, scorer, b_lane)
        ids, vals, counts = st.ids, st.vals, st.counts
        counts[:] = 0
        take_total = 0
        for lane in lanes.values():
            take_total += min(lane.count, b_lane)
        slots_cat = np.empty((take_total,), np.int32)
        cols_cat = np.empty((take_total,), np.int32)
        seqs_cat = np.empty((take_total,), np.int64)
        rows_cat = np.empty((take_total,), np.int32)
        moved = 0
        used_slots: set = set()
        # SORTED lane order: the device-side gather compacts valid rows
        # in (slot, data-shard, lane-position) order, so the host-side
        # seqs/rows bookkeeping must pack in exactly that order for
        # gathered[:moved] to line up with seqs_cat/rows_cat
        for (slot, dshard), lane in sorted(lanes.items()):
            k = min(lane.count, b_lane)
            if k == 0:
                continue
            base = dshard * b_lane
            lane.pop_into(k, ids[slot], vals[slot], base, seqs_cat, rows_cat, moved)
            slots_cat[moved : moved + k] = slot
            cols_cat[moved : moved + k] = st.arange[base : base + k]
            counts[slot, dshard] = k
            used_slots.add(slot)
            moved += k
        depth_left = 0
        for lane in lanes.values():
            depth_left += lane.count
        self.metrics.gauge("tpu_inference_lane_rows", family=family).set(
            depth_left
        )
        if depth_left:
            self._first_pending_ts[(family, sl)] = time.monotonic()
        else:
            self._first_pending_ts.pop((family, sl), None)
        if moved == 0:
            sem.release()
            if breaker is not None:
                breaker.release_trial()  # allowed, but no call was made
            return 0
        assembly_s = time.perf_counter() - t_asm
        self.metrics.histogram("tpu_inference.flush_assembly", unit="s").record(
            assembly_s
        )

        taken = (slots_cat, cols_cat, seqs_cat, rows_cat)
        shape_key = (family, sl, b_lane)
        compiling = shape_key not in self._seen_shapes
        h2d_stage_s: Optional[float] = None  # for the fault record when
        dispatch_s: Optional[float] = None   # the try below dies early
        rec: Optional[dict] = None           # blackbox record, once made
        try:
            # h2d prefetch: issue the ASYNC device copy before dispatch.
            # "Overlapped" is measured honestly: the previous flush's
            # dispatch output is not yet ready ⇔ this staging copy rides
            # under genuinely in-flight device compute (a pending deliver
            # task alone could just be awaiting its publish).
            prev_scores = self._last_scores.get((family, sl))
            try:
                overlapped = (
                    prev_scores is not None and not prev_scores.is_ready()
                )
            except Exception:  # noqa: BLE001 - monkeypatched scorers
                overlapped = bool(any(self._reap.values()))
            t_stage = time.perf_counter()
            stage = getattr(scorer, "stage_inputs", None)
            if stage is not None:
                staged = stage(ids, vals, counts)
                st.staged = staged
            else:  # monkeypatched/minimal scorers (tests)
                staged = (ids, vals, counts)
            h2d_stage_s = time.perf_counter() - t_stage
            self.metrics.histogram("tpu_inference.h2d_stage", unit="s").record(
                h2d_stage_s
            )
            self.metrics.counter("tpu_inference.h2d_staged").inc()
            if overlapped:
                self.metrics.counter("tpu_inference.h2d_overlapped").inc()
            try:
                self.metrics.counter("tpu_inference.staged_bytes").inc(
                    scorer.stage_nbytes(staged)
                )
            except Exception:  # noqa: BLE001 - observability only
                pass
            # shadow-scoring canary: when armed (non-f32/K>1 variant or a
            # recent hot-swap, at the family's canary_frac stride), score
            # this flush ALSO through the previous variant — the legacy
            # f32 step. It must dispatch BEFORE the primary step: it
            # reads the window state the primary is about to donate, and
            # same-queue dispatch order guarantees that read. Shadow
            # FLOPs land in tpu_shadow_flops_total — NEVER the MFU
            # account — so tpu_mfu_pct keeps meaning "serving work".
            shadow_dev = None
            take = getattr(scorer, "canary_take", None)
            if take is not None and take():
                try:
                    shadow_plane = scorer.shadow_step_counts(*staged)
                    shadow_dev = scorer.gather_rows(
                        shadow_plane, staged[2], moved
                    )
                    if self.faultplan is not None:
                        # chaos: the shadow lane is a fault domain too —
                        # a hung shadow transfer blocks the flush's
                        # materialization triple, and the same deadline
                        # must catch it
                        shadow_dev = self.faultplan.wrap(
                            shadow_dev, family, sl, "shadow"
                        )
                    shadow_dev.copy_to_host_async()
                    self.metrics.counter("tpu_inference.canary_flushes").inc()
                    self.metrics.counter(
                        "tpu_shadow_flops_total", family=family
                    ).inc(float(scorer.shadow_flops_per_flush(b_lane)))
                except Exception as exc:  # noqa: BLE001 - the canary is
                    # advisory: it must never take scoring down with it
                    self._record_error("canary", exc)
                    shadow_dev = None
            if self.faultplan is not None:
                # fail_dispatch injection (the poison-batch scenario) —
                # raises through the fault path below like a real
                # kernel crash on this batch's data
                self.faultplan.maybe_raise(family, sl, "serve")
            t_disp = time.perf_counter()
            with _profiler_annotation(self.profile_annotations, family):
                scores_dev = scorer.step_counts(*staged)  # async dispatch
            dispatch_s = time.perf_counter() - t_disp
            self.metrics.histogram("tpu_inference.dispatch", unit="s").record(
                dispatch_s
            )
            disp_labels = {"family": family}
            if self.mm.n_devices > 1:
                # multichip path: stamp the device so ROADMAP item 1's
                # mesh promotion lands with per-device attribution in
                # place. Cardinality is mesh-bounded (device labels come
                # only from live mesh devices) and the service drops its
                # device children on stop (drop_labeled)
                disp_labels["device"] = getattr(
                    scorer, "device_label", "device:?"
                )
            self.metrics.histogram(
                "tpu_inference_dispatch_seconds", **disp_labels
            ).record(dispatch_s)
            if compiling:
                # first flush at this (family, bucket) shape = XLA compile;
                # a counter bump here is how a mid-traffic recompile (new
                # bucket, missed prewarm) becomes attributable instead of
                # an anonymous p99 cliff
                self._seen_shapes.add(shape_key)
                self.metrics.counter("tpu_inference.compiles").inc()
                self.metrics.counter(
                    "tpu_inference_compiles", family=family,
                    bucket=str(b_lane),
                ).inc()
            self._last_flush[family] = {
                "family": family,
                "dispatch_s": round(dispatch_s, 6),
                "compiled": compiling,
                "bucket": b_lane,
                # latency-attribution profile: runtime.latency splits the
                # inference span into its flush sub-stages on these keys
                # (device/d2h/resolve halves land when the reaper
                # resolves — see _resolve_flush)
                "flush_assembly_s": round(assembly_s, 6),
                "flush_h2d_s": round(h2d_stage_s, 6),
                "flush_dispatch_s": round(dispatch_s, 6),
            }
            if self.mm.n_devices > 1:
                # per-device throughput attribution: which chip scored
                # these rows (slice balance / skew ride on this)
                self.metrics.counter(
                    "tpu_inference_device_rows_total",
                    device=scorer.device_label,
                ).inc(moved)
            self.metrics.counter("tpu_inference.flushes").inc()
            self.metrics.counter("tpu_inference.flush_rows").inc(moved)
            if self.flightrec is not None:
                # the blackbox record for this flush — completed in place
                # (d2h/resolve/device timings) when the reaper resolves it
                rec = self.flightrec.record(
                    "flush", family,
                    lane="serve",
                    rows=moved, bucket=b_lane,
                    assembly_s=round(assembly_s, 6),
                    h2d_stage_s=round(h2d_stage_s, 6),
                    dispatch_s=round(dispatch_s, 6),
                    h2d_overlapped=bool(overlapped),
                    compiled=compiling,
                    # kernel variant attribution: which fused-step shape
                    # produced this flush's timings (incident snapshots
                    # must name the variant, not just the family)
                    k_steps=getattr(scorer, "k_steps", 1),
                    param_dtype=getattr(scorer, "param_dtype", "f32"),
                    # multi-chip attribution: WHICH slice/chip ran this
                    # flush — incident snapshots must name the device
                    mesh_slice=sl,
                    device_label=scorer.device_label,
                    trace_id=self._flush_trace_id(seqs_cat),
                    status="inflight",
                )
            # device-side gather: compact ONLY the flushed rows out of
            # the [T, D*B] score plane before anything crosses d2h —
            # transfer volume becomes rows-proportional (wire dtype),
            # independent of tenant count. Shapes come from the ladder
            # prewarm compiles (ShardedScorer.gather_ladder).
            plane_nbytes = int(getattr(scores_dev, "nbytes", 0))
            # the step's device-side score sketch (i32[T, D, NBINS]) —
            # a few hundred bytes riding the same async readback; its
            # host copy starts here like the scores' below
            sketch_dev = getattr(scorer, "last_sketch", None)
            if sketch_dev is not None:
                try:
                    sketch_dev.copy_to_host_async()
                except Exception:  # noqa: BLE001 - numpy/test doubles
                    pass
            gathered = False
            gather = getattr(scorer, "gather_rows", None)
            if gather is not None and hasattr(scores_dev, "is_ready"):
                try:
                    scores_dev = gather(scores_dev, staged[2], moved)
                    gathered = True
                except Exception as exc:  # noqa: BLE001 - fall back to
                    # the full-plane readback rather than lose the flush
                    self._record_error("gather", exc)
            slot_override = None
            if not gathered and len(used_slots) == 1 and scorer.n_slots > 1:
                # legacy d2h diet for gather-less scorers (monkeypatched
                # doubles): one used slot → slice that row on device
                only = next(iter(used_slots))
                scores_dev = scores_dev[np.full((1,), only, np.int32)]
                slots_cat[:] = 0  # rows now index row 0 of the slice
                slot_override = only  # keep NaN attribution honest
            if self.faultplan is not None:
                # hang/corrupt/slow/late-fail injection: the proxy
                # applies the fault exactly where the reaper's executor
                # materialization touches a real wedged device
                scores_dev = self.faultplan.wrap(
                    scores_dev, family, sl, "serve"
                )
            # overlap probe for the NEXT flush — now holds the gathered
            # rows (a few KB), not a full flush of plane memory; the
            # reaper drops it when the family goes idle
            self._last_scores[(family, sl)] = scores_dev
            try:
                # start the d2h copy NOW: it rides under the next
                # flush's compute and is (ideally) done by the time the
                # reaper asks — the mirror image of stage_inputs
                scores_dev.copy_to_host_async()
            except Exception:  # noqa: BLE001 - numpy/test doubles
                pass
        except Exception as exc:  # noqa: BLE001 - a failing scorer must
            # not strand popped rows or kill the loop; repeated failures
            # trigger shard failover
            self._record_error("step", exc)
            if breaker is not None:
                breaker.record_failure()
            err_rec = None
            if self.flightrec is not None:
                if rec is not None:
                    # the flush already has an inflight record (the fault
                    # hit AFTER dispatch, e.g. device-side slicing):
                    # complete IT — appending a second record would leave
                    # a phantom stuck forever at status="inflight" in the
                    # ring and in any breaker-trip snapshot
                    rec["status"] = "error"
                    rec["error"] = repr(exc)
                    err_rec = rec
                else:
                    err_rec = self.flightrec.record(
                        "flush", family,
                        lane="serve",
                        rows=moved, bucket=b_lane,
                        assembly_s=round(assembly_s, 6),
                        h2d_stage_s=(
                            round(h2d_stage_s, 6)
                            if h2d_stage_s is not None else None
                        ),
                        dispatch_s=(
                            round(dispatch_s, 6)
                            if dispatch_s is not None else None
                        ),
                        compiled=compiling,
                        k_steps=getattr(scorer, "k_steps", 1),
                        param_dtype=getattr(scorer, "param_dtype", "f32"),
                        mesh_slice=sl,
                        device_label=scorer.device_label,
                        trace_id=self._flush_trace_id(seqs_cat),
                        status="error", error=repr(exc),
                    )
            # poison-batch ejection, first strike: the staging set is
            # still intact in this synchronous handler, so the staged
            # bytes can be copied for ONE retry — a transient chip fault
            # recovers the rows scored; a deterministic data fault fails
            # again and ships the batch to the scorer-poison DLQ instead
            # of burning more breaker/failover capacity on it.
            ft = self._family_ft(family)
            retry_rows = None
            if (
                ft.poison_retry
                and moved > 0
                and not self._seqs_already_retried(seqs_cat)
            ):
                retry_rows = self._copy_retry_rows(
                    st, slots_cat, cols_cat, b_lane
                )
            if retry_rows is None:
                # resolve the rows unscored THROUGH the reap FIFO, not
                # inline: an earlier flush of this family may still be
                # in flight, and publishing these batches first would
                # hand a tenant its later batch before its earlier one.
                # The permit stays held until the reaper resolves the
                # entry.
                self._reap_enqueue(_PendingFlush(
                    family, None, taken, moved, False, 0, 0, poisoned=True,
                    rec=err_rec, sl=sl,
                ))
            if (
                self.flightrec is not None
                and breaker is not None
                and breaker.state == "open"
            ):
                # breaker TRIP: freeze the blackbox NOW, with the
                # faulting flush's record (timings + trace_id) already
                # in the ring it snapshots
                self.flightrec.snapshot(
                    f"breaker:{family}", family=family,
                    trace_id=err_rec.get("trace_id") if err_rec else None,
                )
            await self._note_scorer_error(family, sl)
            if retry_rows is not None:
                # the rows leave through the retry dispatch's OWN permit
                # (possibly on another slice) — this flush's permit goes
                # back now, not via a pf resolution
                sem.release()
                # AFTER the failover pacing above: if the fault also
                # crossed the failover threshold, the retry lands on the
                # tenants' NEW slices (where a second failure confirms
                # the data owns the fault); below it, on the original
                # slice (where a second failure stays a chip signal)
                await self._retry_poison(family, sl, retry_rows, taken, exc)
            return moved
        try:
            self._train_tick(family, sl, scorer, engine_cfgs)
        except Exception as exc:  # noqa: BLE001 - a training fault must not
            # leak the inflight permit or strand the step's rows (the
            # scoring step itself succeeded; delivery proceeds below)
            self._record_error("train", exc)
        flops_fn = getattr(scorer, "flops_per_flush", None)
        pf = _PendingFlush(
            family, scores_dev, taken, moved, gathered,
            int(getattr(scores_dev, "nbytes", 0)), plane_nbytes,
            flops=float(flops_fn(b_lane)) if flops_fn is not None else 0.0,
            rec=rec, sketch=sketch_dev, shadow=shadow_dev, sl=sl,
        )
        pf.slot_override = slot_override
        # flush supervision: the completion deadline the reaper races
        # (family p99-derived, floored by flush_deadline_ms; None = off)
        dl = self._flush_deadline_s(family, sl)
        if dl is not None:
            pf.deadline = pf.t_dispatch + dl
            if self._family_ft(family).poison_retry:
                # staged-byte copies for the one-shot poison retry: a
                # TIMED-OUT flush needs them long after the staging set
                # recycled — the price of retry-with-identical-bytes.
                pf.retry_rows = self._copy_retry_rows(
                    st, slots_cat, cols_cat, b_lane
                )
        if not hasattr(scores_dev, "copy_to_host_async"):
            # no async copy available (test doubles): materialize eagerly
            # on the pool so fallback flushes still overlap each other
            pf.ensure_host_future(
                asyncio.get_running_loop(), self._deliver_pool
            )
        self._reap_enqueue(pf)
        return moved

    @staticmethod
    def _copy_retry_rows(
        st, slots_cat: np.ndarray, cols_cat: np.ndarray, b_lane: int
    ) -> tuple:
        """Staged-byte copies for the one-shot poison retry (~6 B/row,
        two vectorized gathers). BOTH capture sites — the dispatch-fault
        handler and the supervised healthy dispatch — go through here so
        the retry-with-identical-bytes guarantee can't silently diverge
        between them."""
        return (
            st.ids[slots_cat, cols_cat].copy(),
            st.vals[slots_cat, cols_cat].astype(np.float32),
            (cols_cat // b_lane).astype(np.int32),
        )

    def _seqs_already_retried(self, seqs: np.ndarray) -> bool:
        """True when any packed batch already spent its ONE poison
        retry — every retry-granting site must consult this, or a batch
        whose rows span multiple flushes gets a retry per flush."""
        return any(
            int(s) in self._retried_seqs
            for s in np.unique(seqs).tolist()
        )

    def _flush_trace_id(self, seqs_cat: np.ndarray) -> Optional[str]:
        """The first packed batch's trace id — links a flight-recorder
        flush record to its GET /api/traces/{id} trace (one flush packs
        many batches; the head batch anchors the join)."""
        if not len(seqs_cat):
            return None
        entry = self._batches.get(int(seqs_cat[0]))
        if entry is None:
            return None
        ctx = getattr(entry[0], "trace_ctx", None)
        return getattr(ctx, "trace_id", None)

    def _reap_enqueue(self, pf: _PendingFlush) -> None:
        """Queue one pending flush (normal or poisoned) for the reaper:
        the single definition of the enqueue protocol — FIFO append,
        gauge refresh, reaper wake."""
        q = self._reap.get(pf.key)
        if q is None:
            q = self._reap[pf.key] = _ReapQueue()
        q.append(pf)
        self._deliver_gauge()
        self._reap_event.set()

    # -- poison-batch ejection ---------------------------------------------
    def _tenants_in_flight(
        self, family: str, sl: int, exclude: Optional[_PendingFlush]
    ) -> set:
        """Tenants with unresolved serve flushes queued on (family,
        slice) — the poison-retry FIFO guard reads this: a cross-slice
        retry for such a tenant could overtake (or be overtaken by) its
        other in-flight batches, so its rows take an ORDERED fallback
        instead."""
        out: set = set()
        for p in self._reap.get((family, sl), ()):
            if p is exclude or p.resolved or p.lane != "serve":
                continue
            for s in np.unique(p.taken[2]).tolist():
                entry = self._batches.get(int(s))
                if entry is not None:
                    out.add(entry[0].tenant)
        return out

    def _enqueue_ordered_unscored(
        self, family: str, sl: int, taken_sel: tuple
    ) -> None:
        """Append one host-only poisoned entry at a slice's FIFO tail
        WITHOUT a permit (``owns_permit=False``): the ordered fallback
        when rows must resolve after that queue's in-flight flushes but
        the caller may BE that queue's resolve task — acquiring there
        deadlocks against the head it is resolving."""
        pf = _PendingFlush(
            family, None, taken_sel, int(len(taken_sel[2])), False, 0, 0,
            poisoned=True, sl=sl,
        )
        pf.owns_permit = False
        self._reap_enqueue(pf)

    async def _retry_poison(
        self, family: str, sl_first: int, retry_rows: tuple, taken: tuple,
        exc: BaseException, inline: bool = False,
        exclude: Optional[_PendingFlush] = None,
    ) -> None:
        """First strike handled: re-dispatch the faulted flush's rows
        ONCE with the same staged host bytes — one solo flush per
        affected tenant, on the tenant's CURRENT placement (stream →
        data-shard routing is placement-independent, so the bytes are
        valid anywhere the tenant lands; after a quarantine/threshold
        failover that IS the failover slice). A second failure on a
        DIFFERENT slice than ``sl_first`` means two chips agreed — the
        data owns the fault and the batches ship to the DLQ
        (``_eject_poison``); a second failure on the SAME chip stays
        chip-attributed (unscored resolve + failover pacing).

        ``inline=True`` marks the resolve-task callers (deadline
        timeout / deliver fault of the queue HEAD, passed as
        ``exclude``): ordered fallbacks there resolve rows directly —
        resolves are sequential per (family, slice), so the head's own
        task runs before every queued entry — and never await a permit
        on the first slice (the head still holds one; waiting would
        deadlock the queue against itself).

        Per-tenant FIFO guard: a tenant with OTHER unresolved serve
        flushes on the first slice does not cross-slice retry at all —
        its rows resolve unscored in order (inline, or an ordered
        permit-less FIFO entry) rather than racing its own in-flight
        batches on two slices."""
        slots_cat, _cols_cat, seqs_cat, rows_cat = taken
        ids_rows, vals_rows, dshards = retry_rows
        uniq = np.unique(seqs_cat).tolist()
        by_tenant: Dict[str, list] = {}
        for s in uniq:
            entry = self._batches.get(int(s))
            if entry is not None:
                by_tenant.setdefault(entry[0].tenant, []).append(int(s))
        busy = self._tenants_in_flight(family, sl_first, exclude)
        for tenant, seq_list in sorted(by_tenant.items()):
            sel = np.isin(seqs_cat, np.asarray(seq_list, np.int64))
            engine = self.engines.get(tenant)
            if (
                not isinstance(engine, TpuInferenceEngine)
                or engine.placement is None
            ):
                # stopped mid-fault: no placement to retry on — resolve
                # unscored (its bus cursor already advanced; per-tenant
                # order is moot for a stopped tenant)
                await self._resolve_rows(
                    seqs_cat[sel], rows_cat[sel], None, family=family
                )
                continue
            p = engine.placement
            if (family, p.shard) in self._quarantined or tenant in busy:
                # capacity-stranded (retrying on a known-sick slice is
                # pointless) or FIFO-guarded (other in-flight batches
                # of this tenant on the first slice): ordered unscored
                # resolution instead of a retry
                if inline:
                    # the head's own resolve task: runs before every
                    # queued entry by construction
                    await self._resolve_rows(
                        seqs_cat[sel], rows_cat[sel], None, family=family
                    )
                else:
                    # FIFO guard outranks the quarantine shortcut: a
                    # busy tenant's rows must queue behind its earlier
                    # in-flight flushes on the FIRST slice even when
                    # its new placement is also quarantined — p.shard's
                    # (likely empty) queue would publish them ahead
                    self._enqueue_ordered_unscored(
                        family,
                        sl_first if tenant in busy else p.shard,
                        tuple(a[sel] for a in taken),
                    )
                continue
            self._retried_seqs.update(seq_list)
            self.metrics.counter("tpu_inference.poison_retries").inc()
            await self._dispatch_retry(
                engine, family, sl_first,
                ids_rows[sel], vals_rows[sel], dshards[sel],
                seqs_cat[sel], rows_cat[sel], exc, inline=inline,
            )

    async def _dispatch_retry(
        self, engine: "TpuInferenceEngine", family: str, sl_first: int,
        ids_r: np.ndarray, vals_r: np.ndarray, dsh: np.ndarray,
        seqs: np.ndarray, rows: np.ndarray, orig_exc: BaseException,
        inline: bool = False,
    ) -> None:
        """One tenant's poison-retry flush: identical bytes, the
        tenant's current (slice, slot), the normal reap FIFO. A second
        dispatch failure here either ejects to the DLQ (different slice
        than the first strike — two chips agreed on the data) or stays
        a chip fault (same slice: unscored resolve through the FIFO,
        breaker + failover pacing — exactly what an un-retried faulted
        flush would have done).

        ``inline=True`` + a retry landing back on ``sl_first`` means
        the caller IS that queue's resolve task with the head's permit
        still held — the retry entry rides permit-less
        (``owns_permit=False``) instead of awaiting a permit the head
        may be the last holder of."""
        p = engine.placement
        sl2, slot2 = p.shard, p.slot
        try:
            scorer = self.scorers.get((family, sl2))
            if scorer is None:
                scorer = self.scorer_for_slice(family, sl2, engine.config)
            mb = engine.config.microbatch
            # stable per-dshard regrouping keeps each lane's rows in
            # their original FIFO order (= the device gather's pack
            # order)
            order = np.argsort(dsh, kind="stable")
            ids_r, vals_r, dsh = ids_r[order], vals_r[order], dsh[order]
            seqs, rows = seqs[order], rows[order]
            lane_counts = np.bincount(
                dsh, minlength=self.mm.n_data_shards
            )
            b_lane = self._pick_bucket(
                int(lane_counts.max()), tuple(mb.buckets), mb.max_batch
            )
            t, d = scorer.n_slots, self.mm.n_data_shards
            ids_st = np.zeros((t, d * b_lane), scorer.ids_np_dtype)
            vals_st = np.zeros((t, d * b_lane), scorer.vals_np_dtype)
            counts = np.zeros((t, d), np.int32)
            cols = np.empty((len(seqs),), np.int32)
            off = 0
            for dd in range(d):
                k = int(lane_counts[dd])
                if not k:
                    continue
                base = dd * b_lane
                ids_st[slot2, base : base + k] = ids_r[off : off + k]
                vals_st[slot2, base : base + k] = vals_r[off : off + k]
                cols[off : off + k] = np.arange(
                    base, base + k, dtype=np.int32
                )
                counts[slot2, dd] = k
                off += k
            slots2 = np.full((len(seqs),), slot2, np.int32)
            taken2 = (slots2, cols, seqs, rows)
        except Exception as exc2:  # noqa: BLE001 - retry infra failed
            # BEFORE dispatch (scorer build on a degraded fleet /
            # staging alloc): chip-attributed, never poison — and the
            # rows must still resolve (unscored, permit-less, through
            # the retry slice's FIFO) or the zero-loss invariant breaks
            self._record_error("poison-retry-setup", exc2)
            for s in np.unique(seqs).tolist():
                self._retried_seqs.discard(int(s))
            pf2 = _PendingFlush(
                family, None,
                (
                    np.full((len(seqs),), slot2, np.int32),
                    np.zeros((len(seqs),), np.int32), seqs, rows,
                ),
                len(seqs), False, 0, 0, poisoned=True, sl=sl2,
            )
            pf2.owns_permit = False
            self._reap_enqueue(pf2)
            await self._note_scorer_error(family, sl2)
            return
        sem = self._inflight_sem((family, sl2))
        own_permit = not (inline and sl2 == sl_first)
        if own_permit:
            await sem.acquire()
        enqueued = False
        try:
            stage = getattr(scorer, "stage_inputs", None)
            staged = (
                stage(ids_st, vals_st, counts) if stage is not None
                else (ids_st, vals_st, counts)
            )
            if self.faultplan is not None:
                # the retry carries its OWN lane so chaos plans can
                # target the second strike deterministically (a "serve"
                # selector would race other tenants' regular flushes on
                # the retry slice for the fault budget)
                self.faultplan.maybe_raise(family, sl2, "retry")
            shape_key = (family, sl2, b_lane)
            if shape_key not in self._seen_shapes:
                self._seen_shapes.add(shape_key)
                self.metrics.counter("tpu_inference.compiles").inc()
            scores_dev = scorer.step_counts(*staged)
            gathered = False
            gather = getattr(scorer, "gather_rows", None)
            if gather is not None and hasattr(scores_dev, "is_ready"):
                scores_dev = gather(scores_dev, staged[2], len(seqs))
                gathered = True
            if self.faultplan is not None:
                scores_dev = self.faultplan.wrap(
                    scores_dev, family, sl2, "retry"
                )
            try:
                scores_dev.copy_to_host_async()
            except Exception:  # noqa: BLE001 - test doubles
                pass
            rec = None
            if self.flightrec is not None:
                rec = self.flightrec.record(
                    "flush", family,
                    lane="serve", retry=True,
                    rows=len(seqs), bucket=b_lane,
                    mesh_slice=sl2,
                    device_label=getattr(scorer, "device_label", "?"),
                    trace_id=self._flush_trace_id(seqs),
                    status="inflight",
                )
            pf = _PendingFlush(
                family, scores_dev, taken2, len(seqs), gathered,
                int(getattr(scores_dev, "nbytes", 0)), 0,
                rec=rec, sl=sl2,
            )
            pf.retried = True
            pf.retry_from = sl_first
            pf.owns_permit = own_permit
            if not gathered:
                pf.slot_override = slot2
            dl = self._flush_deadline_s(family, sl2)
            if dl is not None:
                pf.deadline = pf.t_dispatch + dl
            if not hasattr(scores_dev, "copy_to_host_async"):
                pf.ensure_host_future(
                    asyncio.get_running_loop(), self._deliver_pool
                )
            self._reap_enqueue(pf)
            enqueued = True
        except Exception as exc2:  # noqa: BLE001 - second strike
            self._record_error("poison-retry", exc2)
            if self._poison_confirmed(family, sl2, sl_first):
                # two DIFFERENT chips failed the same staged bytes: the
                # DATA is the fault — eject the batches, keep the tenant
                await self._eject_poison(family, seqs, exc2)
            else:
                # same chip twice (or the retry slice is already known-
                # sick): a chip signal — resolve the rows unscored
                # through the FIFO on the permit we hold, and pace
                # breaker/failover exactly like an un-retried fault
                for s in np.unique(seqs).tolist():
                    self._retried_seqs.discard(int(s))
                breaker = self.breakers.get((family, sl2))
                if breaker is not None:
                    breaker.record_failure()
                pf2 = _PendingFlush(
                    family, None, taken2, len(seqs), False, 0, 0,
                    poisoned=True, sl=sl2,
                )
                pf2.owns_permit = own_permit
                self._reap_enqueue(pf2)
                enqueued = True  # the poisoned entry inherits the permit
                await self._note_scorer_error(family, sl2)
        finally:
            if own_permit and not enqueued:
                sem.release()

    def _poison_confirmed(
        self, family: str, sl_retry: int, sl_first: int
    ) -> bool:
        """Is a retry failure DATA-attributable? Only when the second
        strike ran on a different slice than the first (two independent
        chips) and that slice isn't itself already suspect — a parked
        family or quarantined retry slice means the fleet, not the
        batch, is sick."""
        return (
            sl_retry != sl_first
            and family not in self._parked
            and (family, sl_retry) not in self._quarantined
        )

    async def _eject_poison(
        self, family: str, seqs: np.ndarray, error: BaseException
    ) -> int:
        """Second strike: attribute the fault to the data. Each affected
        batch leaves the scoring pipeline for its tenant's
        ``scorer-poison`` dead-letter topic (trace-linked, requeue-able
        over the existing DLQ REST surface) and its registry entry is
        popped so no later resolve can publish it — exactly-once
        accounting moves the batch from 'store' to 'DLQ'. The tenant
        keeps serving: no breaker outcome, no failover pacing."""
        from sitewhere_tpu.runtime.bus import RetryingConsumer

        uniq = sorted({int(s) for s in np.asarray(seqs).tolist()})
        ejected = 0
        consumers: Dict[str, RetryingConsumer] = {}
        for s in uniq:
            entry = self._batches.pop(s, None)
            self._retried_seqs.discard(s)
            if entry is None:
                continue
            batch = entry[0]
            rc = consumers.get(batch.tenant)
            if rc is None:
                rc = consumers[batch.tenant] = RetryingConsumer(
                    self.bus, batch.tenant, "scorer-poison", self.group,
                    metrics=self.metrics, tracer=self.tracer,
                )
            await rc.dead_letter(
                batch, self.bus.naming.inbound_events(batch.tenant),
                attempts=2, error=error,
            )
            ejected += 1
            self.metrics.counter("tpu_inference.poison_ejected").inc()
            if self.flightrec is not None:
                self.flightrec.record(
                    "poison", family,
                    tenant=batch.tenant, seq=s, rows=batch.n,
                    error=repr(error),
                )
        return ejected

    # -- auto-failover ----------------------------------------------------
    async def _note_scorer_error(self, family: str, sl: int = 0) -> None:
        """Count consecutive scorer failures per (family, mesh-slice);
        at the threshold, rebuild the SICK SLICE's scorer runtime (a
        failed dispatch can invalidate the donated state buffer) and
        fail that slice's tenants over to DIFFERENT mesh shards
        (reference analog: tenant engines restarting on another replica
        after repeated probe failures [U]) — healthy slices keep
        serving untouched. Repeated rounds without a healthy delivery
        PARK the family: events pass through unscored rather than
        churning failovers forever — degraded, never lost."""
        n = self._consec_errors.get((family, sl), 0) + 1
        self._consec_errors[(family, sl)] = n
        if n < self.failover_threshold or family in self._parked:
            return
        self._consec_errors[(family, sl)] = 0
        rounds = self._failover_rounds.get(family, 0) + 1
        self._failover_rounds[family] = rounds
        if rounds > self.max_failover_rounds:
            self._parked.add(family)
            self._record_error(
                "park", RuntimeError(
                    f"family '{family}' parked after {rounds - 1} failover "
                    f"rounds; events pass through unscored"
                ),
            )
            self.metrics.counter("tpu_inference.parked").inc()
            return
        # may reference dead buffers
        self._last_scores.pop((family, sl), None)
        scorer = self.scorers.get((family, sl))
        if scorer is not None:
            try:
                scorer.rebuild_runtime()
                # the rebuilt jit cache recompiles every shape: reset the
                # slice's seen-shape set so the compile counter stays true
                self._seen_shapes = {
                    k for k in self._seen_shapes if k[:2] != (family, sl)
                }
            except Exception as exc:  # noqa: BLE001 - device may be gone
                self._record_error("rebuild", exc)
        # SUSPECT: quarantine the slice (router avoids it, tenants fail
        # over off it, probation probes re-admit it once it heals) —
        # failed-over tenants RETURN to a healed slice instead of the
        # pre-supervision one-way door
        await self._quarantine_slice(family, sl, reason="scorer-errors")

    # -- quarantine & probation (slice re-adoption) ------------------------
    async def _quarantine_slice(
        self, family: str, sl: int, reason: str
    ) -> None:
        """Mark one (family, mesh-slice) SUSPECT: the router routes
        around it, its tenants fail over to healthy slices (those that
        can't — fleet at capacity — degrade to unscored pass-through on
        the quarantined slice), and a background probe re-dispatches
        synthetic flushes until ``probation_probes`` consecutive
        landings re-admit it. Idempotent per (family, slice)."""
        key = (family, sl)
        if key in self._quarantined:
            return
        ft = self._family_ft(family)
        self._quarantined[key] = {
            "reason": reason,
            "since_ms": time.time() * 1000.0,
            "ok_probes": 0,
            "next_probe": time.monotonic() + ft.probe_interval_s,
        }
        self.metrics.counter("tpu_inference.quarantined").inc()
        self.metrics.gauge("tpu_inference_quarantined_slices").set(
            len(self._quarantined)
        )
        self.router.quarantine(family, sl)
        if self.flightrec is not None:
            self.flightrec.record(
                "quarantine", family,
                event="quarantine", mesh_slice=sl, reason=reason,
            )
        moved = 0
        stranded = 0
        for tenant, engine in list(self.engines.items()):
            if (
                isinstance(engine, TpuInferenceEngine)
                and engine.placement is not None
                and engine.config.model == family
                and engine.placement.shard == sl
            ):
                if engine.placement.slot < 0:
                    # paged-out tenant on the quarantined slice: its
                    # weights are host-side encoded bytes — failing over
                    # means re-pointing the ghost at a healthy slice, NO
                    # device touch (router.quarantine above already
                    # steers the eventual page-in's place() call)
                    engine.placement = self._ghost_placement(
                        engine.tenant, family
                    )
                    self.metrics.counter(
                        "tpu_paging.quarantine_ghosts", family=family
                    ).inc()
                    moved += 1
                    continue
                if await self._failover_tenant(engine):
                    moved += 1
                else:
                    stranded += 1
        if stranded and not moved:
            healthy = [
                s2 for s2 in range(self.router.n_shards)
                if (family, s2) in self.scorers
                and s2 not in self.router.quarantined(family)
            ]
            if not healthy:
                # every serving slice of the family is quarantined and
                # no tenant could move: that IS the park condition —
                # events pass through unscored family-wide, and either
                # probation (slice heals) or a tenant lifecycle event
                # (operator) unparks
                self._parked.add(family)
                self._record_error(
                    "park", RuntimeError(
                        f"family '{family}' parked: every serving slice "
                        f"quarantined and no failover capacity"
                    ),
                )
                self.metrics.counter("tpu_inference.parked").inc()

    def clear_quarantine(self, family: str) -> int:
        """Re-admit every quarantined slice of ``family`` without
        probation — the operator-lifecycle escape hatch (engine
        (re)start), mirroring the breaker resets it rides beside."""
        n = 0
        for key in [k for k in self._quarantined if k[0] == family]:
            self._quarantined.pop(key, None)
            self.router.readmit(family, key[1])
            task = self._probing.pop(key, None)
            if task is not None:
                task.cancel()
            n += 1
        if n:
            self.metrics.gauge("tpu_inference_quarantined_slices").set(
                len(self._quarantined)
            )
        return n

    async def host_probe(self, n: int = 1) -> int:
        """HOST-probation probes (docs/ROBUSTNESS.md "Host fault
        domains"): land ``n`` synthetic zero-row flushes through the
        real wire and report how many made deadline. A host re-appearing
        after a lease fence calls this and carries the count in its
        heartbeat (``probes_ok``); the coordinator's ``HostSupervisor``
        readmits the host only once the count clears its
        ``probation_probes`` bar — the process-level mirror of
        ``_probe_slice``. Each probe rides the first serving slice (the
        cheapest proof the whole staging→step→gather wire answers); a
        host with no serving state yet trivially passes — there is
        nothing to be wedged."""
        ok = 0
        for _ in range(max(1, int(n))):
            landed = not self.scorers
            for (family, sl), scorer in sorted(self.scorers.items()):
                try:
                    landed = await self._dispatch_probe(scorer, family, sl)
                except asyncio.CancelledError:
                    raise
                except Exception as exc:  # noqa: BLE001 - a probe fault
                    # IS the verdict, never a crash
                    self._record_error("host-probe", exc)
                    landed = False
                break
            if landed:
                ok += 1
                self.metrics.counter("tpu_inference.host_probes_ok").inc()
            else:
                self.metrics.counter(
                    "tpu_inference.host_probe_failures"
                ).inc()
        return ok

    def _probe_quarantined(self) -> None:
        """Scoring-loop tick: launch (at most one per slice) probation
        probes for quarantined slices whose probe interval elapsed.
        Probes defer while live traffic is under overload pressure —
        recovery bookkeeping never contends with shedding traffic."""
        if not self._quarantined:
            return
        now = time.monotonic()
        for key, qs in list(self._quarantined.items()):
            if key in self._probing or now < qs["next_probe"]:
                continue
            if self.overload is not None and self.overload.any_pressure():
                qs["next_probe"] = now + self._family_ft(
                    key[0]
                ).probe_interval_s
                continue
            task = asyncio.get_running_loop().create_task(
                self._probe_slice(key)
            )
            self._probing[key] = task

            def _done(t: asyncio.Task, k=key) -> None:
                if self._probing.get(k) is t:
                    del self._probing[k]
                if not t.cancelled() and t.exception() is not None:
                    self._record_error("probe", t.exception())

            task.add_done_callback(_done)

    async def _probe_slice(self, key: Tuple[str, int]) -> None:
        """One probation probe: a synthetic prewarmed-shape flush on the
        quarantined slice, supervised by its own deadline. N consecutive
        landings re-admit the slice; any failure restarts the count."""
        family, sl = key
        ft = self._family_ft(family)
        scorer = self.scorers.get(key)
        ok = False
        if scorer is not None:
            try:
                ok = await self._dispatch_probe(scorer, family, sl)
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 - a probe fault IS
                # the verdict, never a crash
                self._record_error("probe", exc)
                ok = False
        qs = self._quarantined.get(key)
        if qs is None:
            return  # re-admitted/cleared while the probe was in flight
        if ok:
            qs["ok_probes"] += 1
            self.metrics.counter("tpu_inference.probe_flushes").inc()
            if qs["ok_probes"] >= max(1, ft.probation_probes):
                await self._readmit_slice(family, sl)
                return
        else:
            qs["ok_probes"] = 0
            self.metrics.counter("tpu_inference.probe_failures").inc()
        qs["next_probe"] = time.monotonic() + ft.probe_interval_s

    def _probe_executor(self):
        """The dedicated single-thread probe pool. Probes materialize
        against a possibly GENUINELY wedged chip — a blocked np.asarray
        there never returns, and running it on the shared deliver pool
        would leak one worker per timed-out probe until the pool
        starved HEALTHY slices' deliveries (the fleet-wide wedge this
        layer exists to prevent). One dedicated thread bounds the
        damage: a stuck probe blocks only later probes, which queue
        behind it and time out as failures."""
        pool = getattr(self, "_probe_pool", None)
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor

            pool = self._probe_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tpu-probe"
            )
        return pool

    async def _dispatch_probe(
        self, scorer, family: str, sl: int
    ) -> bool:
        """Run one zero-row synthetic flush through the REAL wire
        (staging → step → gather → materialization) with its own
        deadline, entirely ON the probe thread — a quarantined slice's
        jit cache may have been wiped by the failover rebuild, and the
        recompile (tens of seconds on a real chip) must stall the probe
        thread, never the scoring loop. Zero counts leave window state
        untouched (scatter mode=drop — the prewarm contract), so
        probing a quarantined slice cannot corrupt anything a returning
        tenant would see."""
        import numpy as _np

        t, d = scorer.n_slots, scorer.mm.n_data_shards
        # smallest shape the slice already compiled; a wiped cache
        # (failover rebuild) recompiles on the probe thread
        seen = sorted(
            k[2] for k in self._seen_shapes
            if k[:2] == (family, sl) and isinstance(k[2], int)
        )
        b = seen[0] if seen else 64
        ids = _np.zeros((t, d * b), scorer.ids_np_dtype)
        vals = _np.zeros((t, d * b), scorer.vals_np_dtype)
        counts = _np.zeros((t, d), _np.int32)
        plan = self.faultplan

        def _probe_flush():
            stage = getattr(scorer, "stage_inputs", None)
            staged = (
                stage(ids, vals, counts) if stage else (ids, vals, counts)
            )
            if plan is not None:
                plan.maybe_raise(family, sl, "probe")
            out = scorer.step_counts(*staged)
            gather = getattr(scorer, "gather_rows", None)
            if gather is not None and hasattr(out, "is_ready"):
                out = gather(out, staged[2], 1)
            if plan is not None:
                out = plan.wrap(out, family, sl, "probe")
            return np.asarray(out)

        deadline = self._flush_deadline_s(family, sl) or (
            self.deliver_drain_timeout_s
        )
        fut = asyncio.get_running_loop().run_in_executor(
            self._probe_executor(), _probe_flush
        )
        try:
            await asyncio.wait_for(fut, timeout=deadline)
        except asyncio.TimeoutError:
            # NOT tpu_flush_timeout_total: no in-flight flush was
            # force-resolved (that counter's contract) — the caller's
            # probe_failures counter carries this outcome
            return False
        return True

    async def _readmit_slice(self, family: str, sl: int) -> None:
        """Probation passed: the slice rejoins the router, its breaker
        and escalation history clear, the family unparks, and tenants
        REBALANCE BACK through the same FIFO-preserving fences every
        slice move rides."""
        key = (family, sl)
        self._quarantined.pop(key, None)
        self.metrics.gauge("tpu_inference_quarantined_slices").set(
            len(self._quarantined)
        )
        self.router.readmit(family, sl)
        self._consec_errors.pop(key, None)
        self._failover_rounds.pop(family, None)
        self._parked.discard(family)
        breaker = self.breakers.get(key)
        if breaker is not None:
            breaker.reset()
        self.metrics.counter("tpu_inference.readmitted").inc()
        if self.flightrec is not None:
            self.flightrec.record(
                "quarantine", family, event="readmit", mesh_slice=sl,
            )
        # capacity self-heal: tenants displaced by the quarantine come
        # home (load-gap-driven, so a balanced fleet moves nothing)
        await self.apply_rebalance(family)

    async def _failover_tenant(self, engine: "TpuInferenceEngine") -> bool:
        """Re-place one tenant onto another shard (usually a different
        MESH SLICE): carry its params (live copy if the old slice still
        answers, else last checkpoint, else pristine), wipe + free the
        old slot, and move pending rows through a ``_SliceFence`` so
        per-tenant delivery order survives the move. Stream →
        data-shard assignments are placement-independent, so no rows
        and no window routing are lost (window HISTORY restarts on the
        new slice, as before)."""
        from sitewhere_tpu.parallel.tenant_router import PlacementError

        tenant = engine.tenant
        try:
            old_p = engine.placement
            new_p = self.router.failover(tenant)
        except PlacementError as exc:
            self._record_error("failover", exc)
            return False
        await self._apply_move(engine, old_p, new_p)
        self.metrics.counter("tpu_inference.failovers").inc()
        return True

    async def _apply_move(
        self, engine: "TpuInferenceEngine", old_p, new_p
    ) -> None:
        """Migrate one tenant's live serving state between placements —
        the shared mechanics of failover and rebalance. The router has
        ALREADY committed ``new_p``."""
        from sitewhere_tpu.runtime.checkpoint import host_copy_params

        tenant = engine.tenant
        family = engine.config.model
        old_scorer = self.scorers.get((family, old_p.shard))
        params = None
        if old_scorer is not None:
            try:  # live params may be unreachable on a sick slice
                params = host_copy_params(old_scorer.slot_params(old_p.slot))
            except Exception:  # noqa: BLE001
                if self.checkpoints is not None:
                    try:
                        params = (
                            await asyncio.get_running_loop().run_in_executor(
                                None, self.checkpoints.load_params,
                                tenant, family,
                            )
                        )
                    except Exception as exc:  # noqa: BLE001
                        self._record_error("failover-params", exc)
            try:
                old_scorer.reset_slot(old_p.slot)
            except Exception as exc:  # noqa: BLE001 - slice may be dead
                self._record_error("failover-reset", exc)
        # the tenant's pending TRAIN rows stay keyed to the OLD
        # (slot, data-shard): drop them (droppable history — the store
        # re-feeds) or the next tenant placed on that slot would train
        # on THIS tenant's replayed data; its cadence tick goes with it
        # (a recycled slot must not inherit a mature tick either)
        tl = self._train_lanes.get((family, old_p.shard))
        if tl is not None:
            for key in [k for k in tl if k[0] == old_p.slot]:
                tl.pop(key)
            self._train_rows_gauge(family, old_p.shard)
        self._train_ticks.get((family, old_p.shard), {}).pop(
            old_p.slot, None
        )
        engine.placement = new_p
        new_scorer = self.scorer_for_slice(family, new_p.shard, engine.config)
        new_scorer.activate(
            new_p.slot, params=params,
            trainable=engine.config.training.enabled,
            lr=engine.config.training.lr,
        )
        # slot re-map only: the model didn't change, so the drift
        # reference survives the move (register keeps same-family
        # history — see ScoreHealth.register)
        self.scorehealth.register(
            tenant, family, new_p.slot,
            getattr(new_scorer, "sketch_edges", []),
            mesh_slice=new_p.shard,
        )
        self._begin_fence(engine, old_p, new_p)

    def _begin_fence(self, engine: "TpuInferenceEngine", old_p, new_p) -> None:
        """Start (or re-target) the tenant's slice-move fence: snapshot
        the OLD slice queue's in-flight flushes and park the tenant's
        pending lane rows behind them. Same-slice moves (the old
        single-slice failover shape) need no ordering fence — rows
        re-key directly."""
        tenant = engine.tenant
        family = engine.config.model
        fence = self._fences.get(tenant)
        if fence is not None:
            # a second move before the first fence lifted: rows are
            # already parked and the ORIGINAL old-slice snapshot still
            # gates them — only the landing target changes
            fence.new_sl, fence.new_slot = new_p.shard, new_p.slot
            return
        old_lanes = self._lanes.get((family, old_p.shard), {})
        pending = list(self._reap.get((family, old_p.shard), ()))
        if old_p.shard == new_p.shard:
            # same-slice slot move: FIFO is already guaranteed by the
            # single slice queue — re-key lanes in place
            for d in range(self.mm.n_data_shards):
                lane = old_lanes.pop((old_p.slot, d), None)
                if lane is not None and lane.count:
                    dst = old_lanes.get((new_p.slot, d))
                    if dst is None:
                        old_lanes[(new_p.slot, d)] = lane
                    else:
                        li, lv, ls, lr = lane.pop(lane.count)
                        dst.push(li, lv, ls, lr)
            return
        self.metrics.counter("tpu_inference.slice_moves").inc()
        fence = _SliceFence(
            tenant, family, pending, new_p.shard, new_p.slot
        )
        for d in range(self.mm.n_data_shards):
            lane = old_lanes.pop((old_p.slot, d), None)
            if lane is not None and lane.count:
                li, lv, ls, lr = lane.pop(lane.count)
                fence.park(d, li, lv, ls, lr)
        if not pending and not fence.depth():
            return  # nothing in flight, nothing parked — no fence needed
        self._fences[tenant] = fence
        self.metrics.gauge("tpu_inference_fences").set(len(self._fences))

    def _lift_fences(self) -> None:
        """Release every fence whose old-slice snapshot has fully
        resolved: parked rows push into the NEW slice's lanes in arrival
        order. Driven from the scoring loop (cheap no-op while no move
        is in flight)."""
        for tenant in list(self._fences):
            fence = self._fences[tenant]
            if fence.new_sl is None:
                # paging fence: the tenant is non-resident — rows stay
                # parked until a page-in retargets the fence at the
                # landed (slice, slot); only _page_in lifts it
                continue
            if not fence.ready():
                continue
            del self._fences[tenant]
            lanes = self._lanes.get((fence.family, fence.new_sl))
            if lanes is None:
                lanes = self._lanes[(fence.family, fence.new_sl)] = {}
            moved = 0
            for d, ring in sorted(fence.stash.items()):
                if not ring.count:
                    continue
                li, lv, ls, lr = ring.pop(ring.count)
                dst = lanes.get((fence.new_slot, d))
                if dst is None:
                    dst = lanes[(fence.new_slot, d)] = _LaneRing(
                        max(64, ring.capacity)
                    )
                dst.push(li, lv, ls, lr)
                moved += len(ls)
            if moved:
                key = (fence.family, fence.new_sl)
                if key not in self._first_pending_ts:
                    self._first_pending_ts[key] = time.monotonic()
        self.metrics.gauge("tpu_inference_fences").set(len(self._fences))

    async def apply_rebalance(self, family: Optional[str] = None) -> int:
        """Router-planned load rebalance (tenant add/remove skew):
        apply each move through the same fenced migration as failover —
        per-tenant FIFO delivery holds across every slice move. Returns
        the number of tenants moved."""
        moves = self.router.rebalance(family)
        applied = 0
        for old_p, new_p in moves:
            engine = self.engines.get(old_p.tenant)
            if engine is None or not isinstance(engine, TpuInferenceEngine):
                continue
            await self._apply_move(engine, old_p, new_p)
            applied += 1
            self.metrics.counter("tpu_inference.rebalanced").inc()
        return applied

    # -- weight paging (runtime.paging; docs/PERFORMANCE.md) ---------------
    def _ghost_placement(
        self, tenant: str, family: str
    ) -> TenantPlacement:
        """A slot=-1 placement for a non-resident tenant: the shard is
        a real serving slice of the family (preferring healthy ones) so
        stream→data-shard routing and fence parking have a home, but no
        physical slot is held — a page-in claims one later."""
        slices = sorted(s for (f, s) in self.scorers if f == family)
        avoid = self.router.quarantined(family)
        healthy = [s for s in slices if s not in avoid]
        shard = (healthy or slices or [0])[0]
        return TenantPlacement(tenant, family, shard, -1)

    def _install_paging_fence(self, engine: "TpuInferenceEngine") -> None:
        """Park every row a non-resident tenant receives: a paging
        fence (``new_sl=None``) with an EMPTY old-slice snapshot —
        nothing gates it but the page-in that retargets it at the
        landed (slice, slot). Parked depth counts against the lane
        watermark, so a long page-in backpressures intake into the bus
        instead of buffering unboundedly host-side."""
        if engine.tenant in self._fences:
            return
        self._fences[engine.tenant] = _SliceFence(
            engine.tenant, engine.config.model, [], None, None
        )
        self.metrics.gauge("tpu_inference_fences").set(len(self._fences))

    def _page_out(self, engine: "TpuInferenceEngine") -> None:
        """Evict one RESIDENT tenant to the host byte cache and leave a
        ghost placement behind. Synchronous on the event loop — the
        whole evict→write-back→commit runs without an await, so no
        flush can interleave with a half-freed slot (the commit section
        tools/check_commit.py guards: ``host_copy_params`` …
        ``commit_page_out``)."""
        from sitewhere_tpu.runtime.checkpoint import (
            encode_segment, host_copy_params,
        )

        p = engine.placement
        tenant = engine.tenant
        family = engine.config.model
        scorer = self.scorers[(family, p.shard)]
        trainable = bool(engine.config.training.enabled)
        cached = self.pager.cache.get(tenant)
        if not trainable and cached is not None:
            # clean write-back elided: a non-trainable tenant's weights
            # cannot have diverged from the blob its last page-in used
            blob, dirty = cached[0], False
        else:
            # materialize on THIS (loop) thread: reset_slot below
            # donates the stacked buffers (see host_copy_params)
            params = host_copy_params(scorer.slot_params(p.slot))
            opt = scorer.slot_opt_state(p.slot)
            blob = encode_segment(params, opt)
            dirty = trainable
        scorer.reset_slot(p.slot)
        # pending TRAIN rows are droppable history (the store re-feeds —
        # PR 12 round-4 rule), but COUNTED: a paging storm that starves
        # training must be visible
        tl = self._train_lanes.get((family, p.shard))
        if tl is not None:
            dropped = 0
            for key in [k for k in tl if k[0] == p.slot]:
                dropped += tl.pop(key).count
            if dropped:
                self.metrics.counter(
                    "tpu_paging.train_rows_dropped", family=family
                ).inc(dropped)
            self._train_rows_gauge(family, p.shard)
        self._train_ticks.get((family, p.shard), {}).pop(p.slot, None)
        # serve rows still pending re-park behind a paging fence, FIFO
        # behind the old slice's in-flight flushes — the same ordering
        # machinery as a failover move, targetless until the next
        # page-in lands
        fence = self._fences.get(tenant)
        if fence is None:
            fence = self._fences[tenant] = _SliceFence(
                tenant, family,
                list(self._reap.get((family, p.shard), ())), None, None,
            )
            self.metrics.gauge(
                "tpu_inference_fences"
            ).set(len(self._fences))
        else:
            fence.new_sl, fence.new_slot = None, None
        lanes = self._lanes.get((family, p.shard), {})
        for d in range(self.mm.n_data_shards):
            lane = lanes.pop((p.slot, d), None)
            if lane is not None and lane.count:
                li, lv, ls, lr = lane.pop(lane.count)
                fence.park(d, li, lv, ls, lr)
                # eviction raced these batches' rows: key them out of the
                # hot-path latency columns like any fence-parked arrival
                for seq in np.unique(ls):
                    entry = self._batches.get(int(seq))
                    if entry is not None and "paged" not in entry[0].trace:
                        entry[0].mark("paged")
        # score-health: free the slot binding WITHOUT touching the
        # frozen reference or PSI window history — they survive
        # residency gaps exactly like failover re-maps
        self.scorehealth.unbind_slot(tenant)
        self.router.remove(tenant)
        engine.placement = TenantPlacement(
            tenant, family, p.shard, -1, generation=p.generation + 1
        )
        self.pager.slice_pager(
            family, p.shard, self.slots_per_shard
        ).drop(tenant)
        self.pager.cache.commit_page_out(tenant, blob, dirty)
        self.metrics.counter("tpu_paging.page_outs", family=family).inc()
        if self.flightrec is not None:
            self.flightrec.record(
                "paging", family, paged=True, event="page_out",
                tenant=tenant, mesh_slice=p.shard, slot=p.slot,
                dirty=dirty,
            )

    def _pick_victim(
        self, family: str
    ) -> Optional["TpuInferenceEngine"]:
        """The cheapest resident tenant of ``family`` to evict: LRU
        weighted by the OverloadController's live traffic signal.
        Pinned, fenced (mid-move), quarantined-slice, and already-ghost
        tenants are exempt. Tenants with rows already packed in serve
        lanes rank BEHIND row-free ones regardless of LRU score:
        evicting them parks those rows behind the paging fence for a
        full page-out/page-in cycle — hot-path latency spent on a tenant
        that is demonstrably still serving (used only when every
        candidate has pending rows: a demand page-in must not stall)."""
        if self.overload is not None:
            traffic = self.overload.tenant_lag
        else:
            def traffic(_t: str) -> float:
                return 0.0
        now = time.monotonic()
        best = busy_best = None
        best_score = busy_score = -1.0
        for (fam, sl), pager in self.pager.pagers.items():
            if fam != family or (fam, sl) in self._quarantined:
                continue
            lanes = self._lanes.get((fam, sl), {})
            for tenant in pager.residents():
                if tenant in pager.pinned or tenant in self._fences:
                    continue
                eng = self.engines.get(tenant)
                if (
                    not isinstance(eng, TpuInferenceEngine)
                    or eng.state is not LifecycleState.STARTED
                    or eng.placement is None
                    or eng.placement.slot < 0
                ):
                    continue
                score = pager.eviction_score(tenant, traffic, now)
                slot = eng.placement.slot
                pending = any(
                    ring.count for (s, _d), ring in lanes.items()
                    if s == slot
                )
                if pending:
                    if score > busy_score:
                        busy_score, busy_best = score, eng
                elif score > best_score:
                    best_score, best = score, eng
        return best if best is not None else busy_best

    async def _page_in(
        self, tenant: str, origin: str, t_req: float
    ) -> None:
        """Activate one non-resident tenant: claim a slot (evicting the
        LRU victim if the family is at physical capacity), stage its
        cached params asynchronously onto the slice's shardings
        (``stage_slot_params`` — the stage_inputs double-buffer pattern
        for weights), then activate + restore opt state and retarget
        the paging fence so parked rows drain FIFO into the new slot."""
        engine = self.engines.get(tenant)
        if (
            not isinstance(engine, TpuInferenceEngine)
            or engine.state is not LifecycleState.STARTED
            or engine.placement is None
            or engine.placement.slot >= 0
        ):
            return  # stopped / already resident: request is stale
        family = engine.config.model
        try:
            new_p = self.router.place(tenant, family=family)
        except PlacementError:
            victim = self._pick_victim(family)
            if victim is None:
                # every resident is pinned/fenced/quarantined — the
                # request re-queues on the tenant's next demand touch
                self.metrics.counter(
                    "tpu_paging.stalled", family=family
                ).inc()
                return
            self._page_out(victim)
            new_p = self.router.place(tenant, family=family)
        scorer = self.scorer_for_slice(family, new_p.shard, engine.config)
        loop = asyncio.get_running_loop()
        params = opt = None
        entry = self.pager.cache.get(tenant)
        if entry is not None:
            from sitewhere_tpu.runtime.checkpoint import decode_segment

            params, opt = await loop.run_in_executor(
                None, decode_segment, entry[0]
            )
        elif self.checkpoints is not None:
            params = await loop.run_in_executor(
                None, self.checkpoints.load_params, tenant, family
            )
        staged = (
            scorer.stage_slot_params(params) if params is not None else None
        )
        if (
            self.engines.get(tenant) is not engine
            or engine.state is not LifecycleState.STARTED
            or engine.placement is None
            or engine.placement.slot >= 0
        ):
            # the tenant stopped (or somehow activated) during the
            # decode/stage awaits: release the slot we claimed
            self.router.remove(tenant)
            return
        scorer.activate(
            new_p.slot, params=staged,
            trainable=engine.config.training.enabled,
            lr=engine.config.training.lr,
        )
        scorer.restore_slot_opt(new_p.slot, opt)
        engine.placement = new_p
        # slot re-map only: same-family register keeps the frozen drift
        # reference and PSI window history — NO rebaseline (the whole
        # point of surviving page-out like a failover re-map)
        self.scorehealth.register(
            tenant, family, new_p.slot,
            getattr(scorer, "sketch_edges", []),
            mesh_slice=new_p.shard,
        )
        self.pager.slice_pager(
            family, new_p.shard, self.slots_per_shard
        ).note_resident(tenant, new_p.slot)
        fence = self._fences.get(tenant)
        if fence is not None and fence.new_sl is None:
            # retarget: _lift_fences releases it once the snapshot (if
            # any) resolves, draining parked rows FIFO into the slot
            fence.new_sl, fence.new_slot = new_p.shard, new_p.slot
        wait_ms = (time.monotonic() - t_req) * 1e3
        self.metrics.histogram(
            "tenant_activation_ms", unit="ms", family=family
        ).record(wait_ms)
        self.pager.note_activation(tenant, wait_ms, origin)
        self.metrics.counter(
            "tpu_paging.page_ins", family=family, origin=origin
        ).inc()
        if self.flightrec is not None:
            self.flightrec.record(
                "paging", family, paged=True, event="page_in",
                tenant=tenant, origin=origin,
                wait_ms=round(wait_ms, 3),
                mesh_slice=new_p.shard, slot=new_p.slot,
            )

    def _paging_tick(self) -> None:
        """One scoring-loop pass of paging work: (a) queue prefetches
        for ghost tenants whose bus lag is RISING (the
        OverloadController's lag_prev comparison — pressure building
        before any row is consumed), (b) re-demand tenants whose paging
        fence holds parked rows — rows parked at EVICTION time precede
        any future arrival, so without this they'd strand until the
        tenant happens to get new traffic (arrival-side demand pushes
        only fire in ``_enqueue_batch``), (c) launch at most ONE page-in
        task (activation mutates the stacked buffers; serializing keeps
        it off the flush critical path and race-free)."""
        now = time.monotonic()
        if self.overload is not None and now >= self._paging_next_prefetch:
            self._paging_next_prefetch = now + 0.25
            for tenant in self.overload.rising_tenants():
                eng = self.engines.get(tenant)
                if (
                    isinstance(eng, TpuInferenceEngine)
                    and eng.state is LifecycleState.STARTED
                    and eng.placement is not None
                    and eng.placement.slot < 0
                ):
                    self.pager.queue.push(tenant, "prefetch", now)
        for tenant, fence in self._fences.items():
            if fence.new_sl is None and fence.depth():
                self.pager.queue.push(tenant, "demand", now)
        task = self._pagein_task
        if task is not None and not task.done():
            return
        self._pagein_task = None
        req = self.pager.queue.pop()
        if req is None:
            return
        task = asyncio.get_running_loop().create_task(
            self._page_in(*req)
        )
        self._pagein_task = task

        def _done(t: asyncio.Task, _tenant: str = req[0]) -> None:
            if t.cancelled():
                return
            exc = t.exception()
            if exc is not None:
                self._record_error(f"page-in:{_tenant}", exc)

        task.add_done_callback(_done)

    def _train_tick(
        self, family: str, sl: int, scorer: ShardedScorer,
        engine_cfgs: Dict[int, TenantEngineConfig],
    ) -> int:
        """Per-flush training cadence bookkeeping, two regimes:

        - **inline** slots (the pre-lane path — ``TRAIN_LANE_ENABLED``
          off, a non-fused family, or ``training.train_lane=False``):
          every Nth scoring flush dispatches ONE legacy optimizer step
          for the mature slots on their resident window state, right
          here on the flush path — bitwise the pre-lane behavior.
        - **lane** slots: the tick only ACCUMULATES; maturity is checked
          (and reset) by ``_train_lane_tick`` at dispatch, off the flush
          critical path, so a throttled slot keeps its mature tick until
          the overload arbiter admits it.

        Either way the jit dispatch is async and tenants with training
        disabled are excluded by the scorer's per-slot train mask."""
        enabled = {
            slot: c.training
            for slot, c in engine_cfgs.items()
            if c.training.enabled
        }
        if not enabled:
            return 0
        if getattr(scorer.spec, "loss", None) is None:
            # a tenant opted into training on a family with no loss
            # contract: it would silently never train — surface it
            self.metrics.counter(
                "tpu_train_skipped_total", family=family, reason="no_trainer"
            ).inc()
            return 0
        lane_on = bool(getattr(scorer, "train_lane", False))
        # per-TENANT cadence: each slot matures on its own every_n_flushes
        # (and trains at its own lr — see ShardedScorer.slot_lr)
        ticks = self._train_ticks.setdefault((family, sl), {})
        mature = []
        for slot, tc in enabled.items():
            if lane_on and tc.train_lane:
                ticks[slot] = ticks.get(slot, 0) + 1
                continue
            n = ticks.get(slot, 0) + 1
            if n >= tc.every_n_flushes:
                mature.append(slot)
                ticks[slot] = 0
            else:
                ticks[slot] = n
        if not mature:
            return 0
        if getattr(scorer, "_train", None) is None:
            try:
                scorer.init_optimizer()  # scale_by_adam + per-slot lr
            except Exception:
                self.metrics.counter(
                    "tpu_train_skipped_total", family=family,
                    reason="optimizer_init",
                ).inc()
                raise
        mask = np.zeros((scorer.n_slots,), bool)
        mask[mature] = True
        self.last_train_losses[(family, sl)] = scorer.train_resident(mask)
        self.metrics.counter("tpu_inference.train_steps").inc()
        if (
            getattr(scorer, "train_lane", False)
            and self._lane_swap.get((family, sl), 0) > 0
        ):
            # MIXED stack (inline + lane tenants): train_resident just
            # invalidated the shared sidecar, which publishes the lane
            # tenants' in-flight uncommitted weights to serving too —
            # that IS a commit, so it must arm the canary and count as
            # a swap instead of silently bypassing the swap contract
            self._lane_swap[(family, sl)] = 0
            scorer.arm_canary()
            self.metrics.counter(
                "tpu_train_swaps_total", family=family
            ).inc()
            if self.flightrec is not None:
                self.flightrec.record(
                    "swap", family,
                    lane="train", mesh_slice=sl,
                    device_label=scorer.device_label,
                    inline=True,
                    canary_armed=bool(scorer.canary_active()),
                )
        return 1

    # -- continual-learning train lane ------------------------------------
    def _train_admit(self, tenant: str) -> bool:
        """The serve/train arbitration: a tenant's training is admitted
        only while live traffic leaves headroom — i.e. the tenant shows
        NO overload signal (full credit, no degradation rung: the one
        shared ``under_pressure`` definition, so the shed gates and the
        train lane can never disagree about what pressure means). Live
        traffic always wins; the hostile-tenant chaos suite pins this
        at exactly 0 train steps under sustained pressure."""
        ov = self.overload
        return ov is None or not ov.under_pressure(tenant)

    async def _consume_train_feed(
        self, tenant: str, engine: "TpuInferenceEngine"
    ) -> None:
        """Low-priority intake from the tenant's replay-train-feed topic
        into the train lane rings. Bounded: past the lane watermark
        (2 × replay_microbatch) the consumer parks and the backlog stays
        in the bus topic (counted; the replay pump's own overload
        arbitration already throttles the producer). A throttled tenant
        (credit < 1 / rung engaged) doesn't pull either — its feed waits
        out the pressure. The feed topic is EXCLUDED from the overload
        credit signal (runtime.overload._tenant_lag), so a parked train
        backlog can never throttle the tenant's serve path."""
        family = engine.config.model
        sl = engine.placement.shard
        scorer = self.scorers.get((family, sl))
        if scorer is None or not getattr(scorer, "train_lane", False):
            return
        if not self._train_admit(tenant):
            return
        pin = self._family_cfg.get(family, engine.config).training
        micro = max(1, int(getattr(pin, "replay_microbatch", 1024)))
        tlanes = self._train_lanes.setdefault((family, sl), {})
        slot = engine.placement.slot
        depth = sum(
            r.count for (s, _d), r in tlanes.items() if s == slot
        )
        if depth >= 2 * micro:
            self.metrics.counter(
                "tpu_inference.train_feed_backpressure"
            ).inc()
            return
        items = await self.bus.consume(
            self.bus.naming.train_feed(tenant), self.group,
            self.poll_batch, timeout_s=0,
        )
        if not items:
            return
        if (
            engine.state is not LifecycleState.STARTED
            or engine.placement is None
        ):
            return  # stopped mid-consume: training rows are droppable
        for b in items:
            if isinstance(b, MeasurementBatch):
                self._enqueue_train_batch(engine, b, tlanes)
        self._train_rows_gauge(family, sl)

    def _enqueue_train_batch(
        self, engine: "TpuInferenceEngine", batch: MeasurementBatch,
        tlanes: Dict[Tuple[int, int], _TrainLaneRing],
    ) -> None:
        """Route one replayed batch's rows into the train lane rings —
        the train twin of ``_enqueue_batch``, minus every delivery
        obligation: no seq registry, no score column, no publish (the
        rows are already persisted history; training is their only
        consumer). Stream routing shares the tenant's serve
        StreamRegistry, so a replayed row's window lands in the SAME
        (slot, data-shard, local-id) ring position its live twin would."""
        slot = engine.placement.slot
        dshards, locals_ = engine.streams.lookup_or_assign_bulk(batch)
        skipped = int((dshards == -1).sum())
        if skipped:
            self.metrics.counter(
                "tpu_train_skipped_total",
                family=engine.config.model, reason="capacity",
            ).inc(skipped)
        for d in range(self.mm.n_data_shards):
            sel = np.nonzero(dshards == d)[0]
            if sel.size == 0:
                continue
            lane = tlanes.get((slot, d))
            if lane is None:
                lane = tlanes[(slot, d)] = _TrainLaneRing(4096)
            # seq/row bookkeeping is vestigial on the train lane (rows
            # never resolve back into a batch) — seq broadcasts 0
            lane.push(locals_[sel], batch.values[sel], 0, sel)

    def _train_rows_gauge(self, family: str, _sl: int = 0) -> None:
        # the gauge is FAMILY-labeled, so it must sum every slice's
        # rings — a per-slice sum would let slices of one family
        # overwrite each other's depth (the last_train_losses keying
        # lesson from the multi-chip review, applied to the gauge)
        depth = sum(
            r.count
            for (f, _s), lanes in self._train_lanes.items()
            if f == family
            for r in lanes.values()
        )
        self.metrics.gauge("tpu_inference_train_rows", family=family).set(
            depth
        )

    async def _train_lane_tick(
        self, fam_cfgs: Dict[Tuple[str, int], Dict[int, TenantEngineConfig]]
    ) -> int:
        """One pass of the async low-priority train lane: for each
        (family, slice) whose scorer carries the fused lane, dispatch at
        most ONE train step — replay-fed when an admitted microbatch is
        buffered, else resident-state when a slot's cadence matured —
        and only when the slice has a FREE in-flight permit right now
        (``sem.locked()`` ⇒ the serve path owns every slot: a saturated
        slice trains exactly 0 steps) and the overload arbiter admits
        the tenant. The dispatch rides the slice's semaphore + reap FIFO
        as ``lane="train"``, so its completion, teardown drain, and
        queue-depth accounting are the serve path's own machinery."""
        steps = 0
        for (family, sl), cfgs in fam_cfgs.items():
            scorer = self.scorers.get((family, sl))
            if scorer is None or not getattr(scorer, "train_lane", False):
                continue
            lane_cfgs = {
                s: c for s, c in cfgs.items()
                if c.training.enabled and c.training.train_lane
            }
            if not lane_cfgs:
                continue
            if family in self._parked:
                self.metrics.counter(
                    "tpu_train_skipped_total", family=family,
                    reason="parked",
                ).inc()
                continue
            pin = self._family_cfg.get(
                family, next(iter(lane_cfgs.values()))
            ).training
            micro = max(1, int(getattr(pin, "replay_microbatch", 1024)))
            admitted = {
                s: c for s, c in lane_cfgs.items()
                if self._train_admit(c.tenant)
            }
            throttled = len(lane_cfgs) - len(admitted)
            if not admitted:
                if throttled:
                    self.metrics.counter(
                        "tpu_train_skipped_total", family=family,
                        reason="throttled",
                    ).inc(throttled)
                continue
            ticks = self._train_ticks.get((family, sl), {})
            tlanes = self._train_lanes.get((family, sl), {})
            feed_rows = sum(
                r.count for (s, _d), r in tlanes.items() if s in admitted
            )
            mature = [
                s for s, c in admitted.items()
                if ticks.get(s, 0) >= c.training.every_n_flushes
            ]
            replay = feed_rows >= micro
            if not replay and not mature:
                continue
            if replay and mature and (
                self._lane_last_source.get((family, sl)) == "replay"
            ):
                # both sources pending: ALTERNATE. A long replay
                # backfill holding feed_rows ≥ micro for hours must not
                # starve a co-tenant's mature resident cadence (the
                # mature slot is admitted but never fed, so no skip
                # counter would ever name its starvation)
                replay = False
            if throttled:
                # mature-but-throttled siblings sat this dispatch out
                self.metrics.counter(
                    "tpu_train_skipped_total", family=family,
                    reason="throttled",
                ).inc(throttled)
            sem = self._inflight_sem((family, sl))
            q = self._reap.get((family, sl))
            if sem.locked() or (q and any(p.lane != "train" for p in q)):
                # the slice is busy SERVING — in-flight flushes hold the
                # window (or every permit): training yields and waits
                # for a genuinely idle gap. "Idle headroom" is literal:
                # a train step only ever enters an EMPTY in-flight
                # window, so a saturated slice trains exactly 0 steps
                # and a serve flush never queues behind a train step it
                # could have preceded.
                self.metrics.counter(
                    "tpu_train_skipped_total", family=family,
                    reason="saturated",
                ).inc()
                continue
            if q:
                # only the lane's OWN previous step is in flight: lane
                # steps self-serialize per slice — normal pacing, not
                # starvation, so it must not pollute the "saturated"
                # signal operators read as serve pressure
                continue
            steps += await self._dispatch_train(
                family, sl, scorer, admitted, mature, replay, pin,
            )
        return steps

    def _pack_train(
        self, family: str, sl: int, scorer, admitted: Dict[int, object],
    ) -> Tuple[int, List[int]]:
        """Pack the admitted slots' pending train rows into a rotating
        staging set (the SAME per-slice pool and wire dtypes as scoring
        flushes), stage them h2d, and scatter them into the scorer's
        train feed windows. Returns (rows moved, slots that contributed
        rows — the only slots the replay step may train: an admitted
        co-tenant with an empty feed must not take a zero-gradient Adam
        step, which would drift its weights on stale momentum and skew
        its bias-correction count). The ingest dispatch is async and
        precedes the train step on the device queue."""
        tlanes = self._train_lanes.get((family, sl), {})
        mbcfg = self._family_cfg[family].microbatch
        pending = max(
            (r.count for (s, _d), r in tlanes.items() if s in admitted),
            default=0,
        )
        if pending == 0:
            return 0, []
        b_lane = self._pick_bucket(
            pending, tuple(mbcfg.buckets), mbcfg.max_batch
        )
        scratch = self._train_scratch
        if scratch is None or len(scratch[0]) < b_lane:
            # pop_into needs seqs/rows landing zones; train rows never
            # resolve, so one reusable scratch pair serves every pack
            scratch = self._train_scratch = (
                np.empty((max(b_lane, mbcfg.max_batch),), np.int64),
                np.empty((max(b_lane, mbcfg.max_batch),), np.int32),
            )
        sc_seqs, sc_rows = scratch
        st = self._staging_set(family, sl, scorer, b_lane)
        ids, vals, counts = st.ids, st.vals, st.counts
        counts[:] = 0
        moved = 0
        fed: set = set()
        for (slot, dshard), lane in sorted(tlanes.items()):
            if slot not in admitted:
                continue
            k = min(lane.count, b_lane)
            if k == 0:
                continue
            lane.pop_into(
                k, ids[slot], vals[slot], dshard * b_lane,
                sc_seqs, sc_rows, 0,
            )
            counts[slot, dshard] = k
            fed.add(slot)
            moved += k
        self._train_rows_gauge(family, sl)
        if moved == 0:
            return 0, []
        staged = scorer.stage_inputs(ids, vals, counts)
        st.staged = staged
        try:
            self.metrics.counter("tpu_inference.staged_bytes").inc(
                scorer.stage_nbytes(staged)
            )
        except Exception:  # noqa: BLE001 - observability only
            pass
        scorer.train_feed_ingest(*staged)
        self.metrics.counter(
            "tpu_train_rows_total", family=family
        ).inc(moved)
        return moved, sorted(fed)

    async def _dispatch_train(
        self, family: str, sl: int, scorer, admitted: Dict[int, object],
        mature: List[int], replay: bool, pin,
    ) -> int:
        """Dispatch one train-lane step and enqueue its completion on the
        slice's reap FIFO. The permit is held until the reaper resolves
        the entry — train steps count against the slice's in-flight
        window exactly like flushes, which is what keeps them off the
        serve critical path (a full window defers training, never
        scoring)."""
        sem = self._inflight_sem((family, sl))
        # locked() was False with no await since: acquire returns now
        await sem.acquire()
        enqueued = False
        try:
            if getattr(scorer, "_train_fused", None) is None:
                try:
                    scorer.init_optimizer()
                except Exception as exc:  # noqa: BLE001 - optimizer
                    # construction is config-driven; surface, don't die
                    self._record_error("train-init", exc)
                    self.metrics.counter(
                        "tpu_train_skipped_total", family=family,
                        reason="optimizer_init",
                    ).inc()
                    return 0
            shape_key = (family, sl, "train")
            compiling = shape_key not in self._seen_shapes
            rows_moved = 0
            source = "resident"
            ticks = self._train_ticks.setdefault((family, sl), {})
            if replay:
                source = "replay"
                rows_moved, trained = self._pack_train(
                    family, sl, scorer, admitted
                )
            else:
                trained = sorted(mature)
            # EVERY trained slot's cadence resets — a replay step IS the
            # slot's training for this interval, so a feed oscillating
            # around the microbatch threshold must not double the
            # configured cadence with a back-to-back resident step
            for s in trained:
                ticks[s] = 0
            if not trained:
                return 0
            self._lane_last_source[(family, sl)] = source
            mask = np.zeros((scorer.n_slots,), bool)
            mask[trained] = True
            if self.faultplan is not None:
                self.faultplan.maybe_raise(family, sl, "train")
            t_disp = time.perf_counter()
            losses_dev = scorer.train_lane_step(mask, replay=replay)
            dispatch_s = time.perf_counter() - t_disp
            if self.faultplan is not None:
                # the train lane is a supervised fault domain too: a
                # hung train step must not wedge the slice's in-flight
                # window forever
                losses_dev = self.faultplan.wrap(
                    losses_dev, family, sl, "train"
                )
            try:
                losses_dev.copy_to_host_async()
            except Exception:  # noqa: BLE001 - test doubles
                pass
            if compiling:
                self._seen_shapes.add(shape_key)
                self.metrics.counter("tpu_inference.compiles").inc()
            self.metrics.counter("tpu_inference.train_steps").inc()
            for s in trained:
                self.metrics.counter(
                    "tpu_train_steps_total", tenant=admitted[s].tenant
                ).inc()
            # zero-stall hot-swap cadence: every swap_every lane steps
            # the master weights commit to the serving kernel view (the
            # activate(params=...) tail — sidecar re-derive + canary
            # arm); between commits scoring runs the previous weights
            swaps = self._lane_swap.get((family, sl), 0) + 1
            swap_every = max(1, int(getattr(pin, "swap_every", 8)))
            if swaps >= swap_every:
                swaps = 0
                scorer.commit_swap()
                self.metrics.counter(
                    "tpu_train_swaps_total", family=family
                ).inc()
                if self.flightrec is not None:
                    self.flightrec.record(
                        "swap", family,
                        lane="train", mesh_slice=sl,
                        device_label=scorer.device_label,
                        steps=swap_every,
                        canary_armed=bool(scorer.canary_active()),
                    )
            self._lane_swap[(family, sl)] = swaps
            rec = None
            if self.flightrec is not None:
                rec = self.flightrec.record(
                    "flush", family,
                    lane="train", source=source,
                    rows=rows_moved, slots=len(trained),
                    dispatch_s=round(dispatch_s, 6),
                    compiled=compiling,
                    mesh_slice=sl,
                    device_label=scorer.device_label,
                    status="inflight",
                )
            flops_fn = getattr(scorer, "train_flops_per_step", None)
            pf = _PendingFlush(
                family, losses_dev, _empty_taken(), 0, False,
                int(getattr(losses_dev, "nbytes", 0)), 0,
                flops=float(flops_fn()) if flops_fn is not None else 0.0,
                rec=rec, sl=sl, lane="train",
            )
            dl = self._flush_deadline_s(family, sl)
            if dl is not None:
                pf.deadline = pf.t_dispatch + dl
            if not hasattr(losses_dev, "copy_to_host_async"):
                pf.ensure_host_future(
                    asyncio.get_running_loop(), self._deliver_pool
                )
            self._reap_enqueue(pf)
            enqueued = True
            return 1
        except Exception as exc:  # noqa: BLE001 - the train lane is
            # best-effort: a faulting step must not take serving down
            # (the serve path's own flushes drive breaker/failover if
            # the device is truly sick)
            self._record_error("train", exc)
            return 0
        finally:
            if not enqueued:
                sem.release()

    def _deliver_gauge(self) -> None:
        self.metrics.gauge("tpu_inference_deliver_inflight").set(
            sum(len(q) for q in self._reap.values())
        )
        # labeled variants beside the legacy aggregate: the reap queues
        # are PER-(family, slice), so per-family depth is where a wedged
        # tenant family shows and per-DEVICE depth is where one slow
        # chip shows (the aggregate hides both). Separate names —
        # mixing bare and labeled children under one name would
        # double-count sum() aggregations.
        fam_depth: Dict[str, int] = {}
        dev_depth: Dict[str, int] = {}
        multi = self.mm.n_devices > 1
        for (family, sl), q in self._reap.items():
            fam_depth[family] = fam_depth.get(family, 0) + len(q)
            if multi:
                lbl = self.mm.slice_device_label(sl)
                dev_depth[lbl] = dev_depth.get(lbl, 0) + len(q)
        for family, depth in fam_depth.items():
            self.metrics.gauge(
                "tpu_inference_deliver_inflight_family", family=family
            ).set(depth)
        for lbl, depth in dev_depth.items():
            self.metrics.gauge(
                "tpu_inference_deliver_inflight_device", device=lbl
            ).set(depth)

    # -- device-time / MFU attribution -----------------------------------
    def _mfu_account(self, family: str):
        acc = self._mfu.get(family)
        if acc is None:
            from sitewhere_tpu.runtime.metrics import MfuAccount

            acc = self._mfu[family] = MfuAccount(self.metrics, family)
        return acc

    def _mfu_device_account(self, family: str, sl: int):
        """Per-(family, mesh-slice) MFU account under the DEVICE-labeled
        names (MfuAccount.DEVICE_NAMES): chip-level utilization so an
        idle or skewed slice is visible instead of averaged away by the
        family aggregate. Cardinality is mesh-bounded."""
        acc = self._mfu_dev.get((family, sl))
        if acc is None:
            from sitewhere_tpu.runtime.metrics import MfuAccount

            f_name, s_name, g_name = MfuAccount.DEVICE_NAMES
            acc = self._mfu_dev[(family, sl)] = MfuAccount(
                self.metrics, family,
                flops_name=f_name, secs_name=s_name, gauge_name=g_name,
                device=self.mm.slice_device_label(sl),
            )
        return acc

    def refresh_mfu(self) -> None:
        """Decay idle families' ``tpu_mfu_pct`` gauges from the sliding
        window (called by the instance's 1 s history tick and the
        /metrics scrape — a family that stopped flushing must read 0,
        not its last busy value)."""
        for acc in self._mfu.values():
            acc.refresh()
        for acc in self._mfu_dev.values():
            acc.refresh()
        # same tick drives the score-health time-based window rotation:
        # a slow stream must still rotate its drift windows instead of
        # waiting hours to fill window_rows
        self.scorehealth.refresh()

    async def _reap_loop(self) -> None:
        """The completion reaper: resolve in-flight flushes as their d2h
        transfers land. Heads that look complete (``landed`` — a cheap
        priority signal) dispatch first; when several families are in
        flight and none does, the reaper waits on ALL their heads and
        takes whichever finishes first — out of order across families,
        strictly FIFO within one (a tenant lives in exactly one family,
        so its batches deliver in order). The reaper itself only WAITS —
        each landed head resolves in a per-family task
        (``_spawn_resolve``), so one tenant's backpressured scored-topic
        publish can't head-of-line block other families' landed
        transfers. Overlap accounting happens at materialize time in
        ``_resolve_flush``: only a transfer whose materialization
        returned without measurable wait (and that the reaper never
        raced on) counts as ``d2h_overlapped``."""
        loop = asyncio.get_running_loop()
        while True:
            # a family with a resolve in flight is ineligible: its next
            # head must wait its turn (per-tenant FIFO)
            heads = [
                q[0] for f, q in self._reap.items()
                if q and f not in self._resolving
            ]
            if not heads:
                # clear-then-wait is race-free on the single-threaded
                # loop: any set() that mattered already showed in heads
                self._reap_event.clear()
                await self._reap_event.wait()
                continue
            # landed heads resolve first; an OVERDUE head (its flush
            # deadline expired without the transfer landing) resolves
            # too — _resolve_flush's bounded wait turns it into the
            # force-resolve + quarantine path within one grace tick
            pf = next(
                (h for h in heads if h.landed() or h.overdue()), None
            )
            if pf is not None:
                self._spawn_resolve(pf)
                continue
            # no head has landed: race every eligible family's head (plus
            # the enqueue/resolve-done event — a NEW family's flush must
            # be able to join the race and win, or one family's slow
            # transfer would head-of-line block every other family — and
            # a timer for the SOONEST flush deadline, so a transfer that
            # never lands wakes the supervisor instead of parking it)
            self._reap_event.clear()
            waiter = asyncio.ensure_future(self._reap_event.wait())
            now = time.perf_counter()
            soonest = min(
                (h.deadline for h in heads if h.deadline is not None),
                default=None,
            )
            timer = (
                asyncio.ensure_future(
                    asyncio.sleep(max(0.0, soonest - now))
                )
                if soonest is not None
                else None
            )
            futs = []
            for h in heads:
                if h.t_wait is None:
                    h.t_wait = now
                # one future per in-flight FAMILY (a handful), not per row
                futs.append(h.ensure_host_future(loop, self._deliver_pool))  # hotpath: ok
            try:
                await asyncio.wait(  # supervised: ok(flush-deadline timer races in futs)
                    [*futs, waiter]
                    + ([timer] if timer is not None else []),
                    return_when=asyncio.FIRST_COMPLETED,
                )
            finally:
                waiter.cancel()
                if timer is not None:
                    timer.cancel()
            pf = next((h for h, f in zip(heads, futs) if f.done()), None)
            if pf is not None:
                self._spawn_resolve(pf)

    def _spawn_resolve(self, pf: _PendingFlush) -> None:
        """Resolve one landed flush in a per-family task. At most one
        resolve runs per family (the loop skips families in
        ``_resolving``), which preserves per-tenant in-order delivery;
        separate tasks restore the cross-family isolation the old
        per-flush deliver tasks had — a full scored topic only stalls
        its own family, and only until ``max_inflight`` backpressures
        the scoring loop as a whole."""
        task = asyncio.get_running_loop().create_task(
            self._resolve_flush(pf)
        )
        self._resolving[pf.key] = task

        def _done(t: asyncio.Task, key: Tuple[str, int] = pf.key) -> None:
            if self._resolving.get(key) is t:
                del self._resolving[key]
            if not t.cancelled() and t.exception() is not None:
                # _resolve_flush handles its own failures; anything
                # escaping would otherwise vanish with the task
                self._record_error("deliver", t.exception())
            # wake the reaper: this family's next head is eligible now
            self._reap_event.set()

        task.add_done_callback(_done)

    # the honest boundary for the d2h_overlapped counter, since jax has
    # no "host copy done" probe — shared with the media readback (see
    # runtime/metrics.py for the rationale)
    D2H_OVERLAP_EPS_S = _D2H_OVERLAP_EPS_S

    # top-k size for the canary's rank-agreement verdict: the rows an
    # alerting/thresholding consumer actually acts on are the highest
    # scores, so rank stability there matters more than mean delta
    CANARY_TOPK = 64

    def _canary_compare(
        self, pf: _PendingFlush, picks: np.ndarray, shadow_np: np.ndarray
    ) -> None:
        """Divergence of the serving scores vs the shadow (previous
        variant) scores for one flush — one shared verdict definition
        (``scorehealth.canary_divergence``, also the bench's canary
        columns); results land in ``score_canary_*`` and the flush's
        blackbox record."""
        from sitewhere_tpu.runtime.scorehealth import canary_divergence

        sp = shadow_np[: pf.moved].astype(np.float32, copy=False)
        verdict = canary_divergence(picks, sp, self.CANARY_TOPK)
        if verdict is None:
            return
        mean_abs, agree, n = verdict
        self.scorehealth.canary_note(pf.family, mean_abs, agree, n)
        if pf.rec is not None:
            pf.rec["canary_mean_abs_delta"] = round(mean_abs, 6)
            pf.rec["canary_topk_agreement"] = round(agree, 4)

    async def _resolve_flush(self, pf: _PendingFlush) -> None:
        """Materialize one flush's (gathered) scores and resolve its rows.

        Materialization ALWAYS happens off the loop (executor) unless an
        earlier race already produced the host array — ``is_ready`` only
        proves device compute finished, so an inline ``np.asarray`` here
        could still stall the loop for the copy's remaining link time.
        Worker-thread materialization is safe because ``pf.scores`` is a
        jit output nothing ever donates — unlike param trees, whose
        buffers later loop-thread calls donate (see
        ``checkpoint.host_copy_params`` for the full invariant)."""
        _slots, _cols, seqs, rows = pf.taken
        scattered = False  # did the (possibly unscored) write-back start?
        # flush supervision: every materialization await below is bounded
        # by the flush's remaining deadline (None = supervision off). An
        # already-overdue head gets one short grace tick so the timeout
        # path — not a 0s race — decides.
        budget = (
            None if pf.deadline is None
            else max(0.05, pf.deadline - time.perf_counter())
        )
        try:
            if pf.lane == "train":
                # train-lane completion: no rows to resolve — materialize
                # the per-slot loss vector (same executor discipline as
                # scores), publish it to last_train_losses, and attribute
                # the step's device window + FLOPs to the TRAIN families
                # (never the serving MFU account)
                scattered = True  # nothing row-shaped to salvage on cancel
                losses_np, _sk, _sh = await asyncio.wait_for(
                    pf.ensure_host_future(
                        asyncio.get_running_loop(), self._deliver_pool
                    ),
                    timeout=budget,
                )
                now = time.perf_counter()
                self.last_train_losses[pf.key] = losses_np
                device_s = max(0.0, now - pf.t_dispatch)
                # train steps feed the same deadline history as serve
                # flushes (they share the in-flight window): mixing only
                # RAISES the p99-derived deadline — conservative-safe
                self._note_device_s(pf.key, device_s)
                self.metrics.histogram(
                    "tpu_inference.train_step", unit="s"
                ).record(device_s)
                if pf.flops:
                    self.metrics.counter(
                        "tpu_train_flops_total", family=pf.family
                    ).inc(pf.flops)
                if pf.rec is not None:
                    pf.rec["device_s"] = round(device_s, 6)
                    finite = losses_np[np.isfinite(losses_np)]
                    pf.rec["loss_max"] = (
                        round(float(finite.max()), 6) if finite.size else None
                    )
                    pf.rec["status"] = "ok"
                return
            if pf.poisoned:
                # the dispatch itself failed (breaker/failover already
                # recorded at the flush site): no transfer to wait for —
                # resolve the rows unscored, but through this FIFO slot
                # so they can't overtake an earlier in-flight flush
                scattered = True
                await self._resolve_rows(seqs, rows, None, family=pf.family)
                return
            t0 = time.perf_counter()
            scores_np, sketch_np, shadow_np = await asyncio.wait_for(
                pf.ensure_host_future(
                    asyncio.get_running_loop(), self._deliver_pool
                ),
                timeout=budget,
            )
            now = time.perf_counter()
            # cumulative wait: from the FIRST time the reaper waited on
            # this flush (race rounds included), not just the last await
            waited_s = now - pf.t_wait if pf.t_wait is not None else now - t0
            self.metrics.histogram("tpu_inference.d2h_wait", unit="s").record(
                waited_s
            )
            d2h_overlapped = (
                pf.t_wait is None and waited_s < self.D2H_OVERLAP_EPS_S
            )
            if d2h_overlapped:
                # the transfer had fully landed before the reaper asked —
                # it rode under later compute (raced-on heads never count,
                # however fast their future resolved afterwards)
                self.metrics.counter("tpu_inference.d2h_overlapped").inc()
            t1 = time.perf_counter()
            # wire dtype (bf16/f16) widens back to f32 at the batch edge
            if pf.gathered:
                picks = scores_np[: pf.moved].astype(np.float32, copy=False)
            else:
                picks = scores_np[_slots, _cols].astype(np.float32, copy=False)
            # score-quality accounting: per-flush NaN census + the
            # device sketch folded into the tenant drift windows, all
            # vectorized (runtime.scorehealth; nan attribution rides the
            # pack-order slots — one bincount, never a per-row loop)
            nan_mask = np.isnan(picks)
            nan_rows = int(nan_mask.sum())
            if nan_rows:
                self.metrics.counter(
                    "tpu_scores_nan_total", family=pf.family
                ).inc(nan_rows)
            if sketch_np is not None:
                nan_by_slot = None
                if nan_rows:
                    # picks align with the pack-order slots on BOTH the
                    # gathered and full-plane fallback paths; only the
                    # single-slot slice zeroed them (override carries it)
                    if pf.slot_override is not None:
                        nan_by_slot = np.zeros(
                            (sketch_np.shape[0],), np.int64
                        )
                        nan_by_slot[pf.slot_override] = nan_rows
                    else:
                        nan_by_slot = np.bincount(
                            _slots[nan_mask], minlength=sketch_np.shape[0]
                        )
                self.scorehealth.ingest_sketch(
                    pf.family, sketch_np.sum(axis=1), nan_by_slot,
                    mesh_slice=pf.sl,
                )
            if shadow_np is not None:
                self._canary_compare(pf, picks, shadow_np)
            # cancellation past this point observes only INSIDE
            # _resolve_rows' publish loop (the scatter is await-free), so
            # scores are written and counts decremented exactly once —
            # the cancel path below must not resolve a second time
            scattered = True
            await self._resolve_rows(seqs, rows, picks)
            resolve_s = time.perf_counter() - t1
            self.metrics.histogram("tpu_inference.resolve", unit="s").record(
                resolve_s
            )
            self.metrics.counter("tpu_inference.reaped").inc()
            self.metrics.counter("tpu_inference.d2h_bytes").inc(pf.nbytes)
            # device-time / MFU attribution: the dispatch was outstanding
            # from issue until its transfer landed — that window times
            # this flush's executed FLOPs (padded plane; see
            # ShardedScorer.flops_per_flush)
            device_s = max(0.0, now - pf.t_dispatch)
            # ...and the flush supervisor's deadline history: the next
            # flush's deadline tracks this (family, slice)'s observed
            # dispatch→landed p99
            self._note_device_s(pf.key, device_s)
            if pf.flops:
                self._mfu_account(pf.family).record(pf.flops, device_s)
                if self.mm.n_devices > 1:
                    # per-chip utilization beside the family aggregate:
                    # each slice's flushes feed ITS device's account
                    self._mfu_device_account(pf.family, pf.sl).record(
                        pf.flops, device_s
                    )
            d2h_labels = {"family": pf.family}
            if self.mm.n_devices > 1:
                scorer = self.scorers.get(pf.key)
                d2h_labels["device"] = getattr(
                    scorer, "device_label", "device:?"
                )
            self.metrics.counter(
                "tpu_inference_d2h_bytes_total", **d2h_labels
            ).inc(pf.nbytes)
            # complete the family's latency-attribution profile: the
            # inference span annotates with the LAST RESOLVED flush's
            # full sub-stage split (a per-batch approximation; the
            # ledger scales it so it never exceeds the span)
            prof = self._last_flush.get(pf.family)
            if prof is not None:
                prof["flush_device_s"] = round(device_s, 6)
                prof["flush_d2h_wait_s"] = round(waited_s, 6)
                prof["flush_resolve_s"] = round(resolve_s, 6)
            if pf.rec is not None:
                # complete the blackbox record in place (see flightrec)
                pf.rec["d2h_wait_s"] = round(waited_s, 6)
                pf.rec["d2h_overlapped"] = d2h_overlapped
                pf.rec["resolve_s"] = round(resolve_s, 6)
                pf.rec["device_s"] = round(device_s, 6)
                pf.rec["status"] = "ok"
                # score-quality fields: incident snapshots can now see
                # WHAT the flush scored, not just how long it took
                pf.rec["nan_rows"] = nan_rows
                finite = picks[~nan_mask]
                pf.rec["score_p99"] = (
                    round(float(np.quantile(finite, 0.99)), 6)
                    if finite.size else None
                )
            if pf.plane_nbytes:
                # what the pre-gather path would have moved — the bench's
                # d2h_plane_reduction column is this ratio
                self.metrics.counter("tpu_inference.d2h_plane_bytes").inc(
                    pf.plane_nbytes
                )
            self._consec_errors.pop(pf.key, None)  # healthy again
            self._failover_rounds.pop(pf.family, None)
            breaker = self.breakers.get(pf.key)
            if breaker is not None:
                breaker.record_success()
        except asyncio.CancelledError:
            # cancelled mid-flight (forced teardown): the rows were already
            # popped from lanes, so resolve them unscored or they're lost.
            # But ONLY if the real-score pass never ran — re-resolving
            # after it would decrement batch row counts a second time
            # (premature NaN publishes) and overwrite written scores
            if not scattered:
                await self._resolve_rows(
                    seqs, rows, None, publish_nowait=True, family=pf.family
                )
            raise
        except asyncio.TimeoutError:
            # the flush deadline expired with the transfer unlanded: the
            # supervisor's SUSPECT path — force-resolve unscored in this
            # FIFO slot (or retry/eject the rows), trip the breaker,
            # snapshot the blackbox, quarantine the slice
            await self._on_flush_timeout(pf, scattered)
        except Exception as exc:  # noqa: BLE001 - a poisoned transfer
            # must not strand the batches: resolve rows unscored — but
            # only if the write-back never ran (same double-decrement
            # hazard as the cancel path above; a fault AFTER it, e.g. a
            # non-transient publish error, already flushed the remaining
            # completed batches inside _resolve_rows)
            self._record_error("deliver", exc)
            poison = pf.retried and self._poison_confirmed(
                pf.family, pf.sl, pf.retry_from
            )
            if not scattered:
                if poison:
                    # a cross-slice retry faulted AGAIN: two chips
                    # agreed — eject to the scorer-poison DLQ
                    await self._eject_poison(pf.family, seqs, exc)
                elif (
                    pf.retry_rows is not None
                    and not pf.retried
                    and pf.lane != "train"
                    and not self._seqs_already_retried(pf.taken[2])
                ):
                    # first strike at materialize time (late device
                    # error): same one-shot retry as a dispatch fault —
                    # inline because this IS the queue head's resolve
                    # task (its permit is still held; exclude it from
                    # the FIFO guard)
                    await self._retry_poison(
                        pf.family, pf.sl, pf.retry_rows, pf.taken, exc,
                        inline=True, exclude=pf,
                    )
                else:
                    if pf.retried:
                        # same-chip second strike: chip-attributed —
                        # the rows leave unscored, unmarked
                        for s in np.unique(seqs).tolist():
                            self._retried_seqs.discard(int(s))
                    await self._resolve_rows(
                        seqs, rows, None, family=pf.family
                    )
            if pf.rec is not None and not pf.poisoned:
                pf.rec["status"] = "error"
                pf.rec["error"] = repr(exc)
            if not pf.poisoned and not poison and pf.lane != "train":
                # a poisoned flush's dispatch failure was already counted
                # at the flush site — recording it again here would let a
                # downstream bus hiccup double-pace failover/parking;
                # train-lane faults are best-effort and must not pace
                # breaker/failover either (serve flushes own that signal)
                breaker = self.breakers.get(pf.key)
                if breaker is not None:
                    breaker.record_failure()
                    if (
                        self.flightrec is not None
                        and breaker.state == "open"
                    ):
                        self.flightrec.snapshot(
                            f"breaker:{pf.family}", family=pf.family,
                            trace_id=(
                                pf.rec.get("trace_id") if pf.rec else None
                            ),
                        )
                await self._note_scorer_error(pf.family, pf.sl)
        finally:
            # the head leaves the queue only once its resolution is DONE
            # (either way) — queue length and the deliver_inflight gauge
            # honestly count unfinished flushes, the teardown drain
            # can't miss a flush the reaper was cancelled inside, and
            # slice-move fences wait on exactly this flag
            pf.resolved = True
            q = self._reap.get(pf.key)
            if q and q[0] is pf:
                q.popleft()
            self._deliver_gauge()
            if pf.owns_permit:
                self._inflight_sem(pf.key).release()
            if (
                self._last_scores.get(pf.key) is pf.scores
                and not self._reap.get(pf.key)
            ):
                # slice idle: the overlap probe must not pin this
                # flush's device scores until the next (maybe never)
                # flush — by now the probe is ready, so dropping it
                # can't change the next overlap verdict
                self._last_scores.pop(pf.key, None)

    async def _on_flush_timeout(
        self, pf: _PendingFlush, scattered: bool
    ) -> None:
        """One flush blew its completion deadline: the supervisor's
        SUSPECT verdict. The rows force-resolve UNSCORED in this FIFO
        slot (exact PR 5 poisoned-flush semantics — zero loss, per-
        tenant order preserved) unless the poison-retry path takes
        ownership of them; the breaker trips (a hung device yields no
        raised outcome for its window to count), the blackbox freezes,
        and the slice enters quarantine + probation. Runs inside
        ``_resolve_flush``'s try — its ``finally`` still pops the queue
        head and releases the permit exactly once."""
        family, sl = pf.key
        self.metrics.counter(
            "tpu_flush_timeout_total", family=family, slice=str(sl)
        ).inc()
        if pf.rec is not None:
            pf.rec["status"] = "timeout"
        # decide attribution BEFORE quarantining: a confirmed-poison
        # verdict (cross-slice retry that ALSO failed) means the DATA,
        # not this chip, owns the fault — quarantining/tripping the
        # retry slice would churn tenants for a data bug, exactly the
        # capacity drain poison ejection exists to stop
        poison = pf.retried and self._poison_confirmed(
            family, sl, pf.retry_from
        )
        if self.flightrec is not None:
            # evidence first: the snapshot carries the wedged flush's
            # own record (timings, kernel variant, slice, trace_id)
            self.flightrec.snapshot(
                f"flush-timeout:{family}", family=family, mesh_slice=sl,
                lane=pf.lane,
                trace_id=pf.rec.get("trace_id") if pf.rec else None,
            )
        _s, _c, seqs, rows = pf.taken
        err = TimeoutError(f"flush deadline expired ({family}@s{sl})")
        if poison:
            await self._eject_poison(family, seqs, err)
            return
        breaker = self.breakers.get(pf.key)
        if breaker is not None:
            breaker.trip()
        await self._quarantine_slice(family, sl, reason="flush-timeout")
        if scattered or pf.lane == "train":
            return  # no rows to salvage (train) / already written back
        if pf.retried:
            # same-chip (or fleet-sick) second timeout: chip-attributed
            for s in np.unique(seqs).tolist():
                self._retried_seqs.discard(int(s))
            await self._force_resolve(pf)
        elif (
            pf.retry_rows is not None
            and not self._seqs_already_retried(seqs)
        ):
            # first strike: the tenants just failed over (quarantine
            # above) — retry the same staged bytes on their new slices
            # (inline: this runs inside the head's own resolve task)
            await self._retry_poison(
                family, sl, pf.retry_rows, pf.taken, err,
                inline=True, exclude=pf,
            )
        else:
            await self._force_resolve(pf)

    async def _force_resolve(
        self, pf: _PendingFlush, nowait: bool = False
    ) -> None:
        """THE force-resolve accounting path: one pending flush's rows
        resolve unscored (NaN, counted via tpu_scores_unscored_total +
        per-tenant note_unscored inside ``_resolve_rows``). Shared by
        the supervisor's deadline timeout (normal backpressure) and
        service teardown (``nowait`` — the consumer may be gone), so
        the two can never diverge on accounting."""
        _s, _c, seqs, rows = pf.taken
        if pf.lane != "train":
            await self._resolve_rows(
                seqs, rows, None, publish_nowait=nowait, family=pf.family
            )

    # -- legacy object path (low-volume / tests) --------------------------
    async def _enqueue_events(self, engine: TpuInferenceEngine, events: List) -> List:
        """Object events: wrap measurements into a single-row batch each is
        wasteful — instead convert the poll's measurements into one batch."""
        measurements = [e for e in events if isinstance(e, DeviceMeasurement)]
        passthrough = [e for e in events if not isinstance(e, DeviceMeasurement)]
        if measurements:
            batch = MeasurementBatch.from_events(
                measurements, [0] * len(measurements), tenant=engine.tenant
            )
            batch.assignment_tokens = np.asarray(
                [e.assignment_token for e in measurements], object
            )
            batch.area_tokens = np.asarray(
                [e.area_token for e in measurements], object
            )
            await self._enqueue_batch(engine, batch)
        return passthrough

    # -- main loop -------------------------------------------------------
    async def _scoring_loop(self) -> None:
        iters = self.metrics.counter("tpu_inference.loop_iters")
        throttled = self.metrics.counter("tpu_inference.fair_throttled")
        while True:
            iters.inc()
            moved = 0
            fam_cfgs: Dict[str, Dict[int, TenantEngineConfig]] = {}
            # weighted fair queuing: every pass replenishes each tenant's
            # deficit (quantum × weight); a tenant that overdrew sits out
            # until its deficit refills, so sustained intake converges to
            # the weight ratio and a hostile tenant's backlog stays in
            # ITS bus topic (where lag → credit → receiver shed)
            self.fair.replenish()
            if self._fences:
                # slice moves in flight: release any whose old-slice
                # snapshot fully resolved (parked rows re-enter lanes)
                self._lift_fences()
            if self._quarantined:
                # probation: launch due probes for quarantined slices
                # (no-op dict check on the healthy path)
                self._probe_quarantined()
            if self.pager is not None:
                # weight paging: issue prefetches for rising-lag ghost
                # tenants, then service ≤ 1 queued page-in — all device
                # mutation stays OFF the flush critical path
                self._paging_tick()
            for tenant, engine in list(self.engines.items()):
                if engine.state is not LifecycleState.STARTED:
                    continue
                assert isinstance(engine, TpuInferenceEngine)
                if engine.placement is not None and engine.placement.slot >= 0:
                    # register for flush even when throttled below: lanes
                    # already holding this tenant's rows must still drain.
                    # Ghost (paged-out, slot=-1) tenants register nothing:
                    # their rows park behind the paging fence and no slot
                    # of theirs exists to flush or train
                    fam_cfgs.setdefault(
                        (engine.config.model, engine.placement.shard), {}
                    )[engine.placement.slot] = engine.config
                    tc = engine.config.training
                    if tc.enabled and tc.train_lane:
                        # replay-fed continual learning: low-priority
                        # intake from the train feed topic into the
                        # train lane rings (bounded + credit-gated —
                        # never charged against the serve fair budget)
                        await self._consume_train_feed(tenant, engine)
                budget = self.fair.budget(tenant)
                if budget <= 0:
                    throttled.inc()
                    continue
                # per-tenant lane watermark: a slow/contended scorer must
                # backpressure intake into the BUS (where depth is a
                # gauge, lag drives the credit signal, and retention
                # bounds memory) instead of buffering unboundedly in
                # lanes. 2× max_batch keeps the next flush fed.
                lanes_now = self._lanes.get(
                    (engine.config.model, engine.placement.shard), {}
                )
                slot_now = engine.placement.slot
                pending_rows = sum(
                    l.count for (s, _d), l in lanes_now.items()
                    if s == slot_now
                )
                fence_now = self._fences.get(tenant)
                if fence_now is not None:
                    # parked rows count against the watermark: a long
                    # fence must backpressure intake into the bus, not
                    # buffer unboundedly host-side
                    pending_rows += fence_now.depth()
                if pending_rows >= 2 * engine.config.microbatch.max_batch:
                    self.metrics.counter(
                        "tpu_inference.lane_backpressure"
                    ).inc()
                    continue
                # a tenant in deficit debt polls ONE item at a time so
                # the overshoot past its budget is bounded by one batch
                items = await self.bus.consume(
                    self.bus.naming.inbound_events(tenant),
                    self.group,
                    self.poll_batch if budget >= self.fair.quantum else 1,
                    timeout_s=0,
                )
                # the engine can stop DURING the consume await (stop
                # cascade); its cursor already advanced, so resolve the
                # items unscored instead of crashing on a dead placement
                if engine.state is not LifecycleState.STARTED or engine.placement is None:
                    await self._passthrough(
                        self.bus.naming.scored_events(tenant), items
                    )
                    continue
                if not items:
                    continue
                batches = [i for i in items if isinstance(i, MeasurementBatch)]
                objects = [i for i in items if not isinstance(i, MeasurementBatch)]
                self.fair.charge(
                    tenant, sum(b.n for b in batches) + len(objects)
                )
                gate = self._gate(tenant)
                sample_rate = 1.0
                if self.overload is not None and self.overload.degraded(
                    tenant, "sample_inference"
                ):
                    pol = self.overload.policy_for(tenant)
                    sample_rate = pol.inference_sample_rate if pol else 1.0
                for b in batches:
                    if gate.check(b):
                        continue  # expired: never reaches a scorer flush
                    await self._enqueue_batch(engine, b, sample_rate)
                    moved += b.n
                objects = [o for o in objects if not gate.check(o)]
                if objects:
                    passthrough = await self._enqueue_events(engine, objects)
                    topic = self.bus.naming.scored_events(tenant)
                    for ev in passthrough:
                        await publish_at_least_once(
                            self.bus, topic, ev, metrics=self.metrics
                        )
                    moved += len(objects)
            for (family, sl), cfgs in fam_cfgs.items():
                if (family, sl) not in self.scorers:
                    continue
                mb = next(iter(cfgs.values())).microbatch
                lanes = self._lanes[(family, sl)]
                full = any(l.count >= mb.max_batch for l in lanes.values())
                if full or self._deadline_reached((family, sl), mb.deadline_ms):
                    moved += await self._flush_slice(cfgs, family, sl)
            if fam_cfgs:
                # the async train lane runs AFTER serve flushes, off the
                # flush critical path: at most one low-priority train
                # dispatch per (family, slice) per pass, and only into a
                # free in-flight permit (a saturated slice trains 0)
                moved += await self._train_lane_tick(fam_cfgs)
            if moved == 0:
                await asyncio.sleep(0.001)

    async def _passthrough(self, topic: str, items: list) -> None:
        """Forward consumed items downstream unscored. While the service is
        up (e.g. a tenant restart mid-flight) this backpressures like the
        normal path — a lagging persistence consumer must slow us down, not
        have retained batches evicted out from under it. The lossy
        ``publish_nowait`` is reserved for service teardown, when the
        consumer may already be gone and an awaitable publish would never
        unblock. The consume cursor has already advanced past these items,
        so even a cancellation mid-publish must still emit them."""
        pending = list(items)
        try:
            while pending:
                item = pending[0]
                if isinstance(item, MeasurementBatch):
                    item.mark("passthrough_stop")
                if self.state is LifecycleState.STARTED:
                    await publish_at_least_once(
                        self.bus, topic, item, metrics=self.metrics
                    )
                else:
                    self.bus.publish_nowait(topic, item)
                pending.pop(0)
        except asyncio.CancelledError:
            for item in pending:
                if isinstance(item, MeasurementBatch):
                    item.mark("passthrough_stop")
                self.bus.publish_nowait(topic, item)
            raise

    def _deadline_reached(self, key: Tuple[str, int], deadline_ms: float) -> bool:
        first = self._first_pending_ts.get(key)
        return first is not None and (time.monotonic() - first) * 1000.0 >= deadline_ms

    def prewarm(self) -> None:
        """Compile every active family's bucket shapes (see
        ShardedScorer.prewarm). Call after tenants are added, before
        latency-sensitive traffic."""
        # union of every resident engine's bucket sizes per (family,
        # slice): tenants sharing a slice may configure different
        # buckets, and a missed size is a mid-scoring-loop XLA compile
        wanted: Dict[Tuple[str, int], set] = {}
        for tenant, engine in self.engines.items():
            assert isinstance(engine, TpuInferenceEngine)
            if engine.placement is None:
                continue
            key = (engine.config.model, engine.placement.shard)
            mb = engine.config.microbatch
            wanted.setdefault(key, set()).update(
                [min(b, mb.max_batch) for b in mb.buckets] + [mb.max_batch]
            )
        lane_keys: set = set()
        for tenant, engine in self.engines.items():
            assert isinstance(engine, TpuInferenceEngine)
            if engine.placement is None:
                continue
            tc = engine.config.training
            if tc.enabled and tc.train_lane:
                lane_keys.add(
                    (engine.config.model, engine.placement.shard)
                )
        for key, sizes in wanted.items():
            scorer = self.scorers.get(key)
            if scorer is not None:
                scorer.prewarm(sorted(sizes))
                if key in lane_keys and getattr(
                    scorer, "train_lane", False
                ):
                    # the train lane's first step/ingest must not pay a
                    # mid-traffic XLA compile either — same rule as the
                    # scoring shapes above
                    if getattr(scorer, "_train_fused", None) is None:
                        scorer.init_optimizer()
                    scorer.prewarm_train_lane(sorted(sizes))
                    # the lane's executables are compiled now: the first
                    # real dispatch must not report a (false) compile —
                    # that would fire the steady_state_recompile
                    # watchdog the moment a replay train job starts
                    self._seen_shapes.add((key[0], key[1], "train"))

    def params_source(self, tenant: str):
        """A zero-arg callable yielding the tenant's CURRENT slot params
        (live-trained, or checkpoint-restored after a restart) — the
        CEP→TPU bridge binds ModelUdf evaluation to this so rule verdicts
        track the tenant's actual model, never a fresh init. Returns None
        while the tenant has no placement (caller falls back)."""

        def source():
            engine = self.engines.get(tenant)
            if engine is None or engine.placement is None:
                return None
            if engine.placement.slot < 0:
                # paged out: the host byte cache is the source of truth
                # (slot_params(-1) would read ANOTHER tenant's last slot)
                return self._cached_params(tenant)
            scorer = self.scorers.get(
                (engine.config.model, engine.placement.shard)
            )
            if scorer is None:
                return None
            return scorer.slot_params(engine.placement.slot)

        return source

    def _cached_params(self, tenant: str):
        """Decode a paged-out tenant's params from its cache blob (host
        numpy tree) — None when no blob exists (a pristine ghost)."""
        if self.pager is None:
            return None
        entry = self.pager.cache.get(tenant)
        if entry is None:
            return None
        from sitewhere_tpu.runtime.checkpoint import decode_segment

        params, _opt = decode_segment(entry[0])
        return params

    def snapshot_params(self) -> Dict[Tuple[str, str], object]:
        """Live param cut for checkpointing: (tenant, family) → param
        pytree for that tenant's slot. The leaves are jax arrays
        (immutable), so the caller can hand them to an executor thread for
        host transfer + serialization without racing ongoing training."""
        out: Dict[Tuple[str, str], object] = {}
        for tenant, engine in self.engines.items():
            assert isinstance(engine, TpuInferenceEngine)
            if engine.placement is None:
                continue
            if engine.placement.slot < 0:
                # paged out: snapshot from the cache blob, not the
                # device (slot -1 would alias another tenant's slot)
                cached = self._cached_params(tenant)
                if cached is not None:
                    out[(tenant, engine.config.model)] = cached
                continue
            scorer = self.scorers.get(
                (engine.config.model, engine.placement.shard)
            )
            if scorer is None:
                continue
            out[(tenant, engine.config.model)] = scorer.slot_params(
                engine.placement.slot
            )
        return out

    # -- introspection ---------------------------------------------------
    def describe(self) -> dict:
        return {
            "mesh": self.mm.describe(),
            "router": self.router.describe(),
            "quarantined": {
                f"{fam}@{sl}": {
                    k: v for k, v in qs.items() if k != "next_probe"
                }
                for (fam, sl), qs in sorted(self._quarantined.items())
            },
            "families": {
                f"{fam}@{sl}": {
                    "n_slots": s.n_slots,
                    "max_streams": s.max_streams,
                    "device": s.device_label,
                    "train_lane": bool(getattr(s, "train_lane", False)),
                }
                for (fam, sl), s in sorted(self.scorers.items())
            },
            "paging": self.pager.stats() if self.pager is not None else None,
        }
