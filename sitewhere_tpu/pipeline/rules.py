"""Rule processing: Siddhi-equivalent CEP over the enriched event stream.

Capability parity with the reference's service-rule-processing (embedded
Siddhi engine per tenant: stream definitions mapped from event topics,
filter/window/aggregate queries, callbacks re-emitting derived events,
zone-test geofence rules — SURVEY.md §2.2/§5 [U]; reference mount empty,
see provenance banner).

Redesign: rules are Python objects evaluated per event batch — filters are
predicates, windows are per-group-key sliding count/time windows with
numpy aggregation, actions emit derived events (alerts / command
invocations) back into the pipeline. The north-star extension is
``ModelUdf``: a rule action can invoke a TPU-hosted model (forecast or
score) on the window's values — the "Siddhi CEP queries gain a UDF that
invokes TPU-hosted anomaly/forecast models" capability (BASELINE.json
north_star; SURVEY.md §2.3).
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from dataclasses import dataclass, field
from typing import (
    Any,
    Awaitable,
    Callable,
    Deque,
    Dict,
    List,
    Optional,
    Sequence,
    Tuple,
)

import numpy as np

from sitewhere_tpu.core.batch import MeasurementBatch
from sitewhere_tpu.core.events import (
    AlertLevel,
    DeviceAlert,
    DeviceCommandInvocation,
    DeviceEvent,
    DeviceLocation,
    DeviceMeasurement,
    EventType,
)
from sitewhere_tpu.runtime.bus import EventBus, RetryingConsumer
from sitewhere_tpu.runtime.config import FaultTolerancePolicy
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent, cancel_and_wait
from sitewhere_tpu.runtime.metrics import MetricsRegistry

Predicate = Callable[[DeviceEvent], bool]
Action = Callable[[DeviceEvent, Dict[str, Any]], Awaitable[Optional[List[DeviceEvent]]]]

AGGREGATES: Dict[str, Callable[[np.ndarray], float]] = {
    "avg": lambda v: float(np.mean(v)),
    "sum": lambda v: float(np.sum(v)),
    "min": lambda v: float(np.min(v)),
    "max": lambda v: float(np.max(v)),
    "count": lambda v: float(len(v)),
    "std": lambda v: float(np.std(v)),
    "last": lambda v: float(v[-1]),
}

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}


@dataclass
class SlidingWindow:
    """Per-group sliding window: count-bounded and/or time-bounded."""

    length: int = 0          # 0 = unbounded by count
    time_ms: int = 0         # 0 = unbounded by time
    _items: Deque[Tuple[int, float]] = field(default_factory=deque)

    def push(self, ts: int, value: float) -> None:
        self._items.append((ts, value))
        if self.length:
            while len(self._items) > self.length:
                self._items.popleft()
        if self.time_ms:
            cutoff = ts - self.time_ms
            while self._items and self._items[0][0] < cutoff:
                self._items.popleft()

    def values(self) -> np.ndarray:
        return np.asarray([v for _, v in self._items], np.float32)

    def __len__(self) -> int:
        return len(self._items)


@dataclass
class Rule:
    """One CEP query: filter → (optional window+aggregate+having) → action.

    ``group_by`` defaults to per-(device, measurement-name) grouping; the
    windowed aggregate value is passed to ``action`` in the context dict.

    ``vector_where``, when set, is the columnar fast path: it takes a
    ``MeasurementBatch`` and returns a bool row mask of candidate hits;
    only hit rows are materialized into event objects for the (stateful)
    per-event ``evaluate``. Stateless filter rules (threshold, anomaly
    score) provide it; windowed rules fall back to full materialization.
    """

    name: str
    event_type: Optional[EventType] = EventType.MEASUREMENT
    where: Optional[Predicate] = None
    vector_where: Optional[Callable[[Any], np.ndarray]] = None
    window: int = 0
    window_time_ms: int = 0
    aggregate: str = ""                      # key into AGGREGATES
    having: Optional[Callable[[float], bool]] = None
    min_window: int = 1
    group_by: Optional[Callable[[DeviceEvent], str]] = None
    action: Optional[Action] = None
    cooldown_ms: int = 0                     # suppress re-fire per group
    # declares vector_where EXACTLY row-equivalent to the scalar where —
    # enables the engine's cooldown pre-compaction (first hit per group).
    # A rule whose vector_where over-approximates where must leave this
    # False, or non-first rows that where would have accepted get dropped
    vector_exact: bool = False

    _windows: Dict[str, SlidingWindow] = field(default_factory=dict)
    _last_fired: Dict[str, float] = field(default_factory=dict)
    fired: int = 0

    def _group(self, e: DeviceEvent) -> str:
        if self.group_by is not None:
            return self.group_by(e)
        name = getattr(e, "name", "")
        return f"{e.device_token}:{name}"

    async def evaluate(self, e: DeviceEvent) -> Optional[List[DeviceEvent]]:
        if self.event_type is not None and e.EVENT_TYPE is not self.event_type:
            return None
        if self.where is not None and not self.where(e):
            return None
        ctx: Dict[str, Any] = {"rule": self.name}
        if self.window or self.window_time_ms:
            key = self._group(e)
            w = self._windows.get(key)
            if w is None:
                w = self._windows[key] = SlidingWindow(self.window, self.window_time_ms)
            value = float(getattr(e, "value", getattr(e, "score", 0.0)) or 0.0)
            w.push(e.event_ts, value)
            if len(w) < self.min_window:
                return None
            vals = w.values()
            ctx["window_values"] = vals
            if self.aggregate:
                agg = AGGREGATES[self.aggregate](vals)
                ctx["aggregate"] = agg
                if self.having is not None and not self.having(agg):
                    return None
        if self.cooldown_ms:
            key = self._group(e)
            now = time.time() * 1000.0
            if now - self._last_fired.get(key, 0.0) < self.cooldown_ms:
                return None
            self._last_fired[key] = now
        self.fired += 1
        if self.action is None:
            return None
        return await self.action(e, ctx)


# -- built-in rule factories ----------------------------------------------

def alert_action(
    alert_type: str,
    level: AlertLevel = AlertLevel.WARNING,
    message: str = "",
) -> Action:
    async def act(e: DeviceEvent, ctx: Dict[str, Any]):
        agg = ctx.get("aggregate")
        msg = message or f"rule '{ctx['rule']}' fired"
        if agg is not None:
            msg += f" (aggregate={agg:.4f})"
        return [
            DeviceAlert(
                device_token=e.device_token,
                assignment_token=e.assignment_token,
                tenant=e.tenant,
                area_token=e.area_token,
                asset_token=e.asset_token,
                customer_token=e.customer_token,
                source="rule",
                level=level,
                alert_type=alert_type,
                message=msg,
                metadata={"rule": ctx["rule"], "origin_event": e.id},
            )
        ]

    return act


def command_action(command_token: str, parameters: Optional[Dict[str, str]] = None) -> Action:
    async def act(e: DeviceEvent, ctx: Dict[str, Any]):
        return [
            DeviceCommandInvocation(
                device_token=e.device_token,
                assignment_token=e.assignment_token,
                tenant=e.tenant,
                command_token=command_token,
                initiator="rule",
                initiator_id=ctx["rule"],
                parameters=dict(parameters or {}),
            )
        ]

    return act


def threshold_rule(
    name: str,
    measurement: str,
    op: str,
    threshold: float,
    level: AlertLevel = AlertLevel.WARNING,
    alert_type: str = "threshold",
    cooldown_ms: int = 0,
) -> Rule:
    """measurement <op> threshold → alert. The CPU-baseline config's rule
    (BASELINE.json:7)."""
    cmp = _OPS[op]
    _np_ops = {">": np.greater, ">=": np.greater_equal, "<": np.less,
               "<=": np.less_equal, "==": np.equal, "!=": np.not_equal}
    np_cmp = _np_ops[op]

    def vec(batch) -> np.ndarray:
        mask = np_cmp(batch.values, threshold)
        if batch.names is not None:
            mask &= batch.names == measurement
        return mask & batch.valid

    return Rule(
        name=name,
        event_type=EventType.MEASUREMENT,
        where=lambda e: e.name == measurement and cmp(e.value, threshold),  # type: ignore[attr-defined]
        vector_where=vec,
        action=alert_action(alert_type, level, f"{measurement} {op} {threshold}"),
        cooldown_ms=cooldown_ms,
        vector_exact=True,
    )


def anomaly_score_rule(
    name: str,
    min_score: float = 3.0,
    level: AlertLevel = AlertLevel.ERROR,
    cooldown_ms: int = 0,
) -> Rule:
    """TPU anomaly score → alert: the scored-stream consumer rule [B:8]."""

    def vec(batch) -> np.ndarray:
        if batch.scores is None:
            return np.zeros((batch.n,), bool)
        with np.errstate(invalid="ignore"):
            return (batch.scores >= min_score) & batch.valid

    return Rule(
        name=name,
        event_type=EventType.MEASUREMENT,
        where=lambda e: e.score is not None and e.score >= min_score,  # type: ignore[attr-defined]
        vector_where=vec,
        action=alert_action("anomaly", level, "tpu anomaly score"),
        cooldown_ms=cooldown_ms,
        vector_exact=True,
    )


def _point_in_polygon(lat: float, lon: float, poly: Sequence[Tuple[float, float]]) -> bool:
    """Ray casting; poly = [(lat, lon), ...]."""
    inside = False
    n = len(poly)
    for i in range(n):
        la1, lo1 = poly[i]
        la2, lo2 = poly[(i + 1) % n]
        if (lo1 > lon) != (lo2 > lon):
            t = (lon - lo1) / (lo2 - lo1)
            if lat < la1 + t * (la2 - la1):
                inside = not inside
    return inside


def geofence_rule(
    name: str,
    bounds: Sequence[Tuple[float, float]],
    inside: bool = False,
    level: AlertLevel = AlertLevel.WARNING,
    cooldown_ms: int = 0,
) -> Rule:
    """Fire when a DeviceLocation is inside (or outside) a zone polygon —
    the reference's zone-test rules (SURVEY.md §2.2 rule-processing [?])."""

    def where(e: DeviceEvent) -> bool:
        assert isinstance(e, DeviceLocation)
        hit = _point_in_polygon(e.latitude, e.longitude, bounds)
        return hit if inside else not hit

    return Rule(
        name=name,
        event_type=EventType.LOCATION,
        where=where,
        action=alert_action("geofence", level, "zone boundary"),
        cooldown_ms=cooldown_ms,
    )


class ModelUdf:
    """TPU-model UDF callable from rule actions (the north-star CEP↔TPU
    bridge [B:5]): wraps a model-zoo forecaster/scorer; evaluates on the
    rule window's values under jit."""

    def __init__(
        self,
        family: str,
        model_config: Optional[Dict[str, Any]] = None,
        seed: int = 0,
        params_source: Optional[Callable[[], Any]] = None,
    ):
        import jax

        from sitewhere_tpu.models import get_model, make_config

        self.spec = get_model(family)
        self.cfg = make_config(family, model_config)
        self.params = self.spec.init(jax.random.PRNGKey(seed), self.cfg)
        # live binding: evaluate with the tenant's CURRENT slot params
        # (trained/restored) instead of the fresh init above — wire via
        # TpuInferenceService.params_source(tenant)
        self.params_source = params_source
        self._jit_cache: Dict[Tuple[str, int], Callable] = {}
        self._key = jax.random.PRNGKey(seed + 1)

    def bind_params_source(self, source: Callable[[], Any]) -> "ModelUdf":
        self.params_source = source
        return self

    def _live_params(self):
        if self.params_source is not None:
            live = self.params_source()
            if live is not None:
                return live
        return self.params

    def _padded(self, values: np.ndarray, target: int) -> np.ndarray:
        v = values[-target:]
        if len(v) < target:
            v = np.concatenate([np.full(target - len(v), v[0] if len(v) else 0.0, np.float32), v])
        return v.astype(np.float32)

    def forecast(self, values: np.ndarray) -> np.ndarray:
        """values [T] → mean forecast [horizon]."""
        import jax
        import jax.numpy as jnp

        if self.spec.forecast is None:
            raise ValueError(f"model '{self.spec.name}' cannot forecast")
        ctx = getattr(self.cfg, "context", 128)
        fn = self._jit_cache.get(("forecast", ctx))
        if fn is None:
            fn = jax.jit(self.spec.forecast, static_argnums=1)
            self._jit_cache[("forecast", ctx)] = fn
        self._key, sub = jax.random.split(self._key)
        window = jnp.asarray(self._padded(values, ctx))[None]
        _, mean = fn(self._live_params(), self.cfg, window, sub)
        return np.asarray(mean[0])

    def score(self, values: np.ndarray) -> float:
        """values [T] → anomaly score of the latest sample."""
        import jax
        import jax.numpy as jnp

        w = getattr(self.cfg, "window", getattr(self.cfg, "context", 32))
        fn = self._jit_cache.get(("score", w))
        if fn is None:
            fn = jax.jit(self.spec.score, static_argnums=1)
            self._jit_cache[("score", w)] = fn
        window = jnp.asarray(self._padded(values, w))[None]
        n = jnp.asarray([min(len(values), w)], jnp.int32)
        return float(fn(self._live_params(), self.cfg, window, n)[0])


def forecast_breach_rule(
    name: str,
    udf: ModelUdf,
    measurement: str,
    op: str,
    threshold: float,
    window: int = 64,
    level: AlertLevel = AlertLevel.WARNING,
    cooldown_ms: int = 60_000,
) -> Rule:
    """Fire when the UDF's *forecast* breaches a threshold — alerts before
    the physical value does (the predictive-CEP capability [B:5])."""
    cmp = _OPS[op]

    async def act(e: DeviceEvent, ctx: Dict[str, Any]):
        vals = ctx["window_values"]
        mean = await asyncio.get_running_loop().run_in_executor(
            None, udf.forecast, vals
        )
        breach = [float(v) for v in mean if cmp(float(v), threshold)]
        if not breach:
            return None
        return [
            DeviceAlert(
                device_token=e.device_token,
                assignment_token=e.assignment_token,
                tenant=e.tenant,
                area_token=e.area_token,
                asset_token=e.asset_token,
                customer_token=e.customer_token,
                source="rule",
                level=level,
                alert_type="forecast-breach",
                message=(
                    f"forecast breaches {measurement} {op} {threshold} "
                    f"(first={breach[0]:.3f})"
                ),
                metadata={"rule": ctx["rule"], "origin_event": e.id},
            )
        ]

    return Rule(
        name=name,
        event_type=EventType.MEASUREMENT,
        where=lambda e: e.name == measurement,  # type: ignore[attr-defined]
        window=window,
        min_window=window // 2,
        action=act,
        cooldown_ms=cooldown_ms,
    )


class RuleEngine(LifecycleComponent):
    """Per-tenant rule engine over the persisted (enriched) event stream."""

    def __init__(
        self,
        tenant: str,
        bus: EventBus,
        rules: Optional[List[Rule]] = None,
        metrics: Optional[MetricsRegistry] = None,
        poll_batch: int = 4096,
        policy: Optional[FaultTolerancePolicy] = None,
        tracer=None,
        overload=None,
    ) -> None:
        super().__init__(f"rule-processing[{tenant}]")
        self.tenant = tenant
        self.bus = bus
        self.rules: List[Rule] = list(rules or [])
        self.metrics = metrics or MetricsRegistry()
        self.poll_batch = poll_batch
        from sitewhere_tpu.runtime.overload import DeadlineGate
        from sitewhere_tpu.runtime.tracing import StageTimer

        self.stage_timer = StageTimer(tracer, self.metrics, tenant, "rules")
        # overload control: expired measurement batches skip rule work
        # (they are already persisted — only derived fan-out is saved),
        # and the 'persist_only' degradation rung pauses evaluation of
        # measurement batches entirely while engaged
        self.overload = overload
        self.deadline_gate = DeadlineGate(
            bus, tenant, "rules", self.metrics, tracer=tracer,
            controller=overload, route_payload=False,
        )
        self.retry = RetryingConsumer(
            bus, tenant, "rules", self.group, policy=policy,
            metrics=self.metrics, tracer=tracer,
        )
        self._task: Optional[asyncio.Task] = None

    @property
    def group(self) -> str:
        return f"rule-processing[{self.tenant}]"

    def add_rule(self, rule: Rule) -> None:
        self.rules.append(rule)

    def remove_rule(self, name: str) -> None:
        self.rules = [r for r in self.rules if r.name != name]

    async def on_start(self) -> None:
        self.bus.subscribe(
            self.bus.naming.persisted_events(self.tenant), self.group
        )
        self._task = asyncio.create_task(self._run(), name=self.name)

    async def on_stop(self) -> None:
        await cancel_and_wait(self._task)
        self._task = None

    async def _run(self) -> None:
        # per-rule faults are isolated inside process_batch/process_event;
        # the retry wrapper covers stage-level faults (derived-event
        # publishes, batch materialization) and dead-letters poison items
        await self.retry.run(
            self.bus.naming.persisted_events(self.tenant),
            self._handle,
            self.poll_batch,
        )

    async def _handle(self, item) -> None:
        t0 = time.time() * 1000.0
        if self.deadline_gate.check(item):
            return  # already persisted; only the derived fan-out is shed
        if (
            isinstance(item, MeasurementBatch)
            and self.overload is not None
            and self.overload.degraded(self.tenant, "persist_only")
        ):
            # persist-only degradation: rule evaluation over measurement
            # batches pauses while the rung is engaged (alerts and other
            # object events still evaluate — they are the valuable ones)
            self.metrics.counter("rules.skipped_degraded").inc(item.n)
            return
        if isinstance(item, MeasurementBatch):
            derived = await self.process_batch(item)
            n = item.n
        else:
            derived = await self.process_event(item)
            n = 1
        self.stage_timer.observe(
            item, t0, time.time() * 1000.0, n_events=n,
            fired=len(derived),
        )

    async def process_batch(self, batch: MeasurementBatch) -> List[DeviceEvent]:
        """Columnar evaluation: rules with a ``vector_where`` run one numpy
        mask over the batch and materialize ONLY hit rows; rules without
        one (windowed/UDF rules) need every row, so the batch materializes
        once and runs the per-event path."""
        evaluated = self.metrics.counter("rules.evaluated")
        derived_out: List[DeviceEvent] = []
        need_full = [
            r for r in self.rules
            if r.vector_where is None
            and r.event_type in (None, EventType.MEASUREMENT)
        ]
        if need_full:
            for e in batch.to_events():
                derived_out.extend(await self.process_event(e))
            return derived_out
        fired = self.metrics.counter("rules.fired")
        for rule in self.rules:
            if rule.event_type not in (None, EventType.MEASUREMENT):
                continue
            evaluated.inc(batch.n)
            try:
                mask = rule.vector_where(batch)
                hits = np.nonzero(mask)[0]
            except Exception as exc:  # noqa: BLE001
                self._record_error(f"rule '{rule.name}' (vector)", exc)
                continue
            if hits.size == 0:
                continue
            # stateless + cooldown rules: within ONE batch only the first
            # hit per (device:name) group can pass the cooldown gate, and
            # groups still cooling down can be skipped outright — compact
            # BEFORE materializing (an alert-storm batch would otherwise
            # objectify thousands of rows just to drop them)
            if (
                rule.cooldown_ms
                and rule.vector_exact
                and not rule.window
                and not rule.window_time_ms
                and rule.group_by is None
            ):
                codes = batch.pair_codes()[hits]
                _, first = np.unique(codes, return_index=True)
                hits = hits[np.sort(first)]
                lf = rule._last_fired
                if lf:
                    now = time.time() * 1000.0
                    toks, nms = batch.device_tokens, batch.names
                    keep = [
                        j
                        for j, i in enumerate(hits.tolist())
                        if now - lf.get(f"{toks[i]}:{nms[i]}", 0.0)
                        >= rule.cooldown_ms
                    ]
                    if len(keep) != len(hits):
                        hits = (
                            hits[np.asarray(keep, np.intp)]
                            if keep
                            else hits[:0]
                        )
                if hits.size == 0:
                    continue
            # hit rows materialize to objects; evaluate() re-applies the
            # scalar filter plus cooldown/window state and runs the action
            for e in batch.select(hits).to_events():
                try:
                    derived = await rule.evaluate(e)
                except Exception as exc:  # noqa: BLE001
                    self._record_error(f"rule '{rule.name}'", exc)
                    continue
                if derived:
                    fired.inc()
                    derived_out.extend(derived)
        await self._emit_derived(derived_out, parent=batch)
        return derived_out

    async def _emit_derived(
        self, derived_out: List[DeviceEvent], parent=None
    ) -> None:
        from sitewhere_tpu.core.trace import trace_ctx_of

        parent_ctx = trace_ctx_of(parent) if parent is not None else None
        for d in derived_out:
            if d.trace_ctx is None and parent_ctx is not None:
                # derived events (alerts, command invocations) stay on the
                # origin event's trace: their persistence/outbound spans
                # show up as children of the rule that fired
                d.trace_ctx = parent_ctx.child()
            d.mark("rule")
            if d.EVENT_TYPE is EventType.COMMAND_INVOCATION:
                await self.retry.publish(
                    self.bus.naming.command_invocations(self.tenant), d
                )
            else:
                await self.retry.publish(
                    self.bus.naming.scored_events(self.tenant), d
                )

    async def process_event(self, e: DeviceEvent) -> List[DeviceEvent]:
        """Evaluate all rules; publish derived events into the pipeline."""
        evaluated = self.metrics.counter("rules.evaluated")
        fired = self.metrics.counter("rules.fired")
        derived_out: List[DeviceEvent] = []
        for rule in self.rules:
            evaluated.inc()
            try:
                derived = await rule.evaluate(e)
            except Exception as exc:  # noqa: BLE001 - a bad rule must not kill the engine
                self._record_error(f"rule '{rule.name}'", exc)
                continue
            if derived:
                fired.inc()
                derived_out.extend(derived)
        # derived alerts re-enter at the scored stage (they get persisted +
        # fanned out); alerts don't match measurement rules so no feedback loop
        await self._emit_derived(derived_out, parent=e)
        return derived_out
