"""Streaming-media classification pipeline: camera chunks → frame decode
→ micro-batched ViT classification → classification events on the bus.

Closes the north-star media loop (BASELINE.json:11; SURVEY.md §2.2
streaming-media [U]; reference mount empty, see provenance banner): the
reference's service only STORES stream chunks — the rebuild adds the TPU
leg, reusing the micro-batching playbook from ``pipeline.inference``
(bucketed static shapes, collect deadline, pipelined materialization off
the event loop).

Chunk kinds:
- ``raw-rgb8``: H*W*3 uint8 bytes (raw camera feed) — np.frombuffer, no
  per-pixel Python;
- ``jpeg``/``png``: decoded via PIL on an executor thread (CPU-bound).
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from sitewhere_tpu.runtime.bus import EventBus
from sitewhere_tpu.runtime.lifecycle import (
    LifecycleComponent,
    LifecycleState,
    cancel_and_wait,
)
from sitewhere_tpu.runtime.metrics import MetricsRegistry
from sitewhere_tpu.services.streaming_media import StreamingMedia


def media_classifications_topic(bus: EventBus, tenant: str) -> str:
    return bus.naming.tenant_topic(tenant, "media-classifications")


class MediaClassificationPipeline(LifecycleComponent):
    """Per-tenant micro-batched frame classifier over the media service."""

    def __init__(
        self,
        tenant: str,
        bus: EventBus,
        media: StreamingMedia,
        metrics: Optional[MetricsRegistry] = None,
        max_batch: int = 16,
        deadline_ms: float = 30.0,
        top_k: int = 5,
        tiny: bool = False,          # tiny ViT for CI; B/16 in prod/bench
        max_inflight: int = 4,
        store_chunks: bool = True,
    ) -> None:
        super().__init__(f"media-pipeline[{tenant}]")
        self.tenant = tenant
        self.bus = bus
        self.media = media
        self.metrics = metrics or MetricsRegistry()
        self.max_batch = max_batch
        self.deadline_ms = deadline_ms
        self.top_k = top_k
        self.tiny = tiny
        self.store_chunks = store_chunks
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=1024)
        self._task: Optional[asyncio.Task] = None
        self._inflight = asyncio.Semaphore(max_inflight)
        self._deliver_tasks: set = set()

    # -- ingest -----------------------------------------------------------
    @property
    def image_size(self) -> int:
        from sitewhere_tpu.models.vit import VIT_B16, VIT_TINY_TEST

        return (VIT_TINY_TEST if self.tiny else VIT_B16).image_size

    async def submit_chunk(
        self,
        stream_id: str,
        seq: int,
        data: bytes,
        kind: str = "raw-rgb8",
    ) -> None:
        """One camera chunk: persisted to the stream store (playback
        parity) and queued for classification."""
        if self.store_chunks:
            self.media.append_chunk(stream_id, seq, data)
        size = self.image_size
        if kind == "raw-rgb8":
            frame = self._decode_raw(data, size)
        else:  # jpeg/png: PIL decode is CPU-bound — off the loop. u8 so
            # every frame shares the on-device normalization path
            frame = await asyncio.get_running_loop().run_in_executor(
                None, self.media.decode_frame, data, size, "u8"
            )
        item = (stream_id, seq, frame, time.monotonic())
        try:
            self._queue.put_nowait(item)
        except asyncio.QueueFull:
            # live video: newest frame wins — shed the oldest queued
            # frame (counted) instead of backpressuring the camera feed
            # into the REST/transport layer
            try:
                self._queue.get_nowait()
            except asyncio.QueueEmpty:  # pragma: no cover - racing consumer
                pass
            self.metrics.counter("media_frames_shed_total").inc()
            try:
                self._queue.put_nowait(item)
            except asyncio.QueueFull:  # pragma: no cover - racing producer
                self.metrics.counter("media_frames_shed_total").inc()

    @staticmethod
    def _decode_raw(data: bytes, size: int) -> np.ndarray:
        n = size * size * 3
        if len(data) < n:
            raise ValueError(f"raw chunk too short: {len(data)} < {n}")
        # stays uint8: frames normalize ON DEVICE (classify_frames), so
        # host→device moves 1 byte/px instead of 4
        return np.frombuffer(data, np.uint8, n).reshape(size, size, 3)

    # -- lifecycle --------------------------------------------------------
    async def on_start(self) -> None:
        # classifier init (86M params for real B/16) runs OFF the loop —
        # a synchronous init would freeze every other tenant's pipeline
        # for its duration
        await asyncio.get_running_loop().run_in_executor(
            None, self.media._get_classifier, self.tiny
        )
        self._task = asyncio.create_task(self._run(), name=self.name)

    async def on_stop(self) -> None:
        await cancel_and_wait(self._task)
        self._task = None
        if self._deliver_tasks:
            # bounded grace, then force-cancel: an in-flight publish
            # against a full topic whose consumer is already stopped
            # would otherwise hang the whole stop cascade
            _done, pending = await asyncio.wait(
                list(self._deliver_tasks), timeout=5.0
            )
            for t in pending:
                await cancel_and_wait(t)

    def _buckets(self) -> List[int]:
        """Static batch-shape ladder (XLA recompile avoidance, same
        playbook as the inference flush buckets): light traffic classifies
        at the smallest fitting shape instead of paying a full max_batch
        forward per frame."""
        out = [1]
        b = 4
        while b < self.max_batch:
            out.append(b)
            b *= 4
        out.append(self.max_batch)
        return out

    def prewarm(self) -> None:
        """Compile every bucket shape before timed traffic."""
        size = self.image_size
        for b in self._buckets():
            self.media.classify_frames(
                np.zeros((b, size, size, 3), np.uint8),
                top_k=self.top_k, tiny=self.tiny,
            )

    # -- batching loop ----------------------------------------------------
    async def _run(self) -> None:
        topic = media_classifications_topic(self.bus, self.tenant)
        frames_ctr = self.metrics.counter("media.frames_classified")
        lat = self.metrics.histogram("media.latency", unit="s")
        while True:
            first = await self._queue.get()
            batch = [first]
            deadline = time.monotonic() + self.deadline_ms / 1000.0
            while len(batch) < self.max_batch:
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    break
                try:
                    batch.append(
                        await asyncio.wait_for(self._queue.get(), timeout)
                    )
                except asyncio.TimeoutError:
                    break
            await self._inflight.acquire()
            task = asyncio.create_task(
                self._classify_and_publish(batch, topic, frames_ctr, lat)
            )
            self._deliver_tasks.add(task)
            task.add_done_callback(self._deliver_tasks.discard)

    async def _classify_and_publish(
        self, batch: List[Tuple], topic: str, frames_ctr, lat
    ) -> None:
        try:
            frames = np.stack([b[2] for b in batch])
            # pad to the smallest fitting bucket shape; padded rows are
            # sliced off the results
            n = len(batch)
            bucket = next(b for b in self._buckets() if b >= n)
            if n < bucket:
                frames = np.concatenate([
                    frames,
                    np.zeros((bucket - n,) + frames.shape[1:], frames.dtype),
                ])
            # jit dispatch + materialization off the loop (the classify
            # output is a jit result nothing donates — worker-thread
            # materialization is safe, see checkpoint.host_copy_params)
            results = await asyncio.get_running_loop().run_in_executor(
                None, self.media.classify_frames, frames, self.top_k, self.tiny
            )
            now_mono = time.monotonic()
            now = time.time() * 1000.0
            for (stream_id, seq, _f, t0), top in zip(batch, results[:n]):
                payload = {
                    "type": "media_classification",
                    "tenant": self.tenant,
                    "stream_id": stream_id,
                    "seq": seq,
                    "top_k": top,
                    "ts": now,
                }
                if self.state is LifecycleState.STARTED:
                    await self.bus.publish(topic, payload)
                else:  # teardown: the consumer may already be gone
                    self.bus.publish_nowait(topic, payload)
                lat.record(now_mono - t0)
            frames_ctr.inc(n)
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 - one bad batch must not
            # kill the classification loop
            self._record_error("classify", exc)
        finally:
            self._inflight.release()
