"""Streaming-media classification pipeline: camera chunks → frame decode
→ micro-batched ViT classification → classification events on the bus.

Closes the north-star media loop (BASELINE.json:11; SURVEY.md §2.2
streaming-media [U]; reference mount empty, see provenance banner): the
reference's service only STORES stream chunks — the rebuild adds the TPU
leg, reusing the micro-batching playbook from ``pipeline.inference``
(bucketed static shapes, collect deadline, pipelined materialization off
the event loop).

Zero-copy feed path (docs/PERFORMANCE.md): decoded frames land directly
in a preallocated uint8 frame ring (``_FrameRing``) at submit time — no
per-frame array allocation, no Python list of frames. Each micro-batch
is ONE contiguous slice copy ring → a pooled staging buffer, and the
classify leg receives that contiguous buffer whole, so the host→device
transfer is a single contiguous put per flush. ``max_inflight`` staging
buffers rotate through in-flight classifies, so batch N+1's transfer
overlaps batch N's device compute — the same double-buffering scheme as
the scoring flush path. This is what closes the frames/s gap between
the model-only and end-to-end ViT numbers on transfer-bound links.

Chunk kinds:
- ``raw-rgb8``: H*W*3 uint8 bytes (raw camera feed) — one memcpy
  straight into the ring slot, no per-pixel Python;
- ``jpeg``/``png``: decoded via PIL on an executor thread (CPU-bound),
  then copied into the ring slot on the loop thread.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from sitewhere_tpu.runtime.bus import EventBus
from sitewhere_tpu.runtime.lifecycle import (
    LifecycleComponent,
    LifecycleState,
    cancel_and_wait,
)
from sitewhere_tpu.runtime.metrics import D2H_OVERLAP_EPS_S, MetricsRegistry
from sitewhere_tpu.services.streaming_media import StreamingMedia


def media_classifications_topic(bus: EventBus, tenant: str) -> str:
    return bus.naming.tenant_topic(tenant, "media-classifications")


class _FrameRing:
    """Preallocated decoded-frame ring for one media pipeline.

    Frames are written into a fixed ``uint8[cap, H, W, 3]`` buffer at
    submit time (``reserve``/``commit``); each micro-batch leaves as ONE
    contiguous slice copy into a pooled staging buffer (``pop_into``) —
    a single contiguous device put per flush, never ``np.stack`` over a
    Python list of frames. Live-video semantics: newest frame wins — a
    full ring sheds its OLDEST pending frame (``media_frames_shed_total``)
    instead of backpressuring the camera feed into the transport layer.
    Depth surfaces per tenant through the ``media_queue_depth`` gauge
    (collected in ``instance.py``; tools/check_queues.py registry).
    """

    __slots__ = ("frames", "meta", "head", "count", "data_event", "metrics")

    def __init__(self, capacity: int, size: int, metrics) -> None:
        self.frames = np.empty((capacity, size, size, 3), np.uint8)
        self.meta: List = [None] * capacity  # (stream_id, seq, t0)
        self.head = 0
        self.count = 0
        self.data_event = asyncio.Event()
        self.metrics = metrics

    @property
    def capacity(self) -> int:
        return len(self.meta)

    def qsize(self) -> int:
        return self.count

    def reserve(self) -> np.ndarray:
        """The next write slot's frame view — fill it, then ``commit``.
        A full ring sheds its oldest pending frame first (counted)."""
        if self.count >= self.capacity:
            self.head = (self.head + 1) % self.capacity
            self.count -= 1
            self.metrics.counter("media_frames_shed_total").inc()
        return self.frames[(self.head + self.count) % self.capacity]

    def commit(self, stream_id: str, seq: int, t0: float) -> None:
        self.meta[(self.head + self.count) % self.capacity] = (
            stream_id, seq, t0,
        )
        self.count += 1
        self.data_event.set()

    def pop_into(self, staging: np.ndarray, max_n: int) -> List[Tuple]:
        """Move up to ``max_n`` frames off the front into ``staging`` with
        one slice copy; returns their metas. Bounded by the contiguous
        span at the head — a wrap remainder rides the next batch (keeps
        every copy a single contiguous memcpy)."""
        k = min(self.count, max_n, self.capacity - self.head)
        if k <= 0:
            return []
        h = self.head
        staging[:k] = self.frames[h : h + k]
        metas = self.meta[h : h + k]
        self.head = (h + k) % self.capacity
        self.count -= k
        return metas


class MediaClassificationPipeline(LifecycleComponent):
    """Per-tenant micro-batched frame classifier over the media service."""

    def __init__(
        self,
        tenant: str,
        bus: EventBus,
        media: StreamingMedia,
        metrics: Optional[MetricsRegistry] = None,
        max_batch: int = 16,
        deadline_ms: float = 30.0,
        top_k: int = 5,
        tiny: bool = False,          # tiny ViT for CI; B/16 in prod/bench
        max_inflight: int = 4,
        store_chunks: bool = True,
        # 256 frames ≈ 38 MB at 224×224×3 — the write cursor cycles the
        # whole ring over time, so capacity bounds RESIDENT memory per
        # tenant, not just backlog; live video (newest-wins shedding)
        # never usefully holds more than a few classify batches anyway
        ring_capacity: int = 256,
        flightrec=None,
    ) -> None:
        super().__init__(f"media-pipeline[{tenant}]")
        self.tenant = tenant
        self.bus = bus
        self.media = media
        self.metrics = metrics or MetricsRegistry()
        self.max_batch = max_batch
        self.deadline_ms = deadline_ms
        self.top_k = top_k
        self.tiny = tiny
        self.store_chunks = store_chunks
        self.max_inflight = max_inflight
        self._ring = _FrameRing(ring_capacity, self.image_size, self.metrics)
        # pooled staging buffers: one per in-flight classify (+1 for the
        # batch being packed) so a buffer is never rewritten while its
        # classify still reads it; sized lazily to the CURRENT max_batch
        # (benches retune max_batch after construction)
        from collections import deque

        self._staging_pool: deque = deque()
        self._task: Optional[asyncio.Task] = None
        self._inflight = asyncio.Semaphore(max_inflight)
        self._deliver_tasks: set = set()
        # flight-recorder + live MFU attribution for the ViT leg (wired
        # on start — the flops figure needs the classifier config)
        self.flightrec = flightrec
        self._mfu = None
        self._flops_per_frame = 0.0

    def refresh_mfu(self) -> None:
        """Decay this tenant's idle ``tpu_mfu_pct`` gauge from the
        sliding window (instance history tick / scrape — a stream that
        stopped must read 0, not its last busy value)."""
        if self._mfu is not None:
            self._mfu.refresh()

    def pending_frames(self) -> int:
        """Decoded frames awaiting classification (media_queue_depth)."""
        return self._ring.qsize()

    def _checkout_staging(self) -> np.ndarray:
        while self._staging_pool:
            buf = self._staging_pool.popleft()
            if buf.shape[0] >= self.max_batch:
                return buf
        size = self.image_size
        return np.empty((self.max_batch, size, size, 3), np.uint8)

    def _return_staging(self, buf: np.ndarray) -> None:
        if len(self._staging_pool) <= self.max_inflight:
            self._staging_pool.append(buf)

    # -- ingest -----------------------------------------------------------
    @property
    def image_size(self) -> int:
        from sitewhere_tpu.models.vit import VIT_B16, VIT_TINY_TEST

        return (VIT_TINY_TEST if self.tiny else VIT_B16).image_size

    async def submit_chunk(
        self,
        stream_id: str,
        seq: int,
        data: bytes,
        kind: str = "raw-rgb8",
    ) -> None:
        """One camera chunk: persisted to the stream store (playback
        parity) and decoded STRAIGHT INTO the frame ring — one memcpy,
        zero per-frame array allocation (shed-oldest when full)."""
        if self.store_chunks:
            self.media.append_chunk(stream_id, seq, data)
        size = self.image_size
        if kind == "raw-rgb8":
            # validate BEFORE reserving a ring slot (a short chunk is the
            # caller's error and must not consume/shear ring state)
            frame = self._decode_raw(data, size)
        else:  # jpeg/png: PIL decode is CPU-bound — off the loop. u8 so
            # every frame shares the on-device normalization path
            frame = await asyncio.get_running_loop().run_in_executor(
                None, self.media.decode_frame, data, size, "u8"
            )
        # reserve+commit run on the loop thread (no await between them)
        self._ring.reserve()[...] = frame
        self._ring.commit(stream_id, seq, time.monotonic())

    @staticmethod
    def _decode_raw(data: bytes, size: int) -> np.ndarray:
        n = size * size * 3
        if len(data) < n:
            raise ValueError(f"raw chunk too short: {len(data)} < {n}")
        # stays uint8: frames normalize ON DEVICE (classify_frames), so
        # host→device moves 1 byte/px instead of 4
        return np.frombuffer(data, np.uint8, n).reshape(size, size, 3)

    # -- lifecycle --------------------------------------------------------
    async def on_start(self) -> None:
        # classifier init (86M params for real B/16) runs OFF the loop —
        # a synchronous init would freeze every other tenant's pipeline
        # for its duration
        await asyncio.get_running_loop().run_in_executor(
            None, self.media._get_classifier, self.tiny
        )
        # device-time/MFU attribution: per-frame analytic flops from the
        # classifier config (labeled per tenant — media pipelines are
        # per-tenant, and drop_labeled(tenant=...) reclaims the children)
        try:
            self._flops_per_frame = self.media.classifier_flops_per_frame(
                self.tiny
            )
        except Exception:  # noqa: BLE001 - attribution must not block start
            self._flops_per_frame = 0.0
        from sitewhere_tpu.runtime.metrics import MfuAccount

        self._mfu = MfuAccount(self.metrics, "vit_b16", tenant=self.tenant)
        self._task = asyncio.create_task(self._run(), name=self.name)

    async def on_stop(self) -> None:
        await cancel_and_wait(self._task)
        self._task = None
        if self._deliver_tasks:
            # bounded grace, then force-cancel: an in-flight publish
            # against a full topic whose consumer is already stopped
            # would otherwise hang the whole stop cascade
            _done, pending = await asyncio.wait(
                list(self._deliver_tasks), timeout=5.0
            )
            for t in pending:
                await cancel_and_wait(t)

    def _buckets(self) -> List[int]:
        """Static batch-shape ladder (XLA recompile avoidance, same
        playbook as the inference flush buckets): light traffic classifies
        at the smallest fitting shape instead of paying a full max_batch
        forward per frame."""
        out = [1]
        b = 4
        while b < self.max_batch:
            out.append(b)
            b *= 4
        out.append(self.max_batch)
        return out

    def prewarm(self) -> None:
        """Compile every bucket shape before timed traffic."""
        size = self.image_size
        for b in self._buckets():
            self.media.classify_frames(
                np.zeros((b, size, size, 3), np.uint8),
                top_k=self.top_k, tiny=self.tiny,
            )

    # -- batching loop ----------------------------------------------------
    async def _run(self) -> None:
        topic = media_classifications_topic(self.bus, self.tenant)
        frames_ctr = self.metrics.counter("media.frames_classified")
        lat = self.metrics.histogram("media.latency", unit="s")
        ring = self._ring
        while True:
            # wait for the first frame (clear-then-recheck: a commit
            # between the count check and the clear must not be missed)
            while ring.count == 0:
                ring.data_event.clear()
                if ring.count:
                    break
                await ring.data_event.wait()
            deadline = time.monotonic() + self.deadline_ms / 1000.0
            while ring.count < self.max_batch:
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    break
                ring.data_event.clear()
                if ring.count >= self.max_batch:
                    break
                try:
                    await asyncio.wait_for(ring.data_event.wait(), timeout)
                except asyncio.TimeoutError:
                    break
            await self._inflight.acquire()
            # the batch leaves the ring as ONE contiguous slice copy into
            # a pooled staging buffer the classify task owns until done
            staging = self._checkout_staging()
            metas = ring.pop_into(staging, self.max_batch)
            if not metas:
                self._inflight.release()
                self._return_staging(staging)
                continue
            task = asyncio.create_task(
                self._classify_and_publish(staging, metas, topic, frames_ctr, lat)
            )
            self._deliver_tasks.add(task)
            task.add_done_callback(self._deliver_tasks.discard)

    async def _classify_and_publish(
        self, staging: np.ndarray, metas: List[Tuple], topic: str, frames_ctr, lat
    ) -> None:
        try:
            # smallest fitting bucket shape; rows past n are whatever the
            # staging buffer held before (valid pixel data, results
            # sliced off) — no pad allocation, no concatenate
            n = len(metas)
            bucket = next(b for b in self._buckets() if b >= n)
            # jit dispatch off the loop (the classify output is a jit
            # result nothing donates — worker-thread materialization is
            # safe, see checkpoint.host_copy_params). staging[:bucket]
            # is one contiguous buffer → one contiguous host→device put;
            # concurrent classifies on pooled buffers overlap transfer
            # with the previous batch's compute. The d2h copy starts
            # inside the dispatch (copy_to_host_async — same async
            # treatment as the scoring reaper), so by materialize time
            # it has been riding under compute, not starting cold.
            loop = asyncio.get_running_loop()
            t_disp0 = time.perf_counter()
            pv, iv = await loop.run_in_executor(
                None, self.media.classify_frames_dispatch, staging[:bucket],
                self.top_k, self.tiny,
            )
            t_disp1 = time.perf_counter()
            dispatch_s = t_disp1 - t_disp0
            disp_end_wall_ms = time.time() * 1000.0
            # materialize OFF the loop: is_ready would only prove the
            # compute finished, not that the async d2h copy crossed the
            # link — overlap is measured, not inferred (a materialization
            # that returns in ~0 never waited on the transfer; same rule
            # as the scoring reaper's D2H_OVERLAP_EPS_S)
            t_wait = time.perf_counter()
            results = await loop.run_in_executor(
                None, self.media.topk_results, pv, iv, n
            )
            waited_s = time.perf_counter() - t_wait
            self.metrics.histogram("media.d2h_wait", unit="s").record(waited_s)
            overlapped = waited_s < D2H_OVERLAP_EPS_S
            if overlapped:
                self.metrics.counter("media.d2h_overlapped").inc()
            # device-time/MFU attribution + blackbox record: the window
            # runs from dispatch RETURN until the top-k landed — the same
            # definition as the scoring path's device_s (which starts at
            # _PendingFlush construction, after its dispatch returned);
            # starting at dispatch issue would count the host dispatch
            # call and executor-queue wait as chip-busy time
            device_s = time.perf_counter() - t_disp1
            if self._mfu is not None and self._flops_per_frame:
                self._mfu.record(self._flops_per_frame * bucket, device_s)
            if self.flightrec is not None:
                # ts_ms must mark the DISPATCH return, not this (post-
                # resolution) record call: the Chrome export anchors the
                # host phases to end and the device window to start at
                # ts_ms, and media only records once the batch resolved
                self.flightrec.record(
                    "flush", f"vit_b16[{self.tenant}]",
                    ts_ms=disp_end_wall_ms,
                    rows=n, bucket=bucket,
                    dispatch_s=round(dispatch_s, 6),
                    d2h_wait_s=round(waited_s, 6),
                    d2h_overlapped=overlapped,
                    device_s=round(device_s, 6),
                    status="ok",
                )
            now_mono = time.monotonic()
            now = time.time() * 1000.0
            for (stream_id, seq, t0), top in zip(metas, results):
                payload = {
                    "type": "media_classification",
                    "tenant": self.tenant,
                    "stream_id": stream_id,
                    "seq": seq,
                    "top_k": top,
                    "ts": now,
                }
                if self.state is LifecycleState.STARTED:
                    await self.bus.publish(topic, payload)
                else:  # teardown: the consumer may already be gone
                    self.bus.publish_nowait(topic, payload)
                lat.record(now_mono - t0)
            frames_ctr.inc(n)
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 - one bad batch must not
            # kill the classification loop
            self._record_error("classify", exc)
        finally:
            self._inflight.release()
            self._return_staging(staging)
