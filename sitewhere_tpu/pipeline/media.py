"""Streaming-media classification pipeline: camera chunks → frame decode
→ micro-batched ViT classification → classification events on the bus.

Closes the north-star media loop (BASELINE.json:11; SURVEY.md §2.2
streaming-media [U]; reference mount empty, see provenance banner): the
reference's service only STORES stream chunks — the rebuild adds the TPU
leg, reusing the micro-batching playbook from ``pipeline.inference``
(bucketed static shapes, collect deadline, pipelined materialization off
the event loop).

Compressed media wire (docs/PERFORMANCE.md "Media wire & on-chip
decode"): by default, COMPRESSED bytes — not raw pixels — are the unit
that crosses every boundary from camera receiver to chip. Camera chunks
land in a preallocated variable-length byte arena (``_ByteRing``) at
submit time with zero host-side pixel materialization; at classify time
the SERIAL half of the decode (JPEG Huffman + dequant,
``native/jpegwire.py``) fans out over an executor thread pool into
int16 DCT coefficient buffers, and the embarrassingly parallel half
(dezigzag, IDCT, chroma upsample, YCbCr→RGB, normalize, patchify) runs
ON DEVICE fused into the ViT jit (``models.vit.apply_dct``). The h2d
payload is zigzag-truncated coefficients — typically 2-10× smaller than
raw RGB, and the ring holds 10-20×-smaller JPEG bytes, so ring capacity
bounds resident BYTES, not frame count. ``MEDIA_WIRE_COMPRESSED_ENABLED``
(captured at pipeline build, the FUSED_STEP_ENABLED pattern) restores
the raw-RGB path bitwise; a missing native build or any unsupported
stream degrades per batch to the PIL path — counted
(``media_native_decode_fallback_total``), never an error.

Zero-copy feed path (docs/PERFORMANCE.md): frames leave the ring as
contiguous span copies into pooled staging buffers, micro-batches ship
as ONE contiguous device put, and ``max_inflight`` pooled buffers
rotate through in-flight classifies so batch N+1's transfer overlaps
batch N's device compute — the same double-buffering scheme as the
scoring flush path.

Chunk kinds:
- ``raw-rgb8``: H*W*3 uint8 bytes (raw camera feed);
- ``jpeg``: compressed frames — native entropy decode + on-device IDCT
  on the compressed wire; PIL on the fallback/legacy paths;
- ``png``: lossless compressed — PIL-decoded (no native path), rides
  the byte ring so submit stays pixel-free either way.
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from sitewhere_tpu.runtime.bus import EventBus
from sitewhere_tpu.runtime.lifecycle import (
    LifecycleComponent,
    LifecycleState,
    cancel_and_wait,
)
from sitewhere_tpu.runtime.metrics import D2H_OVERLAP_EPS_S, MetricsRegistry
from sitewhere_tpu.services.streaming_media import StreamingMedia

# Compressed-frame wire kill switch (mirrors FUSED_STEP_ENABLED /
# WIRE_CODEC_ENABLED): captured at PIPELINE BUILD time. False rebuilds
# the pre-compression pipeline exactly — decoded frames ring
# (``_FrameRing``), submit-time PIL decode, raw-pixel h2d — bit for bit
# (regression-tested in tests/test_media_wire.py). Flip process-wide
# BEFORE tenants start for a rollback/mixed-fleet window.
MEDIA_WIRE_COMPRESSED_ENABLED = True


def media_classifications_topic(bus: EventBus, tenant: str) -> str:
    return bus.naming.tenant_topic(tenant, "media-classifications")


class _FrameRing:
    """Preallocated decoded-frame ring for one media pipeline.

    Frames are written into a fixed ``uint8[cap, H, W, 3]`` buffer at
    submit time (``reserve``/``commit``); each micro-batch leaves as ONE
    contiguous slice copy into a pooled staging buffer (``pop_into``) —
    a single contiguous device put per flush, never ``np.stack`` over a
    Python list of frames. Live-video semantics: newest frame wins — a
    full ring sheds its OLDEST pending frame (``media_frames_shed_total``)
    instead of backpressuring the camera feed into the transport layer.
    Depth surfaces per tenant through the ``media_queue_depth`` gauge
    (collected in ``instance.py``; tools/check_queues.py registry).
    """

    __slots__ = ("frames", "meta", "head", "count", "data_event", "metrics")

    def __init__(self, capacity: int, size: int, metrics) -> None:
        self.frames = np.empty((capacity, size, size, 3), np.uint8)
        self.meta: List = [None] * capacity  # (stream_id, seq, t0, wire_nb)
        self.head = 0
        self.count = 0
        self.data_event = asyncio.Event()
        self.metrics = metrics

    @property
    def capacity(self) -> int:
        return len(self.meta)

    def qsize(self) -> int:
        return self.count

    def used_bytes(self) -> int:
        return self.count * int(self.frames[0].nbytes)

    def reserve(self) -> np.ndarray:
        """The next write slot's frame view — fill it, then ``commit``.
        A full ring sheds its oldest pending frame first (counted)."""
        if self.count >= self.capacity:
            self.head = (self.head + 1) % self.capacity
            self.count -= 1
            self.metrics.counter("media_frames_shed_total").inc()
        return self.frames[(self.head + self.count) % self.capacity]

    def commit(
        self, stream_id: str, seq: int, t0: float, wire_nb: int = 0
    ) -> None:
        # wire_nb: bytes the chunk ARRIVED as (flightrec wire_bytes must
        # report the camera wire, not the decoded pixels it became)
        self.meta[(self.head + self.count) % self.capacity] = (
            stream_id, seq, t0, wire_nb,
        )
        self.count += 1
        self.data_event.set()

    def pop_into(self, staging: np.ndarray, max_n: int) -> List[Tuple]:
        """Move up to ``max_n`` frames off the front into ``staging`` with
        one slice copy; returns their metas. Bounded by the contiguous
        span at the head — a wrap remainder rides the next batch (keeps
        every copy a single contiguous memcpy)."""
        k = min(self.count, max_n, self.capacity - self.head)
        if k <= 0:
            return []
        h = self.head
        staging[:k] = self.frames[h : h + k]
        metas = self.meta[h : h + k]
        self.head = (h + k) % self.capacity
        self.count -= k
        return metas


class _ByteRing:
    """Variable-length compressed-frame ring: one preallocated byte
    arena + a per-frame (offset, length, kind, meta) index ring.

    The compressed wire's holding pen — JPEG chunks are ~10-20× smaller
    than decoded frames, so ``arena_bytes`` bounds RESIDENT bytes per
    tenant where ``_FrameRing`` bounded frame count. Frames occupy
    contiguous arena spans in FIFO order; when the tail can't fit the
    next frame the writer wraps to offset 0 (the skipped tail is dead
    until the reader passes it). ``_FrameRing`` semantics preserved:
    newest frame wins — a full arena (or full index) sheds its OLDEST
    pending frame (``media_frames_shed_total``); depth rides the same
    ``media_queue_depth`` gauge plus ``media_ring_bytes`` for the byte
    watermark (tools/check_queues.py registry).
    """

    __slots__ = (
        "arena", "meta", "head", "count", "write_off", "used",
        "data_event", "metrics",
    )

    def __init__(self, index_capacity: int, arena_bytes: int, metrics) -> None:
        self.arena = np.empty((arena_bytes,), np.uint8)
        # (off, nbytes, kind, stream_id, seq, t0)
        self.meta: List = [None] * index_capacity
        self.head = 0
        self.count = 0
        self.write_off = 0
        self.used = 0          # pending payload bytes (excludes dead tail)
        self.data_event = asyncio.Event()
        self.metrics = metrics

    @property
    def capacity(self) -> int:
        return len(self.meta)

    @property
    def arena_bytes(self) -> int:
        return int(self.arena.shape[0])

    def qsize(self) -> int:
        return self.count

    def used_bytes(self) -> int:
        return self.used

    def _drop_oldest(self) -> None:
        self.meta[self.head] = None
        self.head = (self.head + 1) % self.capacity
        self.count -= 1
        if self.count == 0:
            self.write_off = 0
            self.used = 0

    def _shed_oldest(self) -> None:
        self.used -= self.meta[self.head][1]
        self._drop_oldest()
        self.metrics.counter("media_frames_shed_total").inc()

    def _fit(self, nb: int) -> int:
        """Arena offset where ``nb`` bytes fit RIGHT NOW, or -1."""
        if self.count == 0:
            return 0 if nb <= self.arena_bytes else -1
        head_off = self.meta[self.head][0]
        if self.write_off >= head_off:
            # data occupies [head_off, write_off)
            if nb <= self.arena_bytes - self.write_off:
                return self.write_off
            if nb < head_off:  # wrap (strict: write_off==head_off is full)
                return 0
            return -1
        # wrapped: data occupies [head_off, ...) ∪ [0, write_off).
        # STRICT: filling the gap exactly would make write_off==head_off,
        # which is indistinguishable from the unwrapped-empty-gap state
        if nb < head_off - self.write_off:
            return self.write_off
        return -1

    def append(
        self, data: bytes, kind: str, stream_id: str, seq: int, t0: float
    ) -> bool:
        """One compressed frame into the arena (one memcpy). Sheds
        oldest pending frames until it fits; returns False only for a
        frame larger than the whole arena (caller counts it shed)."""
        nb = len(data)
        if nb > self.arena_bytes:
            self.metrics.counter("media_frames_shed_total").inc()
            return False
        if self.count >= self.capacity:
            self._shed_oldest()
        off = self._fit(nb)
        while off < 0:
            self._shed_oldest()
            off = self._fit(nb)
        self.arena[off : off + nb] = np.frombuffer(data, np.uint8)
        self.meta[(self.head + self.count) % self.capacity] = (
            off, nb, kind, stream_id, seq, t0,
        )
        self.count += 1
        self.write_off = off + nb
        self.used += nb
        self.data_event.set()
        return True

    def peek_bytes(self, max_n: int) -> int:
        """Total payload bytes of the up-to-``max_n`` oldest frames
        (sizes the staging checkout before ``pop_into``)."""
        total = 0
        n = min(self.count, max_n)
        for i in range(n):
            total += self.meta[(self.head + i) % self.capacity][1]
        return total

    def pop_into(
        self,
        staging: np.ndarray,
        offs: np.ndarray,
        lens: np.ndarray,
        max_n: int,
    ) -> List[Tuple]:
        """Move up to ``max_n`` frames off the front into ``staging``
        (compacting: span copies land back to back), filling per-frame
        ``offs``/``lens``; returns their (kind, stream_id, seq, t0)
        metas. Frees ring space immediately — the staging buffer is the
        classify task's own, so a submit racing the decode can never
        overwrite bytes still being read."""
        pos = 0
        n = 0
        cap = int(staging.shape[0])
        metas: List[Tuple] = [None] * min(self.count, max_n)
        while n < max_n and self.count:
            off, nb, kind, stream_id, seq, t0 = self.meta[self.head]
            if pos + nb > cap:
                break
            staging[pos : pos + nb] = self.arena[off : off + nb]
            offs[n] = pos
            lens[n] = nb
            metas[n] = (kind, stream_id, seq, t0)
            pos += nb
            self.used -= nb
            self._drop_oldest()
            n += 1
        del metas[n:]
        return metas


class MediaClassificationPipeline(LifecycleComponent):
    """Per-tenant micro-batched frame classifier over the media service."""

    def __init__(
        self,
        tenant: str,
        bus: EventBus,
        media: StreamingMedia,
        metrics: Optional[MetricsRegistry] = None,
        max_batch: int = 16,
        deadline_ms: float = 30.0,
        top_k: int = 5,
        tiny: bool = False,          # tiny ViT for CI; B/16 in prod/bench
        max_inflight: int = 4,
        store_chunks: bool = True,
        # legacy (kill-switch) decoded-frame ring: 256 frames ≈ 38 MB at
        # 224×224×3 — the write cursor cycles the whole ring over time,
        # so capacity bounds RESIDENT memory per tenant, not just backlog
        ring_capacity: int = 256,
        # compressed wire: the byte arena bounds resident bytes instead.
        # None = a quarter of the legacy ring's resident bytes (~9.6 MB
        # at 224px, floor 4 MB): the full ring_capacity depth at ≥4×
        # compression AND ≥64 frames of raw-rgb8 burst (a raw feed
        # riding the byte ring must still fill a max_batch without
        # waiting out the collect deadline); raw-heavy tenants size it
        # explicitly
        ring_bytes: Optional[int] = None,
        decode_workers: int = 4,
        flightrec=None,
        # flush supervision (docs/ROBUSTNESS.md "Device fault domains"):
        # every classify readback is bounded by max(flush_deadline_ms,
        # flush_deadline_x × this tenant's observed dispatch→landed
        # p99); an overdue batch's frames drop (media is lossy by
        # design — shed-oldest already governs the intake side) and
        # tpu_flush_timeout_total counts it. 0 disables supervision.
        flush_deadline_ms: float = 5000.0,
        flush_deadline_x: float = 8.0,
    ) -> None:
        super().__init__(f"media-pipeline[{tenant}]")
        self.tenant = tenant
        self.bus = bus
        self.media = media
        self.metrics = metrics or MetricsRegistry()
        self.max_batch = max_batch
        self.deadline_ms = deadline_ms
        self.top_k = top_k
        self.tiny = tiny
        self.store_chunks = store_chunks
        self.max_inflight = max_inflight
        # kill switch captured at BUILD time (the FUSED_STEP_ENABLED
        # pattern): a pipeline is born compressed or legacy and never
        # changes mid-flight — rollback = flip the module flag and
        # rebuild the tenant
        self.compressed = bool(MEDIA_WIRE_COMPRESSED_ENABLED)
        if self.compressed:
            if ring_bytes is None:
                frame_nb = self.image_size * self.image_size * 3
                ring_bytes = max(4 << 20, ring_capacity * frame_nb // 4)
            self._ring = _ByteRing(ring_capacity, ring_bytes, self.metrics)
        else:
            self._ring = _FrameRing(ring_capacity, self.image_size, self.metrics)
        # pooled staging buffers: one per in-flight classify (+1 for the
        # batch being packed) so a buffer is never rewritten while its
        # classify still reads it; sized lazily to the CURRENT max_batch
        # (benches retune max_batch after construction)
        # pools are touched from the loop thread AND (in compressed
        # mode) up to max_inflight concurrent executor threads running
        # _decode_batch — every check-then-pop/append runs under this
        # lock (allocation of fresh buffers stays outside it)
        self._pool_lock = threading.Lock()
        self._staging_pool: deque = deque()
        self._byte_staging_pool: deque = deque()   # (buf, offs, lens)
        self._coef_pool: deque = deque()           # (y, cb, cr) full-64
        self._coef_sub = 2                         # cached subsampling mode
        # hysteresis against recurring wasted decodes: a 4:4:4 stream
        # whose payload keeps failing the oversize guard (full-precision
        # 4:4:4 coefficients exceed raw pixels) routes straight to the
        # PIL path after a couple of rejected attempts
        self._sub1_rejects = 0
        self._packed_pools: Dict[tuple, deque] = {}
        # (bucket, k) coefficient variants prewarm compiled: once
        # populated, _decode_batch only picks shapes from this set (a
        # cold variant would pay a 20-40 s XLA compile MID-TRAFFIC on a
        # real chip, holding the inflight semaphore while the live ring
        # sheds); empty (no prewarm — tests/drives) = no restriction
        self._warm_variants: set = set()
        self._task: Optional[asyncio.Task] = None
        self._inflight = asyncio.Semaphore(max_inflight)
        self._deliver_tasks: set = set()
        # native decode pool: the serial Huffman+dequant stage fans out
        # here as per-worker RANGE jobs (ctypes releases the GIL, so
        # frames genuinely decode in parallel); the gauge counts those
        # jobs — bounded by max_inflight × decode_workers — and
        # media.decode_backpressure counts fan-outs that queued behind
        # a pool already running another batch's ranges
        self._decode_workers = max(1, decode_workers)
        self._decode_pool = None
        self._decode_lock = threading.Lock()
        self._decode_inflight = 0
        self._native_ok = False
        self._native_resolved = True   # start() sets False if build pending
        self._native_warned = False
        self._prewarmed = False
        # flight-recorder + live MFU attribution for the ViT leg (wired
        # on start — the flops figure needs the classifier config)
        self.flightrec = flightrec
        self._mfu = None
        self._flops_per_frame = 0.0
        # flush supervision: injectable device faults (runtime.faultplan;
        # None in production) + the classify deadline's p99 history
        self.faultplan = None
        self.flush_deadline_ms = float(flush_deadline_ms)
        self.flush_deadline_x = float(flush_deadline_x)
        from sitewhere_tpu.runtime.metrics import RollingQuantile

        self._classify_p99 = RollingQuantile()

    def _classify_deadline_s(self) -> Optional[float]:
        """The current classify completion budget (None = supervision
        off): the media twin of TpuInferenceService._flush_deadline_s."""
        floor = self.flush_deadline_ms / 1000.0
        if floor <= 0:
            return None
        p99 = self._classify_p99.quantile()
        if p99 is None:
            return floor
        return max(floor, self.flush_deadline_x * p99)

    def _warn_native_absent(self) -> None:
        if self._native_warned:
            return
        self._native_warned = True
        import logging

        logging.getLogger(__name__).warning(
            "media[%s]: native jpegwire unavailable — compressed "
            "frames decode via PIL (counted in "
            "media_native_decode_fallback_total)", self.tenant,
        )

    def refresh_mfu(self) -> None:
        """Decay this tenant's idle ``tpu_mfu_pct`` gauge from the
        sliding window (instance history tick / scrape — a stream that
        stopped must read 0, not its last busy value)."""
        if self._mfu is not None:
            self._mfu.refresh()

    def pending_frames(self) -> int:
        """Decoded frames awaiting classification (media_queue_depth)."""
        return self._ring.qsize()

    def pending_bytes(self) -> int:
        """Resident ring payload bytes (media_ring_bytes gauge — the
        byte watermark the compressed arena bounds)."""
        return self._ring.used_bytes()

    def _checkout_staging(self) -> np.ndarray:
        with self._pool_lock:
            while self._staging_pool:
                buf = self._staging_pool.popleft()
                if buf.shape[0] >= self.max_batch:
                    return buf
        size = self.image_size
        return np.empty((self.max_batch, size, size, 3), np.uint8)

    def _return_staging(self, buf: np.ndarray) -> None:
        with self._pool_lock:
            if len(self._staging_pool) <= self.max_inflight:
                self._staging_pool.append(buf)

    # -- compressed-wire staging pools ------------------------------------
    def _checkout_bytes(self, min_bytes: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Pooled (byte buffer, per-frame offsets, lengths) for one
        popped batch; grows to the requested byte span."""
        with self._pool_lock:
            while self._byte_staging_pool:
                buf, offs, lens = self._byte_staging_pool.popleft()
                if buf.shape[0] >= min_bytes and offs.shape[0] >= self.max_batch:
                    return buf, offs, lens
        cap = max(64 << 10, 1 << (max(min_bytes, 1) - 1).bit_length())
        return (
            np.empty((cap,), np.uint8),
            np.empty((self.max_batch,), np.int64),
            np.empty((self.max_batch,), np.int64),
        )

    def _return_bytes(self, entry) -> None:
        with self._pool_lock:
            if len(self._byte_staging_pool) <= self.max_inflight:
                self._byte_staging_pool.append(entry)

    @property
    def _coef_cap_blocks(self) -> int:
        # padded MCU-aligned Y-plane worst case
        return (((self.image_size + 15) // 16) * 2) ** 2

    @property
    def _chroma_cap_blocks(self) -> int:
        """Chroma decode-buffer capacity: sized for the cached
        subsampling mode — 1/4 of the Y grid at 4:2:0 (the camera/PIL
        default; a full-grid chroma allocation would quadruple resident
        decode memory for nothing), the full Y grid once a 4:4:4 stream
        has been seen (``_decode_batch``'s SOF peek upgrades the cached
        mode before any entropy decode runs)."""
        cap = self._coef_cap_blocks
        return cap if self._coef_sub == 1 else max(cap // 4, 1)

    def _checkout_coefs(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Pooled full-precision (64-coefficient) decode buffers the
        jpegwire pool writes into — one set per in-flight batch."""
        ccap = self._chroma_cap_blocks
        with self._pool_lock:
            while self._coef_pool:
                bufs = self._coef_pool.popleft()
                if bufs[0].shape[0] >= self.max_batch and bufs[1].shape[1] >= ccap:
                    return bufs
        cap = self._coef_cap_blocks
        return (
            np.zeros((self.max_batch, cap, 64), np.int16),
            np.zeros((self.max_batch, ccap, 64), np.int16),
            np.zeros((self.max_batch, ccap, 64), np.int16),
        )

    def _return_coefs(self, bufs) -> None:
        with self._pool_lock:
            # a set superseded by a chroma-mode upgrade drops, not pools
            if bufs[1].shape[1] < self._chroma_cap_blocks:
                return
            if len(self._coef_pool) <= self.max_inflight:
                self._coef_pool.append(bufs)

    def _checkout_packed(self, bucket: int, layout) -> Tuple[np.ndarray, ...]:
        """Pooled zigzag-truncated wire buffers for one (bucket, layout)
        — the contiguous arrays the device put ships. Unwritten rows
        past the live frames carry whatever the pool held (finite int16
        garbage; results sliced off, same contract as pixel staging)."""
        key = (bucket, layout.y_blocks, layout.c_blocks, layout.k)
        with self._pool_lock:
            pool = self._packed_pools.setdefault(key, deque())
            if pool:
                return pool.popleft()
        return (
            np.zeros((bucket, layout.y_blocks, layout.k), np.int16),
            np.zeros((bucket, layout.c_blocks, layout.k), np.int16),
            np.zeros((bucket, layout.c_blocks, layout.k), np.int16),
        )

    def _return_packed(self, bucket: int, layout, bufs) -> None:
        key = (bucket, layout.y_blocks, layout.c_blocks, layout.k)
        with self._pool_lock:
            pool = self._packed_pools.setdefault(key, deque())
            if len(pool) <= self.max_inflight:
                pool.append(bufs)

    # -- ingest -----------------------------------------------------------
    @property
    def image_size(self) -> int:
        from sitewhere_tpu.models.vit import VIT_B16, VIT_TINY_TEST

        return (VIT_TINY_TEST if self.tiny else VIT_B16).image_size

    async def submit_chunk(
        self,
        stream_id: str,
        seq: int,
        data: bytes,
        kind: str = "raw-rgb8",
    ) -> None:
        """One camera chunk: persisted to the stream store (playback
        parity) and — on the compressed wire — appended to the byte
        arena AS-IS (one memcpy, no pixel materialization; shed-oldest
        when full). Legacy path decodes straight into the frame ring.
        Malformed chunks are counted (``media_frames_bad_total``) and
        shed, never raised through the submit path."""
        if self.store_chunks:
            self.media.append_chunk(stream_id, seq, data)
        size = self.image_size
        if self.compressed:
            if kind == "raw-rgb8" and len(data) < size * size * 3:
                # torn/short raw chunk: drop at the edge — decode-stage
                # frombuffer would shear the whole batch
                self.metrics.counter("media_frames_bad_total").inc()
                return
            self._ring.append(data, kind, stream_id, seq, time.monotonic())
            self.metrics.counter(
                "media_wire_bytes_total", tenant=self.tenant
            ).inc(len(data))
            return
        # ---- legacy (kill-switch) path: decode at submit time ----
        if kind == "raw-rgb8":
            # validate BEFORE reserving a ring slot (a short chunk must
            # not consume/shear ring state)
            frame = self._decode_raw(data, size)
            if frame is None:
                return
        else:  # jpeg/png: PIL decode is CPU-bound — off the loop. u8 so
            # every frame shares the on-device normalization path
            try:
                frame = await asyncio.get_running_loop().run_in_executor(
                    None, self.media.decode_frame, data, size, "u8"
                )
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - torn/corrupt chunk: count,
                # shed, keep the submit path alive
                self.metrics.counter("media_frames_bad_total").inc()
                return
        self.metrics.counter(
            "media_wire_bytes_total", tenant=self.tenant
        ).inc(len(data))
        # reserve+commit run on the loop thread (no await between them)
        self._ring.reserve()[...] = frame
        self._ring.commit(stream_id, seq, time.monotonic(), len(data))

    def _decode_raw(self, data: bytes, size: int) -> Optional[np.ndarray]:
        n = size * size * 3
        if len(data) < n:
            # a torn/short chunk is counted and shed — the caller's bug
            # must not take the whole submit path (and pipeline) down
            self.metrics.counter("media_frames_bad_total").inc()
            return None
        # stays uint8: frames normalize ON DEVICE (classify_frames), so
        # host→device moves 1 byte/px instead of 4
        return np.frombuffer(data, np.uint8, n).reshape(size, size, 3)

    # -- lifecycle --------------------------------------------------------
    async def on_start(self) -> None:
        # classifier init (86M params for real B/16) runs OFF the loop —
        # a synchronous init would freeze every other tenant's pipeline
        # for its duration
        await asyncio.get_running_loop().run_in_executor(
            None, self.media._get_classifier, self.tiny
        )
        if self.compressed:
            from concurrent.futures import ThreadPoolExecutor

            from sitewhere_tpu.native import jpegwire as jw

            self._decode_pool = ThreadPoolExecutor(
                max_workers=self._decode_workers,
                thread_name_prefix=f"media-decode[{self.tenant}]",
            )
            # resolve the native build off the loop with a BOUNDED wait
            # (the common cold-cache cc run is a few hundred ms; a slow
            # or hung toolchain must not stall tenant start for the full
            # build timeout). An unresolved probe is not a verdict —
            # _decode_batch keeps re-probing nonblockingly and upgrades
            # when a late build lands; a DEFINITIVE failure stays PIL.
            self._native_ok = await asyncio.get_running_loop().run_in_executor(
                None, jw.jpegwire_lib, True, 10.0
            ) is not None
            self._native_resolved = jw.build_resolved()
            if self._native_resolved and not self._native_ok:
                self._warn_native_absent()
        # device-time/MFU attribution: per-frame analytic flops from the
        # classifier config (labeled per tenant — media pipelines are
        # per-tenant, and drop_labeled(tenant=...) reclaims the children)
        try:
            self._flops_per_frame = self.media.classifier_flops_per_frame(
                self.tiny
            )
        except Exception:  # noqa: BLE001 - attribution must not block start
            self._flops_per_frame = 0.0
        from sitewhere_tpu.runtime.metrics import MfuAccount

        self._mfu = MfuAccount(self.metrics, "vit_b16", tenant=self.tenant)
        self._task = asyncio.create_task(self._run(), name=self.name)

    async def on_stop(self) -> None:
        await cancel_and_wait(self._task)
        self._task = None
        if self._deliver_tasks:
            # bounded grace, then force-cancel: an in-flight publish
            # against a full topic whose consumer is already stopped
            # would otherwise hang the whole stop cascade
            _done, pending = await asyncio.wait(
                list(self._deliver_tasks), timeout=5.0
            )
            for t in pending:
                await cancel_and_wait(t)
        if self._decode_pool is not None:
            self._decode_pool.shutdown(wait=False, cancel_futures=True)
            self._decode_pool = None

    def _buckets(self) -> List[int]:
        """Static batch-shape ladder (XLA recompile avoidance, same
        playbook as the inference flush buckets): light traffic classifies
        at the smallest fitting shape instead of paying a full max_batch
        forward per frame."""
        out = [1]
        b = 4
        while b < self.max_batch:
            out.append(b)
            b *= 4
        out.append(self.max_batch)
        return out

    def _expected_layout(self, sub: int, k: int):
        """The coefficient layout one ``image_size`` frame decodes to at
        subsampling ``sub`` — prewarm compiles against it."""
        from sitewhere_tpu.ops.dct import layout_for

        return layout_for(self.image_size, self.image_size, sub, k)

    def prewarm(self) -> None:
        """Compile every bucket shape before timed traffic: the pixel
        ladder (raw chunks + PIL fallback) always; on the compressed
        wire also the coefficient variants — every batch bucket at full
        precision (k=64) plus the max-batch bucket across the truncation
        ladder (4:2:0, the camera default; an exotic subsampling pays
        one first-use compile instead)."""
        size = self.image_size
        for b in self._buckets():
            self.media.classify_frames(
                np.zeros((b, size, size, 3), np.uint8),
                top_k=self.top_k, tiny=self.tiny,
            )
        self._prewarmed = True
        if self.compressed and not self._native_ok and not self._native_resolved:
            # a prewarm invoked after the background build landed must
            # see it (start()'s bounded wait may have outrun cc)
            from sitewhere_tpu.native import jpegwire as jw

            if jw.build_resolved():
                self._native_resolved = True
                self._native_ok = jw.jpegwire_lib(wait=False) is not None
        if not (self.compressed and self._native_ok):
            return
        from sitewhere_tpu.ops.dct import COEF_BUCKETS

        variants = [(b, 64, 2) for b in self._buckets()]
        variants += [(self.max_batch, k, 2) for k in COEF_BUCKETS if k != 64]
        for b, k, sub in variants:
            lay = self._expected_layout(sub, k)
            y = np.zeros((b, lay.y_blocks, k), np.int16)
            c = np.zeros((b, lay.c_blocks, k), np.int16)
            self.media.topk_results(
                *self.media.classify_coeffs_dispatch(
                    y, c, c, lay, top_k=self.top_k, tiny=self.tiny
                )
            )
        # runtime shape-choice is pinned to this set, keyed (bucket, k,
        # SUBSAMPLING) — sub is part of the jit layout key too: partial
        # buckets ship full precision (k=64 — still the whole JPEG wire
        # win; the truncation diet engages at saturation, where batches
        # are max_batch) and a subsampling prewarm never compiled (4:4:4
        # on a prewarmed pipeline) rides the PIL path, instead of paying
        # a 20-40 s cold XLA compile mid-traffic
        self._warm_variants = set(variants)

    # -- batching loop ----------------------------------------------------
    async def _run(self) -> None:
        topic = media_classifications_topic(self.bus, self.tenant)
        frames_ctr = self.metrics.counter("media.frames_classified")
        lat = self.metrics.histogram("media.latency", unit="s")
        ring = self._ring
        while True:
            # wait for the first frame (clear-then-recheck: a commit
            # between the count check and the clear must not be missed)
            while ring.count == 0:
                ring.data_event.clear()
                if ring.count:
                    break
                await ring.data_event.wait()
            deadline = time.monotonic() + self.deadline_ms / 1000.0
            while ring.count < self.max_batch:
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    break
                ring.data_event.clear()
                if ring.count >= self.max_batch:
                    break
                try:
                    await asyncio.wait_for(ring.data_event.wait(), timeout)
                except asyncio.TimeoutError:
                    break
            await self._inflight.acquire()
            if self.compressed:
                entry = self._checkout_bytes(ring.peek_bytes(self.max_batch))
                buf, offs, lens = entry
                metas = ring.pop_into(buf, offs, lens, self.max_batch)
                if not metas:
                    self._inflight.release()
                    self._return_bytes(entry)
                    continue
                task = asyncio.create_task(
                    self._classify_compressed(
                        entry, metas, topic, frames_ctr, lat
                    )
                )
            else:
                # the batch leaves the ring as ONE contiguous slice copy
                # into a pooled staging buffer the classify task owns
                # until done
                staging = self._checkout_staging()
                metas = ring.pop_into(staging, self.max_batch)
                if not metas:
                    self._inflight.release()
                    self._return_staging(staging)
                    continue
                task = asyncio.create_task(
                    self._classify_and_publish(
                        staging, metas, topic, frames_ctr, lat
                    )
                )
            self._deliver_tasks.add(task)
            task.add_done_callback(self._deliver_tasks.discard)

    # -- compressed-wire decode + dispatch (executor side) ----------------
    def _pool_map(self, fn, jobs: list) -> list:
        """Fan decode jobs (contiguous per-worker frame RANGES, not one
        future per frame — future overhead at camera rate is real) over
        the decode pool and gather in order; tracks the in-flight gauge
        and counts submissions that queued behind a saturated pool
        (media.decode_backpressure)."""
        # local capture: on_stop may null the pool while a force-
        # cancelled classify's executor half is still running — abort
        # the batch instead of AttributeError into an unawaited future
        # (a shutdown pool's submit raises RuntimeError, same abort)
        pool = self._decode_pool
        if pool is None:
            raise RuntimeError("media decode pool stopped")
        with self._decode_lock:
            self._decode_inflight += len(jobs)
            if self._decode_inflight > self._decode_workers:
                self.metrics.counter("media.decode_backpressure").inc()
            self.metrics.gauge(
                "media_decode_inflight", tenant=self.tenant
            ).set(self._decode_inflight)
        try:
            futs = [pool.submit(fn, *j) for j in jobs]
            return [f.result() for f in futs]
        finally:
            with self._decode_lock:
                self._decode_inflight -= len(jobs)
                self.metrics.gauge(
                    "media_decode_inflight", tenant=self.tenant
                ).set(self._decode_inflight)

    def _ranges(self, n: int) -> List[Tuple[int, int]]:
        """Split ``n`` frames into up to ``decode_workers`` contiguous
        ranges (the decode pool's unit of work)."""
        w = min(self._decode_workers, n)
        step = (n + w - 1) // w
        return [(lo, min(lo + step, n)) for lo in range(0, n, step)]

    def _decode_batch(self, buf, offs, lens, metas):
        """Host decode stage for one popped batch (runs on an executor
        thread). Tries the native coefficient path first — ALL frames
        jpeg, native lib present, identical geometry at the classifier's
        frame size, and a coefficient payload no larger than raw pixels;
        otherwise decodes the whole batch to pixels (raw memcpy / PIL),
        counting native fallbacks and shedding malformed frames.

        Returns ``(mode, payload, keep_metas, codec)`` where mode is
        ``"coef"`` (payload = (packed y/cb/cr, layout, bucket)) or
        ``"pix"`` (payload = (staging, bucket))."""
        from sitewhere_tpu.native import jpegwire as jw
        from sitewhere_tpu.ops.dct import FrameLayout, coef_bucket

        n = len(metas)
        size = self.image_size
        kinds = [m[0] for m in metas]
        all_jpeg = all(k == "jpeg" for k in kinds)
        if not self._native_ok and not self._native_resolved:
            # start()'s bounded wait elapsed before the background build
            # finished — re-probe nonblockingly until the outcome is
            # definitive (a build landing late upgrades the pipeline)
            if jw.build_resolved():
                self._native_resolved = True
                self._native_ok = jw.jpegwire_lib(wait=False) is not None
                if not self._native_ok:
                    self._warn_native_absent()
        native_ok = self._native_ok and all_jpeg
        if native_ok and self._prewarmed and not self._warm_variants:
            # the pipeline prewarmed while native was absent, so NO
            # coefficient variant was ever compiled — a late-landing
            # build must not buy a 20-40 s cold XLA compile mid-traffic;
            # stay on PIL until an operator re-runs prewarm()
            native_ok = False
        if native_ok:
            # cheap SOF peek BEFORE committing to the coefficient path:
            # off-size/progressive/mixed-geometry streams must not pay a
            # full wasted entropy decode per batch just to discover the
            # mismatch and re-decode via PIL — and the subsampling mode
            # learned here sizes the chroma buffers correctly up front
            # (no misreading an oversized 4:2:0 as a 4:4:4 stream)
            peek0 = None
            for i in range(n):
                g = jw.peek_geometry(buf[offs[i] : offs[i] + lens[i]])
                if g is None or g[0] != size or g[1] != size or (
                    peek0 is not None and g != peek0
                ):
                    native_ok = False
                    break
                peek0 = g
            if native_ok and self._warm_variants and not any(
                v[2] == peek0[2] for v in self._warm_variants
            ):
                # prewarmed pipelines never compile a cold subsampling
                # mid-traffic (the jit layout key includes sub) — route
                # to the PIL path before paying the entropy decode
                native_ok = False
            if native_ok and peek0[2] == 1:
                if self._sub1_rejects >= 2:
                    # this 4:4:4 stream's payloads keep losing to raw —
                    # stop paying the entropy decode just to rediscover
                    # it (the PIL route below counts the fallback)
                    native_ok = False
                elif self._coef_sub == 2:
                    # first 4:4:4 stream: upgrade the cached mode so
                    # this batch already decodes into full-grid chroma
                    with self._pool_lock:
                        self._coef_sub = 1
                        self._coef_pool.clear()
        if native_ok:
            coefs = self._checkout_coefs()
            try:
                y, cb, cr = coefs
                infos: List = [None] * n

                def _entropy_range(lo: int, hi: int) -> None:
                    for i in range(lo, hi):
                        infos[i] = jw.decode_into(
                            buf[offs[i] : offs[i] + lens[i]],
                            y[i], cb[i], cr[i],
                        )

                self._pool_map(_entropy_range, self._ranges(n))
                geo = None
                kmax = 0
                ok = True
                for info in infos:
                    if info is None:
                        ok = False
                        break
                    g = (info.width, info.height, info.y_gw, info.y_gh,
                         info.c_gw, info.c_gh, info.sub)
                    if geo is None:
                        geo = g
                    elif g != geo:
                        ok = False
                        break
                    kmax = max(kmax, info.y_k, info.c_k)
                if ok and geo is not None and geo[0] == size and geo[1] == size:
                    k = coef_bucket(kmax)
                    bucket_n = next(b for b in self._buckets() if b >= n)

                    def _warm(kk: int) -> bool:
                        # shape pinning: the jit layout key includes k
                        # AND subsampling — a cold variant would compile
                        # 20-40 s mid-traffic (empty set = no prewarm =
                        # no restriction)
                        return not self._warm_variants or (
                            (bucket_n, kk, geo[6]) in self._warm_variants
                        )

                    if not _warm(k):
                        k = 64
                    layout = FrameLayout(*geo, k=k)
                    if _warm(k) and layout.wire_bytes(1) <= size * size * 3:
                        if geo[6] == 1:
                            self._sub1_rejects = 0
                        bucket = bucket_n
                        packed = self._checkout_packed(bucket, layout)
                        py, pcb, pcr = packed
                        np.copyto(py[:n], y[:n, : layout.y_blocks, :k])
                        np.copyto(pcb[:n], cb[:n, : layout.c_blocks, :k])
                        np.copyto(pcr[:n], cr[:n, : layout.c_blocks, :k])
                        return (
                            "coef", (packed, layout, bucket), metas,
                            f"dct{k}",
                        )
                    if geo[6] == 1:
                        # a 4:4:4 batch that lost the size guard (or has
                        # no warm shape): feed the hysteresis so the
                        # peek stage stops re-trying this stream
                        self._sub1_rejects += 1
            finally:
                self._return_coefs(coefs)
        # ---- pixel fallback: raw memcpy or PIL decode per frame ----
        pix = self._checkout_staging()
        keep = np.zeros(n, bool)
        n_fallback = 0
        pil_mask = np.zeros(n, bool)
        for i in range(n):
            if kinds[i] == "raw-rgb8":
                # length validated at submit; one slice-view reshape copy
                pix[i] = buf[offs[i] : offs[i] + size * size * 3].reshape(
                    size, size, 3
                )
                keep[i] = True
            else:
                if kinds[i] == "jpeg":
                    n_fallback += 1
                pil_mask[i] = True

        def _pil_range(lo: int, hi: int) -> None:
            for i in range(lo, hi):
                if not pil_mask[i]:
                    continue
                try:
                    pix[i] = self.media.decode_frame(
                        buf[offs[i] : offs[i] + lens[i]].tobytes(),
                        size, "u8",
                    )
                    keep[i] = True
                except Exception:  # noqa: BLE001 - torn/corrupt frame: shed
                    pass

        if pil_mask.any():
            try:
                self._pool_map(_pil_range, self._ranges(n))
            except BaseException:
                # an aborted pool fan-out (teardown) must hand the
                # pixel staging back before the batch unwinds
                self._return_staging(pix)
                raise
        n_bad = int(n - keep.sum())
        if n_bad:
            self.metrics.counter("media_frames_bad_total").inc(n_bad)
        if n_fallback:
            self.metrics.counter(
                "media_native_decode_fallback_total"
            ).inc(n_fallback)
        if not keep.any():
            self._return_staging(pix)
            return ("none", None, [], "pixels")
        if n_bad:
            sel = np.flatnonzero(keep)
            pix[: sel.shape[0]] = pix[sel]
            keep_metas = [metas[i] for i in sel]
        else:
            keep_metas = metas
        bucket = next(b for b in self._buckets() if b >= len(keep_metas))
        return ("pix", (pix, bucket), keep_metas, "pixels")

    def _decode_and_dispatch(self, entry, metas):
        """Decode stage + jit dispatch, one executor hop. Returns
        ``(pv, iv, plan_mode, payload, keep_metas, codec, wire_bytes,
        decode_s, dispatch_s, h2d_bytes, bucket)`` or None when every
        frame shed. ``dispatch_s`` times ONLY the jit dispatch call —
        the decode stage has its own figure, so the flightrec field
        keeps one meaning across the compressed and legacy legs."""
        buf, offs, lens = entry
        n = len(metas)
        wire_bytes = int(lens[:n].sum())
        t0 = time.perf_counter()
        mode, payload, keep_metas, codec = self._decode_batch(
            buf, offs, lens, metas
        )
        decode_s = time.perf_counter() - t0
        self.metrics.histogram(
            "media_decode_seconds", unit="s", tenant=self.tenant
        ).record(decode_s)
        if mode == "none":
            return None
        t_d = time.perf_counter()
        try:
            if mode == "coef":
                (py, pcb, pcr), layout, bucket = payload
                pv, iv = self.media.classify_coeffs_dispatch(
                    py, pcb, pcr, layout, top_k=self.top_k, tiny=self.tiny
                )
                h2d = py.nbytes + pcb.nbytes + pcr.nbytes
            else:
                pix, bucket = payload
                pv, iv = self.media.classify_frames_dispatch(
                    pix[:bucket], self.top_k, self.tiny
                )
                h2d = int(pix[:bucket].nbytes)
        except BaseException:
            # a failed dispatch must hand its staging back to the pool
            # (the caller only sees None/raise, never the payload)
            if mode == "coef":
                self._return_packed(payload[2], payload[1], payload[0])
            else:
                self._return_staging(payload[0])
            raise
        dispatch_s = time.perf_counter() - t_d
        self.metrics.counter(
            "media_h2d_bytes_total", tenant=self.tenant
        ).inc(h2d)
        return (pv, iv, mode, payload, keep_metas, codec, wire_bytes,
                decode_s, dispatch_s, h2d, bucket)

    async def _finish_classify(
        self,
        pv,
        iv,
        metas_sst: List[Tuple],   # (stream_id, seq, t0) per kept frame
        topic: str,
        frames_ctr,
        lat,
        bucket: int,
        t_disp1: float,
        dispatch_s: float,
        disp_end_wall_ms: float,
        codec: str,
        wire_bytes: int,
        decode_s: Optional[float] = None,
    ) -> None:
        """Shared classify tail (BOTH legs): materialize the dispatched
        top-k off the loop, record d2h-wait/overlap + device-time/MFU +
        the flightrec flush record, publish per-frame events.

        The readback materializes OFF the loop: is_ready would only
        prove the compute finished, not that the async d2h copy crossed
        the link — overlap is measured, not inferred (same rule as the
        scoring reaper's D2H_OVERLAP_EPS_S). The device window runs
        dispatch RETURN → top-k landed (the scoring path's device_s
        definition; the host decode/dispatch stages are NOT chip time),
        and on-device decode FLOPs stay OUT of the ViT MFU numerator
        (the model's flops_per_frame is the honest numerator; decode
        adds < 0.04% and is reported by bench config 5)."""
        loop = asyncio.get_running_loop()
        n = len(metas_sst)
        fn = self.media.topk_results
        if self.faultplan is not None:
            # chaos: the classify readback is a supervised fault domain
            # like the scoring lanes (hang/slow/late-fail inject here)
            fn = self.faultplan.wrap_callable(
                fn, f"vit_b16[{self.tenant}]", 0, "media"
            )
        t_wait = time.perf_counter()
        try:
            results = await asyncio.wait_for(
                loop.run_in_executor(None, fn, pv, iv, n),
                timeout=self._classify_deadline_s(),
            )
        except asyncio.TimeoutError:
            # classify deadline expired: drop the batch's frames (media
            # is lossy by design — intake already sheds oldest), count
            # the timeout against this tenant's classify lane, and
            # freeze the blackbox. The inflight permit releases in the
            # caller's finally, so the pipeline keeps classifying.
            key = f"vit_b16[{self.tenant}]"
            self.metrics.counter(
                "tpu_flush_timeout_total", family=key, slice="media"
            ).inc()
            self.metrics.counter("media.classify_timeouts").inc()
            if self.flightrec is not None:
                self.flightrec.record(
                    "flush", key,
                    ts_ms=disp_end_wall_ms,
                    rows=n, bucket=bucket, codec=codec,
                    wire_bytes=wire_bytes,
                    dispatch_s=round(dispatch_s, 6),
                    status="timeout",
                )
                self.flightrec.snapshot(
                    f"flush-timeout:{key}", family=key, lane="media",
                )
            self._record_error(
                "classify-timeout",
                TimeoutError(
                    f"classify readback blew its deadline "
                    f"({n} frames dropped)"
                ),
            )
            return
        waited_s = time.perf_counter() - t_wait
        self.metrics.histogram("media.d2h_wait", unit="s").record(waited_s)
        overlapped = waited_s < D2H_OVERLAP_EPS_S
        if overlapped:
            self.metrics.counter("media.d2h_overlapped").inc()
        device_s = time.perf_counter() - t_disp1
        # deadline history: the next classify's budget tracks this
        # tenant's observed dispatch→landed p99 (flush supervision)
        self._classify_p99.add(device_s)
        if self._mfu is not None and self._flops_per_frame:
            self._mfu.record(self._flops_per_frame * bucket, device_s)
        if self.flightrec is not None:
            # ts_ms marks the DISPATCH return, not this (post-resolution)
            # record call: the Chrome export anchors the host phases to
            # end and the device window to start at ts_ms
            extra = (
                {} if decode_s is None else {"decode_s": round(decode_s, 6)}
            )
            self.flightrec.record(
                "flush", f"vit_b16[{self.tenant}]",
                ts_ms=disp_end_wall_ms,
                rows=n, bucket=bucket,
                codec=codec,
                wire_bytes=wire_bytes,
                dispatch_s=round(dispatch_s, 6),
                d2h_wait_s=round(waited_s, 6),
                d2h_overlapped=overlapped,
                device_s=round(device_s, 6),
                status="ok",
                **extra,
            )
        now_mono = time.monotonic()
        now = time.time() * 1000.0
        for (stream_id, seq, t0), top in zip(metas_sst, results):
            payload_ev = {
                "type": "media_classification",
                "tenant": self.tenant,
                "stream_id": stream_id,
                "seq": seq,
                "top_k": top,
                "ts": now,
            }
            if self.state is LifecycleState.STARTED:
                await self.bus.publish(topic, payload_ev)
            else:  # teardown: the consumer may already be gone
                self.bus.publish_nowait(topic, payload_ev)
            lat.record(now_mono - t0)
        frames_ctr.inc(n)

    async def _classify_compressed(
        self, entry, metas, topic: str, frames_ctr, lat
    ) -> None:
        """Compressed-wire classify leg: decode stage + dispatch run in
        one executor hop; readback/materialize in a second (same overlap
        accounting as the legacy leg — the async d2h copy rides under
        the next batch's compute)."""
        payload = None
        layout = bucket = None
        mode = "none"
        try:
            loop = asyncio.get_running_loop()
            out = await loop.run_in_executor(
                None, self._decode_and_dispatch, entry, metas
            )
            self._return_bytes(entry)
            entry = None
            if out is None:
                return
            (pv, iv, mode, payload, keep_metas, codec, wire_bytes,
             decode_s, dispatch_s, h2d, bucket) = out
            if mode == "coef":
                layout = payload[1]
            t_disp1 = time.perf_counter()
            await self._finish_classify(
                pv, iv,
                [(m[1], m[2], m[3]) for m in keep_metas],
                topic, frames_ctr, lat, bucket,
                t_disp1, dispatch_s, time.time() * 1000.0,
                codec, wire_bytes, decode_s,
            )
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 - one bad batch must not
            # kill the classification loop
            self._record_error("classify", exc)
        finally:
            self._inflight.release()
            if entry is not None:
                self._return_bytes(entry)
            if mode == "coef" and payload is not None:
                self._return_packed(bucket, layout, payload[0])
            elif mode == "pix" and payload is not None:
                self._return_staging(payload[0])

    async def _classify_and_publish(
        self, staging: np.ndarray, metas: List[Tuple], topic: str, frames_ctr, lat
    ) -> None:
        try:
            # smallest fitting bucket shape; rows past n are whatever the
            # staging buffer held before (valid pixel data, results
            # sliced off) — no pad allocation, no concatenate
            n = len(metas)
            bucket = next(b for b in self._buckets() if b >= n)
            # jit dispatch off the loop (the classify output is a jit
            # result nothing donates — worker-thread materialization is
            # safe, see checkpoint.host_copy_params). staging[:bucket]
            # is one contiguous buffer → one contiguous host→device put;
            # concurrent classifies on pooled buffers overlap transfer
            # with the previous batch's compute. The d2h copy starts
            # inside the dispatch (copy_to_host_async — same async
            # treatment as the scoring reaper), so by materialize time
            # it has been riding under compute, not starting cold.
            loop = asyncio.get_running_loop()
            t_disp0 = time.perf_counter()
            pv, iv = await loop.run_in_executor(
                None, self.media.classify_frames_dispatch, staging[:bucket],
                self.top_k, self.tiny,
            )
            t_disp1 = time.perf_counter()
            self.metrics.counter(
                "media_h2d_bytes_total", tenant=self.tenant
            ).inc(int(staging[:bucket].nbytes))
            # shared tail: readback/overlap accounting, device-time/MFU,
            # flightrec, publish. wire_bytes = the bytes each chunk
            # ARRIVED as (jpeg/png on this path decoded at submit —
            # pixel bytes would disagree with media_wire_bytes_total by
            # the compression ratio).
            await self._finish_classify(
                pv, iv,
                [(m[0], m[1], m[2]) for m in metas],
                topic, frames_ctr, lat, bucket,
                t_disp1, t_disp1 - t_disp0, time.time() * 1000.0,
                "pixels", int(sum(m[3] for m in metas)),
            )
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 - one bad batch must not
            # kill the classification loop
            self._record_error("classify", exc)
        finally:
            self._inflight.release()
            self._return_staging(staging)
