"""Inbound processing: validate, enrich, re-emit for scoring/persistence.

Capability parity with the reference's service-inbound-processing (consume
decoded events; look up device + active assignment via device-management;
route unregistered devices to the registration topic; re-emit enriched
events — SURVEY.md §2.2/§3.1 [U]; reference mount empty, see provenance
banner).

Redesign: the lookup is an in-proc call into the tenant's
``DeviceManagement`` store (the reference pays a cached gRPC hop here);
enriched requests are materialized into typed events
(``core.events``) with the assignment/area/asset context attached, and
published to the inbound-events topic that the tpu-inference stage consumes.
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional

import numpy as np

from sitewhere_tpu.core.batch import MeasurementBatch
from sitewhere_tpu.core.events import (
    DeviceEvent,
    event_from_dict,
    now_ms,
)
from sitewhere_tpu.runtime.bus import EventBus, RetryingConsumer
from sitewhere_tpu.runtime.config import FaultTolerancePolicy
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent, cancel_and_wait
from sitewhere_tpu.runtime.metrics import MetricsRegistry
from sitewhere_tpu.services.device_management import DeviceManagement


class InboundProcessor(LifecycleComponent):
    """Per-tenant inbound stage: decoded-events → inbound-events."""

    def __init__(
        self,
        tenant: str,
        bus: EventBus,
        device_management: DeviceManagement,
        metrics: Optional[MetricsRegistry] = None,
        poll_batch: int = 1024,
        policy: Optional[FaultTolerancePolicy] = None,
        tracer=None,
        overload=None,
    ) -> None:
        super().__init__(f"inbound-processing[{tenant}]")
        self.tenant = tenant
        self.bus = bus
        self.dm = device_management
        self.metrics = metrics or MetricsRegistry()
        self.poll_batch = poll_batch
        self.tracer = tracer
        from sitewhere_tpu.runtime.overload import DeadlineGate
        from sitewhere_tpu.runtime.tracing import StageTimer

        self.stage_timer = StageTimer(tracer, self.metrics, tenant, "inbound")
        # overload control: expired work drops to the tenant's expired
        # topic here, before device lookups and the TPU leg spend on it
        self.deadline_gate = DeadlineGate(
            bus, tenant, "inbound", self.metrics, tracer=tracer,
            controller=overload,
        )
        self.retry = RetryingConsumer(
            bus, tenant, "inbound", self.group, policy=policy,
            metrics=self.metrics, tracer=tracer,
        )
        self._task: Optional[asyncio.Task] = None

    @property
    def group(self) -> str:
        return f"inbound-processing[{self.tenant}]"

    async def on_start(self) -> None:
        self.bus.subscribe(self.bus.naming.decoded_events(self.tenant), self.group)
        self._task = asyncio.create_task(self._run(), name=self.name)

    async def on_stop(self) -> None:
        await cancel_and_wait(self._task)
        self._task = None

    async def _run(self) -> None:
        # at-least-once: each item runs under the stage retry budget;
        # exhausted/poison items dead-letter instead of vanishing
        await self.retry.run(
            self.bus.naming.decoded_events(self.tenant),
            self._handle,
            self.poll_batch,
        )

    async def _handle(self, req) -> None:
        if self.deadline_gate.check(req):
            return  # expired: routed to the expired topic, budget saved
        if isinstance(req, MeasurementBatch):
            await self.process_batch(req)
        else:
            await self.process_request(req)

    async def process_batch(self, batch: MeasurementBatch) -> Optional[MeasurementBatch]:
        """Columnar fast path: validate/enrich a whole batch with ONE
        device+assignment lookup per unique device, not per row."""
        processed = self.metrics.counter("inbound.processed")
        unregistered = self.metrics.counter("inbound.unregistered")
        rejected = self.metrics.counter("inbound.rejected")
        import time as _time

        t0 = _time.time() * 1000.0
        if (
            batch.trace_ctx is None
            and self.tracer is not None
            and self.tracer.enabled_for(self.tenant)
        ):
            # netbus-published batches enter decoded-events without a
            # context (remote producer may predate tracing) — mint here so
            # the rest of the pipeline still traces them
            batch.trace_ctx = self.tracer.mint(
                self.tenant, source_topic="bus"
            )

        tokens = batch.device_tokens
        uniq, inverse = batch.token_index()
        asg_by_u = np.empty((len(uniq),), object)
        area_by_u = np.empty((len(uniq),), object)
        status = np.zeros((len(uniq),), np.int8)  # 0 ok, 1 unknown, 2 no-asg
        for i, tok in enumerate(uniq):
            if self.dm.get_device(str(tok)) is None:
                status[i] = 1
                asg_by_u[i] = area_by_u[i] = ""
                continue
            a = self.dm.active_assignment_for(str(tok))
            if a is None:
                status[i] = 2
                asg_by_u[i] = area_by_u[i] = ""
            else:
                asg_by_u[i] = a.token
                area_by_u[i] = a.area_token
        row_status = status[inverse]
        unknown_rows = np.nonzero(row_status == 1)[0]
        if unknown_rows.size:
            # unknown devices route to registration (low volume: one request
            # per unique unknown device, not per row — registration is
            # idempotent on the token)
            seen: set = set()
            for i in unknown_rows:
                tok = str(tokens[i])
                if tok in seen:
                    continue
                seen.add(tok)
                await self.bus.publish(
                    self.bus.naming.unregistered_devices(self.tenant),
                    {
                        "type": "measurement",
                        "device_token": tok,
                        "name": str(batch.names[i]) if batch.names is not None else "",
                        "value": float(batch.values[i]),
                        "event_ts": int(batch.event_ts[i]),
                    },
                )
            unregistered.inc(unknown_rows.size)
        rejected.inc(int((row_status == 2).sum()))
        keep = np.nonzero(row_status == 0)[0]
        if keep.size == 0:
            return None
        out = batch if keep.size == batch.n else batch.select(keep)
        out.assignment_tokens = asg_by_u[inverse][keep] if keep.size != batch.n \
            else asg_by_u[inverse]
        out.area_tokens = area_by_u[inverse][keep] if keep.size != batch.n \
            else area_by_u[inverse]
        self.stage_timer.observe(
            out, t0, _time.time() * 1000.0, n_events=int(keep.size),
            unregistered=int(unknown_rows.size),
        )
        out.mark("inbound")
        await self.bus.publish(self.bus.naming.inbound_events(self.tenant), out)
        processed.inc(keep.size)
        return out

    async def process_request(self, req: Dict) -> Optional[DeviceEvent]:
        """Process one decoded request; returns the enriched event if one
        was emitted (None for registrations / rejects)."""
        processed = self.metrics.counter("inbound.processed")
        unregistered = self.metrics.counter("inbound.unregistered")
        rejected = self.metrics.counter("inbound.rejected")

        rtype = req.get("type", "measurement")
        if rtype == "register":
            await self.bus.publish(
                self.bus.naming.unregistered_devices(self.tenant), req
            )
            unregistered.inc()
            return None

        device_token = req.get("device_token", "")
        device = self.dm.get_device(device_token)
        if device is None:
            # unknown device → registration pipeline decides (SURVEY.md §3.1)
            await self.bus.publish(
                self.bus.naming.unregistered_devices(self.tenant), dict(req)
            )
            unregistered.inc()
            return None
        assignment = self.dm.active_assignment_for(device_token)
        if assignment is None:
            rejected.inc()
            return None

        import time as _time

        t0 = _time.time() * 1000.0
        enriched = dict(req)
        enriched.pop("_source", None)
        trace_ctx = enriched.pop("_trace", None)
        deadline = enriched.pop("_deadline", None)
        enriched["tenant"] = self.tenant
        enriched["assignment_token"] = assignment.token
        enriched["area_token"] = assignment.area_token
        enriched["asset_token"] = assignment.asset_token
        enriched["customer_token"] = assignment.customer_token
        enriched.setdefault("received_ts", now_ms())
        try:
            event = event_from_dict(enriched)
        except (ValueError, KeyError):
            rejected.inc()
            return None
        event.trace_ctx = trace_ctx
        if deadline is not None:
            event.deadline_ms = float(deadline)
        self.stage_timer.observe(event, t0, _time.time() * 1000.0)
        event.mark("inbound")
        await self.bus.publish(
            self.bus.naming.inbound_events(self.tenant), event
        )
        processed.inc()
        return event
