"""Inbound processing: validate, enrich, re-emit for scoring/persistence.

Capability parity with the reference's service-inbound-processing (consume
decoded events; look up device + active assignment via device-management;
route unregistered devices to the registration topic; re-emit enriched
events — SURVEY.md §2.2/§3.1 [U]; reference mount empty, see provenance
banner).

Redesign: the lookup is an in-proc call into the tenant's
``DeviceManagement`` store (the reference pays a cached gRPC hop here);
enriched requests are materialized into typed events
(``core.events``) with the assignment/area/asset context attached, and
published to the inbound-events topic that the tpu-inference stage consumes.
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional

from sitewhere_tpu.core.events import (
    DeviceEvent,
    event_from_dict,
    now_ms,
)
from sitewhere_tpu.runtime.bus import EventBus
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent, cancel_and_wait
from sitewhere_tpu.runtime.metrics import MetricsRegistry
from sitewhere_tpu.services.device_management import DeviceManagement


class InboundProcessor(LifecycleComponent):
    """Per-tenant inbound stage: decoded-events → inbound-events."""

    def __init__(
        self,
        tenant: str,
        bus: EventBus,
        device_management: DeviceManagement,
        metrics: Optional[MetricsRegistry] = None,
        poll_batch: int = 1024,
    ) -> None:
        super().__init__(f"inbound-processing[{tenant}]")
        self.tenant = tenant
        self.bus = bus
        self.dm = device_management
        self.metrics = metrics or MetricsRegistry()
        self.poll_batch = poll_batch
        self._task: Optional[asyncio.Task] = None

    @property
    def group(self) -> str:
        return f"inbound-processing[{self.tenant}]"

    async def on_start(self) -> None:
        self.bus.subscribe(self.bus.naming.decoded_events(self.tenant), self.group)
        self._task = asyncio.create_task(self._run(), name=self.name)

    async def on_stop(self) -> None:
        await cancel_and_wait(self._task)
        self._task = None

    async def _run(self) -> None:
        src = self.bus.naming.decoded_events(self.tenant)
        while True:
            requests = await self.bus.consume(src, self.group, self.poll_batch)
            for req in requests:
                await self.process_request(req)

    async def process_request(self, req: Dict) -> Optional[DeviceEvent]:
        """Process one decoded request; returns the enriched event if one
        was emitted (None for registrations / rejects)."""
        processed = self.metrics.counter("inbound.processed")
        unregistered = self.metrics.counter("inbound.unregistered")
        rejected = self.metrics.counter("inbound.rejected")

        rtype = req.get("type", "measurement")
        if rtype == "register":
            await self.bus.publish(
                self.bus.naming.unregistered_devices(self.tenant), req
            )
            unregistered.inc()
            return None

        device_token = req.get("device_token", "")
        device = self.dm.get_device(device_token)
        if device is None:
            # unknown device → registration pipeline decides (SURVEY.md §3.1)
            await self.bus.publish(
                self.bus.naming.unregistered_devices(self.tenant), dict(req)
            )
            unregistered.inc()
            return None
        assignment = self.dm.active_assignment_for(device_token)
        if assignment is None:
            rejected.inc()
            return None

        enriched = dict(req)
        enriched.pop("_source", None)
        enriched["tenant"] = self.tenant
        enriched["assignment_token"] = assignment.token
        enriched["area_token"] = assignment.area_token
        enriched["asset_token"] = assignment.asset_token
        enriched["customer_token"] = assignment.customer_token
        enriched.setdefault("received_ts", now_ms())
        try:
            event = event_from_dict(enriched)
        except (ValueError, KeyError):
            rejected.inc()
            return None
        event.mark("inbound")
        await self.bus.publish(
            self.bus.naming.inbound_events(self.tenant), event
        )
        processed.inc()
        return event
