"""Event persistence stage: scored-events → EventStore → outbound-events.

Capability parity with the reference's event-persistence pipeline inside
service-event-management (batch insert loop → TSDB → re-emit enriched
events to the outbound topic for rules/connectors — SURVEY.md §3.1 [U];
reference mount empty, see provenance banner).
"""

from __future__ import annotations

import asyncio
from typing import Optional

from sitewhere_tpu.core.batch import MeasurementBatch
from sitewhere_tpu.runtime.bus import EventBus, RetryingConsumer
from sitewhere_tpu.runtime.config import FaultTolerancePolicy
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent, cancel_and_wait
from sitewhere_tpu.runtime.metrics import MetricsRegistry
from sitewhere_tpu.services.event_store import EventStore


class EventPersistence(LifecycleComponent):
    """Per-tenant persistence stage."""

    def __init__(
        self,
        tenant: str,
        bus: EventBus,
        store: EventStore,
        metrics: Optional[MetricsRegistry] = None,
        poll_batch: int = 4096,
        policy: Optional[FaultTolerancePolicy] = None,
        tracer=None,
        overload=None,
    ) -> None:
        super().__init__(f"event-persistence[{tenant}]")
        self.tenant = tenant
        self.bus = bus
        self.store = store
        self.metrics = metrics or MetricsRegistry()
        self.poll_batch = poll_batch
        from sitewhere_tpu.runtime.overload import DeadlineGate
        from sitewhere_tpu.runtime.tracing import StageTimer

        self.stage_timer = StageTimer(
            tracer, self.metrics, tenant, "persistence"
        )
        # the store is the system of record: by default the gate only
        # OBSERVES lateness here (pipeline_deadline_late_total) — an
        # admitted event that made it this far persists regardless
        # (at-least-once beats deadline at the store boundary) unless
        # the tenant opted into strict mode
        pol = overload.policy_for(tenant) if overload is not None else None
        self.deadline_gate = DeadlineGate(
            bus, tenant, "persistence", self.metrics, tracer=tracer,
            controller=overload,
            drop=bool(pol.drop_expired_at_persist) if pol else False,
        )
        self.retry = RetryingConsumer(
            bus, tenant, "persistence", self.group,
            policy=policy, metrics=self.metrics, tracer=tracer,
        )
        # hoisted out of the per-item handler (hot path)
        self._out_topic = bus.naming.persisted_events(tenant)
        self._persisted = self.metrics.counter("event_management.persisted")
        # replay-to-rescore output: rows that are ALREADY rows of this
        # store come back around with fresh scores (pipeline/replay.py);
        # appending them again would duplicate history
        self._replay_rescored = self.metrics.counter(
            "replay_rescored_total", tenant=tenant
        )
        self._task: Optional[asyncio.Task] = None

    @property
    def group(self) -> str:
        return f"event-persistence[{self.tenant}]"

    async def on_start(self) -> None:
        self.bus.subscribe(self.bus.naming.scored_events(self.tenant), self.group)
        self._task = asyncio.create_task(self._run(), name=self.name)

    async def on_stop(self) -> None:
        await cancel_and_wait(self._task)
        self._task = None

    async def _run(self) -> None:
        await self.retry.run(
            self.bus.naming.scored_events(self.tenant),
            self._handle,
            self.poll_batch,
        )

    async def _handle(self, item) -> None:
        import time as _time

        if isinstance(item, MeasurementBatch) and "replay" in item.trace:
            # replayed rescore batch: its rows are the store's own rows
            # riding the scoring path again (docs/STORAGE.md "Replay").
            # Never re-append (zero duplicate history) and never re-fan
            # downstream (rules/outbound already fired on the original
            # pass; the scored topic carried the fresh scores to any
            # subscriber that wants them). The fresh scores DO write
            # back onto the sealed rows (copy-on-write overlays), so a
            # later rescore job's only_unscored dedupe skips them — no
            # re-publish of already-rescored history. Counted so
            # store ∪ replay accounting stays exact.
            if item.scores is not None and item.event_ids is not None:
                self.store.measurements.write_back_scores(
                    item.event_ids, item.scores
                )
            self._replay_rescored.inc(item.n)
            return
        if self.deadline_gate.check(item):
            return  # strict mode only; default gate never drops here
        t0 = _time.time() * 1000.0
        if isinstance(item, MeasurementBatch):
            # columnar fast path: ONE append + ONE re-publish per batch
            self.store.add_measurement_batch(item)
            self._persisted.inc(item.n)
            self.stage_timer.observe(
                item, t0, _time.time() * 1000.0, n_events=item.n
            )
            item.mark("persisted")
            await self.retry.publish(self._out_topic, item)
        else:
            self.store.add_event(item)
            self._persisted.inc()
            self.stage_timer.observe(item, t0, _time.time() * 1000.0)
            item.mark("persisted")
            await self.retry.publish(self._out_topic, item)
