"""L4 event pipeline: ingest → decode → inbound → tpu-inference → persist
→ rules → outbound, plus command delivery (SURVEY.md §3.1/§3.2).

Each stage is a lifecycle component consuming/producing bus topics; the
whole pipeline runs in one process over the in-proc bus (prod: Kafka shim
behind the same interface).
"""
