"""Event sources: protocol termination → decode → decoded-events topic.

Capability parity with the reference's service-event-sources
(``IInboundEventSource``/``IInboundEventReceiver`` + decoder chain; MQTT/
AMQP/CoAP/WebSocket receivers — SURVEY.md §2.2/§3.1 [U]; reference mount
empty, see provenance banner).

Redesign: receivers push raw payloads into an asyncio queue; an
``EventSource`` drains the queue, decodes, dedups, and publishes request
dicts to the tenant's decoded-events topic (failed decodes go to the
failed-decode topic with the raw payload attached). Network receivers are
pluggable: the in-proc queue the MQTT simulator (``sim.devices``) feeds,
and ``MqttReceiver`` — a real-socket MQTT 3.1.1 subscriber built on the
in-repo wire-protocol client (``comm.mqtt``).
"""

from __future__ import annotations

import asyncio
import base64
import json
import time
from typing import Any, Dict, List, Optional

from sitewhere_tpu.core.batch import MeasurementBatch
from sitewhere_tpu.core.events import now_ms
from sitewhere_tpu.pipeline.decoders import (
    Deduplicator,
    EventDecoder,
    get_decoder,
)
from sitewhere_tpu.runtime.bus import EventBus, RetryingConsumer
from sitewhere_tpu.runtime.config import FaultTolerancePolicy
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent, cancel_and_wait
from sitewhere_tpu.runtime.metrics import MetricsRegistry
from sitewhere_tpu.runtime.overload import (
    PRIORITY_NAMES,
    PriorityClassQueue,
    classify_priority,
)


class InboundReceiver(LifecycleComponent):
    """Base receiver: produces (payload: bytes, context: dict) pairs.

    Admission control (runtime.overload): the queue is priority-classed
    (alerts > commands > measurements, classified from cheap context
    hints). Under burst the lowest class sheds first at its fill
    watermark — a measurement flood can never evict an alert — and the
    measurement watermark shrinks with the tenant's credit signal when
    downstream stages lag (cooperative intake throttle)."""

    def __init__(self, name: str) -> None:
        super().__init__(name)
        self.queue = PriorityClassQueue(maxsize=65536)
        self.queue.on_shed = self._on_shed
        self.shed_total = 0
        # EventSource attaches the instance registry so sheds surface as
        # ``receiver_shed_total`` on the normal /metrics scrape
        self.metrics: Optional[MetricsRegistry] = None
        # EventSource installs a richer hook (tenant-labeled counters +
        # tail-trace visibility) on top of the local accounting
        self.shed_hook = None
        # set by EventSource when the tenant has tracing enabled: payloads
        # get a receive stamp so the decode span's queue-wait (time spent
        # in this receiver queue) is measurable. Guarded — an untraced
        # tenant's submit path stays allocation-identical to before.
        self.stamp_recv_ts = False

    def _on_shed(self, priority: int, n: int) -> None:
        self.shed_total += n
        if self.metrics is not None:
            self.metrics.counter("receiver_shed_total").inc(n)
        if self.shed_hook is not None:
            self.shed_hook(priority, n)

    async def submit(self, payload: bytes, **context: Any) -> None:
        if self.stamp_recv_ts:
            context["_recv_t"] = time.time() * 1000.0
        await self.queue.put(
            (payload, context), classify_priority(context)
        )

    def submit_nowait(self, payload: bytes, **context: Any) -> None:
        """Non-blocking submit for network receiver loops. A full class
        watermark sheds the OLDEST queued payload of the lowest present
        class (newest data wins under burst — counted, never raised
        into the receiver loop)."""
        if self.stamp_recv_ts:
            context["_recv_t"] = time.time() * 1000.0
        self.queue.put_nowait((payload, context), classify_priority(context))


class QueueReceiver(InboundReceiver):
    """In-proc receiver — the broker-less MQTT stand-in the simulator and
    tests feed directly. ``topic`` context mimics an MQTT topic string."""


class MqttReceiver(InboundReceiver):
    """MQTT receiver over a REAL socket: connects to any MQTT 3.1.1
    broker (external, or the in-repo ``comm.mqtt.MqttBroker``) with the
    in-repo wire-protocol client — no third-party MQTT stack needed."""

    def __init__(self, name: str, host: str = "localhost", port: int = 1883,
                 topics: Optional[List[str]] = None, qos: int = 0,
                 username: str = "", password: str = "") -> None:
        super().__init__(name)
        self.host, self.port = host, port
        self.topics = topics or ["sitewhere/input/#"]
        self.qos = qos
        self.username, self.password = username, password
        self._client = None

    async def on_start(self) -> None:
        from sitewhere_tpu.comm.mqtt import MqttClient

        client = MqttClient(self.host, self.port, client_id=self.name,
                            username=self.username, password=self.password)
        await client.connect()

        async def on_message(topic: str, payload: bytes) -> None:
            await self.submit(payload, topic=topic)

        for t in self.topics:
            await client.subscribe(t, on_message, qos=self.qos)
        self._client = client

    async def on_stop(self) -> None:
        if self._client is not None:
            await self._client.disconnect()
            self._client = None


class AmqpReceiver(InboundReceiver):
    """AMQP 0-9-1 receiver over a real socket (reference: RabbitMQ
    receivers in service-event-sources [U]): consumes wire payloads from
    the named queues with the in-repo protocol client (``comm.amqp``)."""

    def __init__(self, name: str, host: str = "localhost", port: int = 5672,
                 queues: Optional[List[str]] = None) -> None:
        super().__init__(name)
        self.host, self.port = host, port
        self.queues = queues or ["sitewhere.input"]
        self._client = None

    async def on_start(self) -> None:
        from sitewhere_tpu.comm.amqp import AmqpClient

        client = await AmqpClient(self.host, self.port).connect()

        async def on_message(body: bytes, queue: str) -> None:
            await self.submit(body, topic=f"amqp/{queue}")

        try:
            for q in self.queues:
                await client.queue_declare(q)
                await client.consume(q, on_message)
        except BaseException:
            # a failed subscribe must not leak the connected client (a
            # retrying supervisor would accumulate sockets)
            await client.close()
            raise
        self._client = client

    async def on_stop(self) -> None:
        if self._client is not None:
            await self._client.close()
            self._client = None


class SocketReceiver(InboundReceiver):
    """Raw TCP socket termination (reference: raw socket receivers in
    service-event-sources [U]): devices connect and send length-prefixed
    wire payloads (4-byte big-endian length + body, the simplest framing
    a constrained device can emit). Each frame is one payload for the
    tenant's decoder."""

    MAX_FRAME = 16 * 1024 * 1024

    def __init__(self, name: str, host: str = "127.0.0.1", port: int = 0) -> None:
        super().__init__(name)
        self.host, self.port = host, port
        self.bound_port = None
        self._server = None
        self._conns: set = set()

    async def on_start(self) -> None:
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port
        )
        self.bound_port = self._server.sockets[0].getsockname()[1]

    async def on_stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for t in list(self._conns):
            await cancel_and_wait(t)

    async def _serve(self, reader, writer) -> None:
        task = asyncio.current_task()
        self._conns.add(task)
        peer = writer.get_extra_info("peername")
        try:
            while True:
                head = await reader.readexactly(4)
                n = int.from_bytes(head, "big")
                if n == 0 or n > self.MAX_FRAME:
                    return  # malformed framing: drop the connection
                payload = await reader.readexactly(n)
                await self.submit(payload, topic=f"socket/{peer}")
        except (asyncio.IncompleteReadError, ConnectionResetError):
            return
        finally:
            self._conns.discard(task)
            writer.close()


class EventSource(LifecycleComponent):
    """One (receiver, decoder) pair publishing decoded event requests."""

    def __init__(
        self,
        source_id: str,
        tenant: str,
        bus: EventBus,
        receiver: InboundReceiver,
        decoder: EventDecoder | str = "json",
        metrics: Optional[MetricsRegistry] = None,
        dedup: bool = True,
        policy: Optional[FaultTolerancePolicy] = None,
        tracer=None,
        overload=None,
    ) -> None:
        super().__init__(f"event-source[{source_id}]")
        self.source_id = source_id
        self.tenant = tenant
        self.bus = bus
        self.receiver = receiver
        self.decoder = get_decoder(decoder) if isinstance(decoder, str) else decoder
        self.metrics = metrics or MetricsRegistry()
        self.dedup = Deduplicator() if dedup else None
        self._pump: Optional[asyncio.Task] = None
        receiver.metrics = self.metrics
        # overload control (runtime.overload.OverloadController | None):
        # admission watermarks + credit feedback on the receiver queue,
        # and the deadline budget stamped onto every accepted payload
        self.overload = overload
        self.metrics.describe(
            "pipeline_shed_total",
            "payloads shed at receiver admission, per tenant and "
            "priority class",
        )
        receiver.shed_hook = self._shed_hook
        if overload is not None:
            pol = overload.policy_for(tenant)
            if pol is not None:
                receiver.queue.fill = [
                    pol.shed_alerts_fill,
                    pol.shed_commands_fill,
                    pol.shed_measurements_fill,
                ]
                receiver.queue.credit_fn = lambda: overload.credit(tenant)
            if overload.deadline_ms(tenant) is not None:
                # the deadline budget is anchored at ADMISSION (receiver
                # enqueue), not decode — without the receive stamp a
                # decode-bound pump would grant queue-aged payloads the
                # full budget and the bounded-latency guarantee would
                # have a blind spot upstream of the bus lag signal
                receiver.stamp_recv_ts = True
        # THE trace mint edge: every ingest transport (in-proc broker,
        # real MQTT, HTTP, WS, CoAP, socket) funnels payloads through a
        # receiver into this source, so minting here covers them all
        self.tracer = tracer
        from sitewhere_tpu.runtime.tracing import StageTimer

        self.stage_timer = StageTimer(tracer, self.metrics, tenant, "decode")
        if tracer is not None and tracer.enabled_for(tenant):
            receiver.stamp_recv_ts = True
        # decode is the first at-least-once stage: publishes ride a retry
        # budget; undecodable payloads dead-letter to failed-decode
        self.retry = RetryingConsumer(
            bus, tenant, "decode", f"event-source[{source_id}]",
            policy=policy, metrics=self.metrics, tracer=tracer,
        )
        self.add_child(receiver)

    _last_shed_trace = 0.0

    def _shed_hook(self, priority: int, n: int) -> None:
        """Receiver sheds become observable: tenant+class-labeled
        counters always, plus a retained 'shed' trace (tail sampling)
        at most once per second per source — receiver shedding used to
        be invisible to tracing entirely."""
        self.metrics.counter(
            "pipeline_shed_total",
            tenant=self.tenant, priority=PRIORITY_NAMES[priority],
        ).inc(n)
        if self.overload is not None:
            self.overload.note_shed(self.tenant, n)
        tracer = self.tracer
        if tracer is None or not tracer.enabled_for(self.tenant):
            return
        now = time.time()
        if now - self._last_shed_trace < 1.0:
            return
        self._last_shed_trace = now
        ctx = tracer.mint(self.tenant, source_topic=f"shed:{self.source_id}")
        if ctx is not None:
            tracer.mark_hit(ctx, "shed")
            tracer.record_span(
                ctx, "receiver", now * 1000.0, now * 1000.0,
                n_events=n, terminal=True,
                priority=PRIORITY_NAMES[priority],
            )

    async def on_start(self) -> None:
        self._pump = asyncio.create_task(
            self._run(), name=f"pump:{self.name}"
        )

    async def on_stop(self) -> None:
        await cancel_and_wait(self._pump)
        self._pump = None

    # per-cycle caps → bound the columnar batch size. DRAIN caps raw
    # payloads; EVENT_CAP caps decoded EVENTS, so bulk/burst wire messages
    # (100s of samples each) can't snowball into monster batches that
    # destabilize downstream flush sizing
    DRAIN = 8192
    EVENT_CAP = 32768

    async def _run(self) -> None:
        decoded_topic = self.bus.naming.decoded_events(self.tenant)
        failed_topic = self.bus.naming.failed_decode(self.tenant)
        received = self.metrics.counter("event_sources.received")
        decoded_ctr = self.metrics.counter("event_sources.decoded")
        failed = self.metrics.counter("event_sources.failed_decode")
        duped = self.metrics.counter("event_sources.deduplicated")
        q = self.receiver.queue
        while True:
            # block for the first payload, then drain whatever is queued —
            # the columnar fast path forms one MeasurementBatch per cycle
            # instead of publishing per-event objects (SURVEY.md §7 step 1).
            # Payloads decode AS they drain so the event cap can stop the
            # cycle mid-queue.
            measurements: list = []
            # columnar accumulators (zero-dict decode fast path)
            c_toks: list = []
            c_names: list = []
            c_vals: list = []
            c_ets: list = []
            # array-chunk accumulator (bulk binary wire: zero per-row work)
            np_chunks: list = []
            decode_any = getattr(self.decoder, "decode_any", None)
            n_payloads = 0
            n_events = 0
            now = 0  # stamped AFTER the blocking get — idle wait must not
            # count toward the rows' ingest latency

            async def report_failed(payload, context, exc) -> None:
                failed.inc()
                # failed-decode IS the decode stage's dead-letter topic:
                # carry the same stage/attempt metadata the uniform DLQ
                # entries do, so the REST surface lists them together.
                # Non-blocking like every DLQ write: an idle requeue
                # cursor must never backpressure the decode pump shut
                self.bus.publish_nowait(
                    failed_topic,
                    {
                        "stage": "decode",
                        "tenant": self.tenant,
                        "attempts": 1,  # decode is deterministic: poison
                        "source": self.source_id,
                        "error": f"{type(exc).__name__}: {exc}",
                        "payload_b64": base64.b64encode(payload).decode(),
                        "context": {k: str(v) for k, v in context.items()},
                        "ts": now,
                    },
                )

            item = await q.get()
            now = now_ms()
            first_context = item[1]  # decode-span baggage + queue wait
            while True:
                payload, context = item
                n_payloads += 1
                try:
                    if decode_any is not None:
                        kind, out = decode_any(payload, context)
                    else:
                        kind, out = "requests", self.decoder.decode(payload, context)
                except Exception as exc:  # noqa: BLE001 - any bad payload (incl.
                    # UnicodeDecodeError from garbled bytes) must not kill the pump
                    await report_failed(payload, context, exc)
                    kind, out = "requests", []
                if kind == "columns":
                    toks, names, vals, ets = out
                    c_toks.extend(toks)
                    c_names.extend(names)
                    c_vals.extend(vals)
                    c_ets.extend(ets)
                    n_events += len(vals)
                elif kind == "columns_np":
                    np_chunks.extend(out)
                    n_events += sum(len(c[2]) for c in out)
                else:
                    n_events += len(out)
                    await self._route_requests(
                        out, measurements, decoded_topic, duped, decoded_ctr, now
                    )
                if n_events >= self.EVENT_CAP or n_payloads >= self.DRAIN:
                    break
                try:
                    item = q.get_nowait()
                except asyncio.QueueEmpty:
                    break
            received.inc(n_payloads)
            out_batches = []
            # batch construction must not kill the pump on one malformed
            # row (e.g. a string value the decoder didn't vet) — drop the
            # offending group to the failed topic instead
            if np_chunks:
                try:
                    out_batches.append(MeasurementBatch.from_column_chunks(
                        self.tenant, np_chunks, received_ms=float(now),
                    ))
                except Exception as exc:  # noqa: BLE001
                    await report_failed(b"<bulk chunk batch>", {}, exc)
            if c_vals:
                try:
                    out_batches.append(MeasurementBatch.from_columns(
                        self.tenant, c_toks, c_names, c_vals, c_ets,
                        received_ms=float(now),
                    ))
                except Exception as exc:  # noqa: BLE001
                    await report_failed(b"<columnar batch>", {}, exc)
            if measurements:
                try:
                    out_batches.append(
                        MeasurementBatch.from_requests(self.tenant, measurements)
                    )
                except Exception:  # noqa: BLE001 - salvage: re-try row by
                    # row so one bad request doesn't drop its whole group
                    good = []
                    for req in measurements:
                        try:
                            float(req.get("value", 0.0))
                            float(req.get("event_ts", now))
                            good.append(req)
                        except (TypeError, ValueError) as exc:
                            await report_failed(
                                json.dumps(req, default=str).encode(), {}, exc
                            )
                    if good:
                        out_batches.append(
                            MeasurementBatch.from_requests(self.tenant, good)
                        )
            t_done = time.time() * 1000.0
            src_topic = str(first_context.get("topic", self.source_id))
            recv_t = first_context.get("_recv_t")
            queue_wait = max(0.0, float(now) - recv_t) if recv_t else 0.0
            traced = self.tracer is not None and self.tracer.enabled_for(
                self.tenant
            )
            # admission deadline: accepted work gets `admission + budget`
            # from the tenant's OverloadPolicy — anchored at the receiver
            # enqueue stamp when present so receiver-queue wait spends
            # budget too; every downstream stage consults the remainder
            # (runtime.overload.DeadlineGate)
            budget = (
                self.overload.deadline_ms(self.tenant)
                if self.overload is not None
                else None
            )
            deadline_base = float(recv_t) if recv_t else float(now)
            for mb in out_batches:
                if budget is not None:
                    mb.deadline_ms = deadline_base + budget
                if traced:
                    # mint at the edge; the context rides the batch through
                    # every stage (and over the netbus wire, pickled)
                    dev = (
                        str(mb.device_tokens[0])
                        if mb.device_tokens is not None and mb.n
                        else ""
                    )
                    mb.trace_ctx = self.tracer.mint(
                        self.tenant, device=dev, source_topic=src_topic,
                        # the admission class rides the context so the
                        # latency ledger cohorts by (tenant, priority)
                        priority=PRIORITY_NAMES[
                            classify_priority(first_context)
                        ],
                    )
                # span recorded BEFORE the publish so the downstream
                # stage's span parents under this one deterministically
                self.stage_timer.observe(
                    mb, float(now), t_done, n_events=mb.n,
                    queue_wait_ms=queue_wait,
                )
                mb.mark("decoded")
                await self.retry.publish(decoded_topic, mb)
                decoded_ctr.inc(mb.n)

    async def _route_requests(
        self, reqs, measurements, decoded_topic, duped, decoded_ctr, now
    ) -> None:
        """Non-columnar requests: dedup, split measurements (batched later)
        from other event types (published as objects immediately)."""
        for req in reqs:
            rid = req.get("id")
            if self.dedup and rid and self.dedup.seen(str(rid)):
                duped.inc()
                continue
            req.setdefault("received_ts", now)
            if req.get("type", "measurement") == "measurement":
                measurements.append(req)
            else:
                req["_source"] = self.source_id
                if self.overload is not None:
                    budget = self.overload.deadline_ms(self.tenant)
                    if budget is not None:
                        # non-measurement events never expire (DeadlineGate
                        # skips them) but carry the stamp for observability
                        req["_deadline"] = float(now) + budget
                if "_trace" not in req and self.tracer is not None:
                    ev_type = str(req.get("type", ""))
                    ctx = self.tracer.mint(
                        self.tenant,
                        device=str(req.get("device_token", "")),
                        source_topic=self.source_id,
                        priority=(
                            "alert" if "alert" in ev_type else "command"
                        ),
                    )
                    if ctx is not None:  # None = tracing disabled: no key
                        req["_trace"] = ctx
                await self.retry.publish(decoded_topic, req)
                decoded_ctr.inc()


def make_source(
    source_id: str,
    tenant: str,
    bus: EventBus,
    decoder: str = "json",
    metrics: Optional[MetricsRegistry] = None,
) -> EventSource:
    """Convenience: an EventSource over a fresh QueueReceiver."""
    return EventSource(
        source_id, tenant, bus, QueueReceiver(f"recv[{source_id}]"), decoder, metrics
    )
