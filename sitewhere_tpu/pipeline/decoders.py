"""Payload decoders/encoders: device wire formats ↔ typed events.

Capability parity with the reference's event decoders
(``IDeviceEventDecoder`` impls in service-event-sources: JSON, SiteWhere
protobuf, Groovy-scripted — SURVEY.md §2.2 [U]; reference mount empty, see
provenance banner). Redesign:

- **JSON**: the canonical dev/sim format — one event dict or
  ``{"device": ..., "events"/"requests": [...]}`` batches.
- **Binary**: a compact struct-packed format for constrained devices,
  standing in for the reference's device protobuf spec (`RegisterDevice`,
  `DeviceMeasurements`, ... — SURVEY.md §2.1 sitewhere-communication [U]).
  Fixed little-endian layout, no varints — cheap to decode in bulk.
- **Scripted**: a user-supplied Python callable (the Groovy analog) with a
  guarded execution wrapper.

Decoders return *requests* (dicts) rather than events so inbound processing
can attach identity (assignment, area, asset) before materialization.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Callable, Dict, List, Mapping, Optional, Protocol

from sitewhere_tpu.core.events import (
    AlertLevel,
    DeviceEvent,
    EventType,
    now_ms,
)
from sitewhere_tpu.native import parse_json_bulk


class DecodeError(ValueError):
    pass


class EventDecoder(Protocol):
    name: str

    def decode(self, payload: bytes, context: Optional[Mapping[str, Any]] = None) -> List[Dict[str, Any]]:
        """payload → list of event-request dicts (keys: type, device_token,
        plus per-type payload fields)."""
        ...


def _as_requests(obj: Any) -> List[Dict[str, Any]]:
    if isinstance(obj, list):
        out: List[Dict[str, Any]] = []
        for o in obj:
            out.extend(_as_requests(o))
        return out
    if not isinstance(obj, dict):
        raise DecodeError(f"expected object, got {type(obj).__name__}")
    if "events" in obj or "requests" in obj:
        device = obj.get("device") or obj.get("device_token", "")
        reqs = _as_requests(obj.get("events") or obj.get("requests"))
        for r in reqs:
            r.setdefault("device_token", device)
        return reqs
    obj.setdefault("type", EventType.MEASUREMENT.value)
    return [obj]


class JsonDecoder:
    """The canonical JSON wire format."""

    name = "json"

    def decode(self, payload: bytes, context=None) -> List[Dict[str, Any]]:
        try:
            obj = json.loads(payload)
        except json.JSONDecodeError as exc:
            raise DecodeError(f"bad JSON payload: {exc}") from exc
        reqs = _as_requests(obj)
        if context and context.get("device_token"):
            for r in reqs:
                r.setdefault("device_token", context["device_token"])
        return reqs

    def decode_any(self, payload: bytes, context=None):
        """ONE parse, two possible shapes: ``("columns", (toks, names,
        vals, ets))`` for pure-measurement payloads (no per-row dicts), or
        ``("requests", [dict, ...])`` for everything else. Payloads with
        client-supplied ids always take the request path so the
        Deduplicator sees them.

        The dominant bulk shape ({"device", "events": [...]}) parses in
        NATIVE code straight into columnar arrays (sitewhere_tpu.native);
        anything it can't take — including payloads with ids, per-event
        devices, or escapes — falls through to the general path below, so
        the native layer changes speed, never coverage."""
        fast = parse_json_bulk(payload)
        if fast is not None:
            device, name, vals, ets = fast
            if not device and context:
                device = str(context.get("device_token", ""))
            return "columns_np", [(device, name, vals, ets)]
        try:
            obj = json.loads(payload)
        except json.JSONDecodeError as exc:
            raise DecodeError(f"bad JSON payload: {exc}") from exc
        cols = self._columns_from_obj(obj, context)
        if cols is not None:
            return "columns", cols
        reqs = _as_requests(obj)
        if context and context.get("device_token"):
            for r in reqs:
                r.setdefault("device_token", context["device_token"])
        return "requests", reqs

    @staticmethod
    def _columns_from_obj(obj, context):
        if not isinstance(obj, dict):
            return None
        events = obj.get("events")
        if isinstance(events, list):
            device = obj.get("device") or obj.get("device_token") or (
                context.get("device_token", "") if context else ""
            )
            try:
                # C-driven comprehensions; `+ 0.0` rejects non-numeric
                # values here (TypeError) instead of crashing the batch
                # build later; any odd shape falls back to the general path
                vals = [e["value"] + 0.0 for e in events]
                names = [e.get("name", "") for e in events]
                toks = [e.get("device_token") or device for e in events]
                ets = [e.get("event_ts", 0) + 0.0 for e in events]
            except (KeyError, TypeError):
                return None
            if any(
                e.get("type", "measurement") != "measurement" or "id" in e
                for e in events
            ):
                return None
            return toks, names, vals, ets
        if obj.get("type", "measurement") == "measurement" and "id" not in obj:
            try:
                val = obj["value"] + 0.0
                ets = obj.get("event_ts", 0) + 0.0
            except (KeyError, TypeError):
                return None
            tok = obj.get("device_token") or (
                context.get("device_token", "") if context else ""
            )
            return [tok], [obj.get("name", "")], [val], [ets]
        return None


# -- binary format --------------------------------------------------------
# Header: magic u16 = 0x5754 ("TW"), version u8, msg_type u8,
#         device_token: u8 len + bytes. Then per-type body (LE):
#   MEASUREMENT (0): name (u8 len + bytes), value f64, event_ts u64
#   LOCATION    (1): lat f64, lon f64, elevation f64, event_ts u64
#   ALERT       (2): level u8, type (u8 len+bytes), message (u16 len+bytes),
#                    event_ts u64
#   REGISTER    (3): device_type_token (u8 len+bytes), area_token (u8+bytes)
#   ACK         (4): originating_event_id (u8+bytes), response (u16+bytes)
# Messages may be concatenated back-to-back in one payload.

MAGIC = 0x5754
_MSG_MEASUREMENT, _MSG_LOCATION, _MSG_ALERT, _MSG_REGISTER, _MSG_ACK = range(5)
# bulk burst: ONE message carries a device's buffered samples for one
# measurement name — the analog of the reference's multi-sample
# `DeviceMeasurements` protobuf (SURVEY.md §2.1 sitewhere-communication [U]).
#   body: name (u8 len+bytes), count u32, base_ts u64, stride_ms u32,
#         values f32[count] (LE)
_MSG_MEASUREMENTS_BULK = 5
_ALERT_LEVELS = [AlertLevel.INFO, AlertLevel.WARNING, AlertLevel.ERROR, AlertLevel.CRITICAL]


def _pack_str(s: str, wide: bool = False) -> bytes:
    b = s.encode()
    return struct.pack("<H" if wide else "<B", len(b)) + b


class _Reader:
    def __init__(self, data: bytes) -> None:
        self.data = data
        self.off = 0

    def u(self, fmt: str):
        size = struct.calcsize(fmt)
        if self.off + size > len(self.data):
            raise DecodeError("truncated binary payload")
        (v,) = struct.unpack_from(fmt, self.data, self.off)
        self.off += size
        return v

    def s(self, wide: bool = False) -> str:
        n = self.u("<H" if wide else "<B")
        if self.off + n > len(self.data):
            raise DecodeError("truncated string in binary payload")
        v = self.data[self.off : self.off + n].decode()
        self.off += n
        return v

    @property
    def more(self) -> bool:
        return self.off < len(self.data)


class BinaryDecoder:
    """Struct-packed compact format for constrained devices."""

    name = "binary"

    def decode_any(self, payload: bytes, context=None):
        """Columnar fast path: a payload made ENTIRELY of bulk-measurement
        messages decodes to ``("columns_np", [(device, name, values f32[k],
        event_ts f64[k]), ...])`` — numeric columns come straight off the
        wire via ``np.frombuffer``, zero per-row Python. Anything else
        falls back to the per-message request path. Parsing is inlined
        (no _Reader method dispatch): this runs once per wire payload at
        full ingest rate."""
        import numpy as np

        data = payload
        ln = len(data)
        off = 0
        unpack = struct.unpack_from
        chunks: List[tuple] = []
        while off < ln:
            if off + 4 > ln:
                raise DecodeError("truncated binary payload")
            magic, version, msg = unpack("<HBB", data, off)
            if magic != MAGIC:
                raise DecodeError("bad magic")
            if version != 1:
                raise DecodeError("unsupported binary version")
            if msg != _MSG_MEASUREMENTS_BULK:
                return "requests", self.decode(payload, context)
            off += 4
            dlen = data[off] if off < ln else 0
            off += 1
            nend = off + dlen
            if nend > ln:
                raise DecodeError("truncated string in binary payload")
            device = data[off:nend].decode()
            off = nend
            if off >= ln:
                raise DecodeError("truncated binary payload")
            nlen = data[off]
            off += 1
            nend = off + nlen
            if nend > ln:
                raise DecodeError("truncated string in binary payload")
            name = data[off:nend].decode()
            off = nend
            if off + 16 > ln:
                raise DecodeError("truncated binary payload")
            count, base_ts, stride = unpack("<IQI", data, off)
            off += 16
            nbytes = count * 4
            if off + nbytes > ln:
                raise DecodeError("truncated bulk values")
            vals = np.frombuffer(data, "<f4", count, off)
            off += nbytes
            ets = base_ts + stride * np.arange(count, dtype=np.float64)
            chunks.append((device, name, vals, ets))
        return "columns_np", chunks

    def decode(self, payload: bytes, context=None) -> List[Dict[str, Any]]:
        r = _Reader(payload)
        out: List[Dict[str, Any]] = []
        while r.more:
            if r.u("<H") != MAGIC:
                raise DecodeError("bad magic")
            version = r.u("<B")
            if version != 1:
                raise DecodeError(f"unsupported binary version {version}")
            msg = r.u("<B")
            device = r.s()
            if msg == _MSG_MEASUREMENTS_BULK:
                name = r.s()
                count = r.u("<I")
                base_ts = r.u("<Q")
                stride = r.u("<I")
                for j in range(count):
                    out.append(
                        {
                            "type": "measurement",
                            "device_token": device,
                            "name": name,
                            "value": r.u("<f"),
                            "event_ts": base_ts + j * stride,
                        }
                    )
            elif msg == _MSG_MEASUREMENT:
                out.append(
                    {
                        "type": "measurement",
                        "device_token": device,
                        "name": r.s(),
                        "value": r.u("<d"),
                        "event_ts": r.u("<Q"),
                    }
                )
            elif msg == _MSG_LOCATION:
                out.append(
                    {
                        "type": "location",
                        "device_token": device,
                        "latitude": r.u("<d"),
                        "longitude": r.u("<d"),
                        "elevation": r.u("<d"),
                        "event_ts": r.u("<Q"),
                    }
                )
            elif msg == _MSG_ALERT:
                lvl = r.u("<B")
                out.append(
                    {
                        "type": "alert",
                        "device_token": device,
                        "level": _ALERT_LEVELS[min(lvl, 3)].value,
                        "alert_type": r.s(),
                        "message": r.s(wide=True),
                        "event_ts": r.u("<Q"),
                    }
                )
            elif msg == _MSG_REGISTER:
                out.append(
                    {
                        "type": "register",
                        "device_token": device,
                        "device_type_token": r.s(),
                        "area_token": r.s(),
                    }
                )
            elif msg == _MSG_ACK:
                out.append(
                    {
                        "type": "command_response",
                        "device_token": device,
                        "originating_event_id": r.s(),
                        "response": r.s(wide=True),
                    }
                )
            else:
                raise DecodeError(f"unknown binary message type {msg}")
        return out


def encode_measurement_binary(
    device_token: str, name: str, value: float, event_ts: Optional[int] = None
) -> bytes:
    return (
        struct.pack("<HBB", MAGIC, 1, _MSG_MEASUREMENT)
        + _pack_str(device_token)
        + _pack_str(name)
        + struct.pack("<dQ", value, event_ts if event_ts is not None else now_ms())
    )


def encode_measurements_bulk_binary(
    device_token: str,
    name: str,
    values,
    base_ts: Optional[int] = None,
    stride_ms: int = 1,
) -> bytes:
    """Encode a device's buffered burst of samples as ONE bulk message
    (values f32, timestamps base + i*stride) — the high-rate wire format."""
    import numpy as np

    arr = np.asarray(values, "<f4")
    return (
        struct.pack("<HBB", MAGIC, 1, _MSG_MEASUREMENTS_BULK)
        + _pack_str(device_token)
        + _pack_str(name)
        + struct.pack(
            "<IQI", arr.shape[0],
            base_ts if base_ts is not None else now_ms(), stride_ms,
        )
        + arr.tobytes()
    )


def encode_location_binary(
    device_token: str, lat: float, lon: float, elevation: float = 0.0,
    event_ts: Optional[int] = None,
) -> bytes:
    return (
        struct.pack("<HBB", MAGIC, 1, _MSG_LOCATION)
        + _pack_str(device_token)
        + struct.pack("<dddQ", lat, lon, elevation,
                      event_ts if event_ts is not None else now_ms())
    )


def encode_register_binary(
    device_token: str, device_type_token: str, area_token: str = ""
) -> bytes:
    return (
        struct.pack("<HBB", MAGIC, 1, _MSG_REGISTER)
        + _pack_str(device_token)
        + _pack_str(device_type_token)
        + _pack_str(area_token)
    )


class ScriptedDecoder:
    """User-scripted decoder (the reference's Groovy analog [U]): any
    callable ``(payload: bytes, context: dict) -> list[dict]``."""

    name = "scripted"

    def __init__(self, fn: Callable[[bytes, Dict[str, Any]], List[Dict[str, Any]]]) -> None:
        self._fn = fn

    def decode(self, payload: bytes, context=None) -> List[Dict[str, Any]]:
        try:
            reqs = self._fn(payload, dict(context or {}))
        except DecodeError:
            raise
        except Exception as exc:  # noqa: BLE001 - user code must not kill the source
            raise DecodeError(f"scripted decoder failed: {exc!r}") from exc
        if not isinstance(reqs, list):
            raise DecodeError("scripted decoder must return a list of requests")
        return reqs


DECODERS: Dict[str, Callable[[], EventDecoder]] = {
    "json": JsonDecoder,
    "binary": BinaryDecoder,
}


def get_decoder(name: str) -> EventDecoder:
    try:
        return DECODERS[name]()
    except KeyError:
        raise KeyError(f"unknown decoder '{name}' (known: {sorted(DECODERS)})") from None


class Deduplicator:
    """Drop repeated event ids within a sliding window of the last N ids
    (reference: deduplicators in event sources, SURVEY.md §2.2 [U])."""

    def __init__(self, capacity: int = 4096) -> None:
        self.capacity = capacity
        self._seen: Dict[str, None] = {}

    def seen(self, event_id: str) -> bool:
        if not event_id:
            return False
        if event_id in self._seen:
            return True
        self._seen[event_id] = None
        if len(self._seen) > self.capacity:
            self._seen.pop(next(iter(self._seen)))
        return False
