"""Command delivery: cloud → device command invocations.

Capability parity with the reference's service-command-delivery
(``ICommandDestination`` MQTT/CoAP/SMS destinations, command encoders
(protobuf/JSON), routing by device type, parameter extractors — SURVEY.md
§2.2/§3.2 [U]; reference mount empty, see provenance banner).

Flow (§3.2): command-invocations topic → look up device/type/command →
validate+encode → destination.deliver to the per-device topic; undeliverable
invocations go to the undelivered topic for inspection/retry.
"""

from __future__ import annotations

import asyncio
import json
import struct
from typing import Dict, List, Optional, Protocol

from sitewhere_tpu.core.events import DeviceCommandInvocation
from sitewhere_tpu.core.model import Device, DeviceCommand
from sitewhere_tpu.pipeline.decoders import MAGIC
from sitewhere_tpu.runtime.bus import EventBus
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent, cancel_and_wait
from sitewhere_tpu.runtime.metrics import MetricsRegistry
from sitewhere_tpu.services.device_management import DeviceManagement


class CommandEncodeError(ValueError):
    pass


def _coerce(value: str, ptype: str):
    try:
        if ptype == "double":
            return float(value)
        if ptype == "int64":
            return int(value)
        if ptype == "bool":
            return value.lower() in ("1", "true", "yes")
        return value
    except ValueError as exc:
        raise CommandEncodeError(f"parameter not a {ptype}: {value!r}") from exc


def validate_parameters(cmd: DeviceCommand, params: Dict[str, str]) -> Dict[str, object]:
    """Check required params + coerce types per the command signature."""
    out: Dict[str, object] = {}
    for p in cmd.parameters:
        name, ptype = p.get("name", ""), p.get("type", "string")
        required = str(p.get("required", "false")).lower() == "true"
        if name in params:
            out[name] = _coerce(params[name], ptype)
        elif required:
            raise CommandEncodeError(f"missing required parameter '{name}'")
    return out


class JsonCommandEncoder:
    """Canonical JSON command frame."""

    name = "json"

    def encode(
        self, inv: DeviceCommandInvocation, cmd: DeviceCommand, params: Dict[str, object]
    ) -> bytes:
        return json.dumps(
            {
                "command": cmd.name,
                "namespace": cmd.namespace,
                "invocation_id": inv.id,
                "parameters": params,
            },
            separators=(",", ":"),
        ).encode()


class BinaryCommandEncoder:
    """Compact binary frame matching the device binary spec family
    (``pipeline.decoders`` binary format; msg_type 0x10 = command)."""

    name = "binary"
    MSG_COMMAND = 0x10

    def encode(self, inv, cmd, params) -> bytes:
        body = json.dumps(params, separators=(",", ":")).encode()
        out = struct.pack("<HBB", MAGIC, 1, self.MSG_COMMAND)
        for s in (inv.device_token, cmd.name, inv.id):
            b = s.encode()
            out += struct.pack("<B", len(b)) + b
        out += struct.pack("<H", len(body)) + body
        return out


class CommandDestination(Protocol):
    async def deliver(self, device: Device, payload: bytes, inv: DeviceCommandInvocation) -> None: ...


class BrokerCommandDestination:
    """Publishes encoded commands to the per-device topic on the sim/MQTT
    broker (the reference's MQTT parameter-extractor destination [U])."""

    def __init__(self, broker, topic_pattern: str = "sitewhere/command/{device}") -> None:
        self.broker = broker
        self.topic_pattern = topic_pattern

    async def deliver(self, device: Device, payload: bytes, inv) -> None:
        await self.broker.publish(
            self.topic_pattern.format(device=device.token), payload
        )


class CollectingDestination:
    """Test/dev destination: collects (device_token, payload) pairs."""

    def __init__(self) -> None:
        self.deliveries: List[tuple] = []

    async def deliver(self, device: Device, payload: bytes, inv) -> None:
        self.deliveries.append((device.token, payload, inv.id))


class MqttCommandDestination:
    """Per-device command delivery over a REAL MQTT socket — the cloud→
    device half of the wire loop (reference: the MQTT command destination
    + parameter extractor in service-command-delivery, SURVEY.md §3.2 [U];
    reference mount empty, see provenance banner).

    Built on the in-repo MQTT 3.1.1 client (``comm.mqtt.MqttClient``):
    connects lazily on first delivery, publishes the encoded frame to the
    per-device topic at QoS 1 (broker PUBACK confirms the handoff), and on
    any socket error drops the connection so the next invocation
    reconnects — the failed invocation itself rides the undelivered topic
    via CommandDelivery's normal fail path."""

    def __init__(
        self,
        host: str,
        port: int,
        topic_pattern: str = "sitewhere/{tenant}/command/{device}",
        username: str = "",
        password: str = "",
        qos: int = 1,
        client_id: str = "",
    ) -> None:
        self.host, self.port = host, port
        self.topic_pattern = topic_pattern
        self.username, self.password = username, password
        self.qos = qos
        self.client_id = client_id or f"cmd-dest-{id(self):x}"
        self._client = None
        self._lock = asyncio.Lock()

    CONNECT_TIMEOUT_S = 10.0

    async def _ensure(self):
        async with self._lock:
            if self._client is None:
                from sitewhere_tpu.comm.mqtt import MqttClient

                # bounded dial: a blackholed broker must not wedge the
                # serial delivery loop for the kernel TCP timeout while
                # holding the lock (failed invocations ride the
                # undelivered topic instead)
                self._client = await asyncio.wait_for(
                    MqttClient(
                        self.host, self.port, client_id=self.client_id,
                        username=self.username, password=self.password,
                    ).connect(),
                    self.CONNECT_TIMEOUT_S,
                )
            return self._client

    async def deliver(self, device: Device, payload: bytes, inv) -> None:
        client = await self._ensure()
        topic = self.topic_pattern.format(
            device=device.token, tenant=getattr(inv, "tenant", ""),
        )
        try:
            await client.publish(topic, payload, qos=self.qos)
        except Exception:
            # poisoned connection: tear down so the next deliver dials fresh
            self._client = None
            try:
                await client.disconnect()
            except Exception:  # noqa: BLE001 - already broken
                pass
            raise

    async def close(self) -> None:
        async with self._lock:
            if self._client is not None:
                await self._client.disconnect()
                self._client = None


class CoapCommandDestination:
    """Command delivery over CoAP/UDP (reference: the CoAP command
    destination [U]): POSTs the encoded frame to the device's own CoAP
    server at ``/command``. Device addressing comes from a resolver
    callable (default: the device's ``coap_host``/``coap_port`` metadata —
    registration can record the observed source address there)."""

    def __init__(self, resolver=None, path: str = "command",
                 timeout_s: float = 5.0) -> None:
        self.resolver = resolver or self._metadata_resolver
        self.path = path
        self.timeout_s = timeout_s

    @staticmethod
    def _metadata_resolver(device: Device):
        host = device.metadata.get("coap_host", "")
        port = device.metadata.get("coap_port", "")
        if not host or not port:
            raise CommandEncodeError(
                f"device '{device.token}' has no coap_host/coap_port metadata"
            )
        return host, int(port)

    async def deliver(self, device: Device, payload: bytes, inv) -> None:
        from sitewhere_tpu.comm.coap import CoapClient

        host, port = self.resolver(device)
        code = await CoapClient(host, port).post(
            self.path, payload,
            queries={"invocation": inv.id},
            timeout_s=self.timeout_s,
        )
        if (code >> 5) != 2:  # not a 2.xx success class
            raise ConnectionError(
                f"CoAP command POST to {host}:{port} returned "
                f"{code >> 5}.{code & 0x1F:02d}"
            )


class CommandDelivery(LifecycleComponent):
    """Per-tenant command-delivery stage."""

    def __init__(
        self,
        tenant: str,
        bus: EventBus,
        device_management: DeviceManagement,
        destination: CommandDestination,
        encoder: str = "json",
        metrics: Optional[MetricsRegistry] = None,
        poll_batch: int = 1024,
    ) -> None:
        super().__init__(f"command-delivery[{tenant}]")
        self.tenant = tenant
        self.bus = bus
        self.dm = device_management
        self.destination = destination
        self.encoder = (
            JsonCommandEncoder() if encoder == "json" else BinaryCommandEncoder()
        )
        self.metrics = metrics or MetricsRegistry()
        self.poll_batch = poll_batch
        self._task: Optional[asyncio.Task] = None

    @property
    def group(self) -> str:
        return f"command-delivery[{self.tenant}]"

    async def on_start(self) -> None:
        self.bus.subscribe(
            self.bus.naming.command_invocations(self.tenant), self.group
        )
        self._task = asyncio.create_task(self._run(), name=self.name)

    async def on_stop(self) -> None:
        await cancel_and_wait(self._task)
        self._task = None
        close = getattr(self.destination, "close", None)
        if close is not None:  # real-wire destinations own a socket
            await close()

    async def _run(self) -> None:
        src = self.bus.naming.command_invocations(self.tenant)
        while True:
            invocations = await self.bus.consume(src, self.group, self.poll_batch)
            for inv in invocations:
                await self.deliver_invocation(inv)

    async def deliver_invocation(self, inv: DeviceCommandInvocation) -> bool:
        delivered = self.metrics.counter("command_delivery.delivered")
        undelivered = self.metrics.counter("command_delivery.undelivered")

        async def fail(reason: str) -> bool:
            undelivered.inc()
            await self.bus.publish(
                self.bus.naming.undelivered_commands(self.tenant),
                {"invocation": inv.to_dict(), "reason": reason},
            )
            return False

        device = self.dm.get_device(inv.device_token)
        if device is None:
            return await fail(f"unknown device '{inv.device_token}'")
        dtype = self.dm.get_device_type(device.device_type_token)
        if dtype is None:
            return await fail(f"unknown device type '{device.device_type_token}'")
        cmd = dtype.command_by_token(inv.command_token) or next(
            (c for c in dtype.commands if c.name == inv.command_token), None
        )
        if cmd is None:
            return await fail(f"unknown command '{inv.command_token}'")
        try:
            params = validate_parameters(cmd, inv.parameters)
            payload = self.encoder.encode(inv, cmd, params)
        except CommandEncodeError as exc:
            return await fail(str(exc))
        try:
            await self.destination.deliver(device, payload, inv)
        except Exception as exc:  # noqa: BLE001
            self._record_error("deliver", exc)
            return await fail(f"destination error: {exc!r}")
        delivered.inc()
        return True
