"""Replay-to-rescore engine: stream the segment store back through the
pipeline at wire speed (ROADMAP item 5; docs/STORAGE.md "Replay").

A :class:`ReplayJob` names a tenant, a time/seq window, and a target:

- ``rescore`` — replayed batches publish to the tenant's inbound-events
  topic, so they ride the IDENTICAL feed path as live traffic: the
  scoring loop's ``_LaneRing`` staging, the double-buffered h2d prefetch,
  the device-side gather, and the async-D2H completion reaper (PR 4/5).
  Scored output lands on the scored-events topic like any live batch;
  the persistence stage recognizes the replay mark and does NOT append
  the rows again (they ARE the store). This is the DR path for PR 1's
  at-least-once story: rows that persisted unscored (outage, parked
  family) get their scores computed and re-emitted downstream.
- ``rules`` — already-scored history re-publishes to the persisted-events
  topic so the rule engine re-fires over it (alert backfill after a rule
  change).
- ``train`` — scored history publishes to the tenant's replay-train-feed
  topic: the feeder for on-device continual learning. The scoring
  loop's train-lane intake consumes it into per-(slot, data-shard)
  train rings, packs ``replay_microbatch``-row microbatches through the
  live staging → h2d wire, and runs fused stacked train steps over a
  separate train window state — windows beyond the resident serve
  state (docs/PERFORMANCE.md "Continual learning lane"). The feed
  topic is deliberately EXCLUDED from the overload credit signal
  (runtime.overload) — the consumer is itself credit-gated, so a
  parked train backlog must never throttle the tenant's serve path.

Mechanics:

- **planning** goes through the store's zone maps (``SegmentColumns.plan``)
  — segments outside the window are pruned without touching a row
  (``replay_segments_pruned_total``);
- **scanning** streams mmap'd column slices (``SegmentColumns.scan`` →
  ``slice_columns``) into ``MeasurementBatch`` construction with the
  vocab/inverse group index inherited for free — no per-event objects,
  no string sorts (tools/check_hotpath.py registers the path);
- a **bounded intake ring** (``_ReplayRing``, tools/check_queues.py) sits
  between the scanner and the publish pump, so a throttled pump
  backpressures the disk scan instead of buffering the store in memory;
- the pump is a **low-priority lane arbitrated by the PR 3 overload
  controller**: live traffic always wins credit — while the tenant's
  ``overload_credit`` is below 1.0 or any degradation rung is engaged,
  the pump parks (``replay_throttled_total``) and resumes only when the
  pressure clears;
- **dedupe**: ``rescore`` (without ``force``) skips rows whose stored
  score is already set — no row is double-scored — and the job's
  **cursor** (last raw seq covered) commits after each published batch
  with no await in between, so a crashed job resumes exactly: replayed ∪
  skipped accounting stays exact and nothing is lost or re-published;
- the cursor (plus accounting) persists to ``state_dir`` when the
  instance checkpoints, and ``resume_jobs`` restarts unfinished jobs.

Guarantee boundary: the cursor marks PUBLISHED, not scored-and-written-
back — scores land asynchronously at the persistence stage. A graceful
stop checkpoints the bus, so in-flight replayed batches survive the
restart and drain through scoring. A hard kill without a checkpoint can
leave a published window unscored past the cursor; those rows are still
NaN in the store, so the NEXT rescore job's ``only_unscored`` plan picks
them up — the recovery move is re-running the job, the same at-least-
once posture as the rest of the PR 1 delivery story. The same in-flight
window means a job that just finished may have scores still landing; a
back-to-back second rescore job can re-publish that boundary window
(idempotent — write-back overwrites with the same model's scores).
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from collections import deque
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

from sitewhere_tpu.core.batch import MeasurementBatch
from sitewhere_tpu.runtime.metrics import MetricsRegistry
from sitewhere_tpu.storage.segstore import slice_columns

REPLAY_TARGETS = ("rescore", "rules", "train")


class _ReplayRing:
    """Bounded intake ring between the segment scanner and the publish
    pump: prepared scan slices queue here, and a full ring backpressures
    the scanner (``replay.ring_backpressure``) instead of letting a
    throttled replay buffer the store into memory. Depth is the
    ``replay_ring_depth{tenant}`` gauge (tools/check_queues.py)."""

    def __init__(self, capacity: int, metrics: MetricsRegistry,
                 tenant: str) -> None:
        self.capacity = max(1, int(capacity))
        self._items: deque = deque()
        self._data = asyncio.Event()
        self._space = asyncio.Event()
        self._space.set()
        self._gauge = metrics.gauge("replay_ring_depth", tenant=tenant)
        self._backpressure = metrics.counter("replay.ring_backpressure")

    def qsize(self) -> int:
        return len(self._items)

    async def put(self, item) -> None:
        while len(self._items) >= self.capacity:
            self._backpressure.inc()
            self._space.clear()
            await self._space.wait()
        self._items.append(item)
        self._gauge.set(len(self._items))
        self._data.set()

    async def get(self):
        while not self._items:
            self._data.clear()
            await self._data.wait()
        item = self._items.popleft()
        self._gauge.set(len(self._items))
        self._space.set()
        return item


@dataclass
class ReplayJob:
    """One replay job's identity, window, cursor, and exact accounting."""

    job_id: str
    tenant: str
    target: str = "rescore"
    ts0: int = 0
    ts1: int = 0
    seq_lo: int = 0
    seq_hi: Optional[int] = None
    device: str = ""
    force: bool = False
    status: str = "running"      # running | paused | done | failed | cancelled
    cursor: int = 0              # next raw seq to cover (resume point)
    plan_seq_end: int = -1       # last raw seq the plan covers
    replayed: int = 0            # rows published
    skipped_dedupe: int = 0      # rows skipped: already scored (dedupe)
    throttled: int = 0           # pump park ticks (overload arbitration)
    segments_planned: int = 0
    segments_pruned: int = 0     # zone-map pruned, zero rows touched
    bytes_read: int = 0
    started_ms: float = field(default_factory=lambda: time.time() * 1000.0)
    finished_ms: Optional[float] = None
    error: str = ""

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ReplayJob":
        known = cls.__dataclass_fields__
        return cls(**{k: v for k, v in d.items() if k in known})

    def report(self) -> dict:
        out = self.to_dict()
        end = self.finished_ms or time.time() * 1000.0
        dt_s = max((end - self.started_ms) / 1000.0, 1e-9)
        out["ev_s"] = round(self.replayed / dt_s, 1)
        span = max(self.plan_seq_end - self.seq_lo + 1, 1)
        remaining = max(self.plan_seq_end - self.cursor + 1, 0)
        out["lag_ratio"] = round(
            0.0 if self.status == "done" else min(remaining / span, 1.0), 4
        )
        return out


def _slice_to_batch(tenant: str, cols: Dict[str, object],
                    target: str) -> MeasurementBatch:
    """One scan slice's columns → a columnar MeasurementBatch. Vectorized
    end to end: numeric views pick, token columns fan out from the
    segment vocab AND hand the batch its group-index cache (no string
    sort downstream — the ``lookup_or_assign_bulk`` feed is free), ids
    come from the store so replayed identity matches persisted identity.
    The ``replay`` trace mark is the contract with the persistence stage
    (replayed rows are already rows of the store — never re-appended)."""
    n = int(len(cols["values"]))
    tok_u, tok_inv = cols["tok"]
    name_u, name_inv = cols["name"]
    tok_inv = np.ascontiguousarray(tok_inv, np.int32)
    name_inv = np.ascontiguousarray(name_inv, np.int32)
    asg = cols.get("asg")
    area = cols.get("area")
    batch = MeasurementBatch(
        tenant=tenant,
        stream_ids=np.zeros((n,), np.int32),
        values=np.ascontiguousarray(cols["values"], np.float32),
        event_ts=cols["event_ts"].astype(np.float64),
        received_ts=cols["received_ts"].astype(np.float64),
        valid=np.ones((n,), bool),
        event_ids=cols["event_ids"],
        device_tokens=(
            tok_u[tok_inv] if len(tok_u) else np.full((n,), "", object)
        ),
        names=(
            name_u[name_inv] if len(name_u) else np.full((n,), "", object)
        ),
        assignment_tokens=(
            asg[0][np.asarray(asg[1])] if asg is not None and len(asg[0])
            else None
        ),
        area_tokens=(
            area[0][np.asarray(area[1])] if area is not None and len(area[0])
            else None
        ),
        # rescore recomputes scores (fresh NaN column is created at lane
        # enqueue); rules/train re-emit the STORED scores
        scores=(
            None if target == "rescore"
            else np.ascontiguousarray(cols["scores"], np.float32)
        ),
        tok_index=(tok_u, tok_inv),
        name_index=(name_u, name_inv),
    )
    batch.mark("replay")  # the persistence-skip + provenance mark
    return batch


class ReplayEngine:
    """Owns replay jobs across tenants: planning, the scanner/pump task
    pair per job, overload arbitration, cursor persistence, metrics."""

    def __init__(
        self,
        bus,
        metrics: Optional[MetricsRegistry] = None,
        overload=None,
        flightrec=None,
        tracer=None,
        state_dir: Optional[str | Path] = None,
        batch_rows: int = 8192,
        ring_capacity: int = 4,
        throttle_tick_s: float = 0.02,
        max_finished: int = 64,
    ) -> None:
        self.bus = bus
        self.metrics = metrics or MetricsRegistry()
        self.overload = overload
        self.flightrec = flightrec
        # tracing hook (runtime.tracing.Tracer | None): replayed batches
        # re-enter the live feed path, so they mint their own contexts —
        # without one, every downstream span is silently skipped and the
        # latency ledgers lose the whole replay cohort
        self.tracer = tracer
        self.state_dir = Path(state_dir) if state_dir is not None else None
        if self.state_dir is not None:
            self.state_dir.mkdir(parents=True, exist_ok=True)
        self.batch_rows = int(batch_rows)
        self.ring_capacity = int(ring_capacity)
        self.throttle_tick_s = float(throttle_tick_s)
        self.max_finished = int(max_finished)
        self.jobs: Dict[str, ReplayJob] = {}
        self._tasks: Dict[str, List[asyncio.Task]] = {}
        m = self.metrics
        m.describe(
            "replay_events_total",
            "rows replayed from the segment store, per tenant and target",
        )
        m.describe(
            "replay_bytes_total",
            "segment-store column bytes streamed by replay, per tenant",
        )
        m.describe(
            "replay_segments_pruned_total",
            "segments skipped by zone-map planning (zero rows touched)",
        )
        m.describe(
            "replay_throttled_total",
            "replay pump park ticks while live traffic held the tenant's "
            "overload credit",
        )
        m.describe(
            "replay_lag_ratio",
            "active RESCORE job's unreplayed fraction of its planned seq "
            "span (0 = caught up / idle); rules/train backfills don't "
            "drive it — concurrent jobs would clobber the tenant gauge",
        )
        m.describe(
            "replay_ring_depth",
            "prepared replay batches queued between segment scanner and "
            "publish pump, per tenant",
        )
        m.describe(
            "replay_recovered_windows_total",
            "rescore jobs whose cursor was rewound on resume to re-cover "
            "a hard kill's published-but-unscored NaN window "
            "(resume_jobs recover_unscored=True)",
        )

    # -- job control -------------------------------------------------------
    def start_job(
        self,
        tenant: str,
        store,
        *,
        ts0: int = 0,
        ts1: int = 0,
        seq_lo: int = 0,
        seq_hi: Optional[int] = None,
        device: str = "",
        target: str = "rescore",
        force: bool = False,
        job: Optional[ReplayJob] = None,
    ) -> ReplayJob:
        """Plan + launch one replay job (or relaunch a resumed one)."""
        if target not in REPLAY_TARGETS:
            raise ValueError(
                f"unknown replay target '{target}' (one of {REPLAY_TARGETS})"
            )
        if job is None and target == "rescore":
            # one rescore job per tenant at a time: two concurrent jobs
            # over overlapping windows would each plan the same rows as
            # unscored (scores only write back at the persistence stage)
            # and double-publish them
            for j in self.jobs.values():
                if (
                    j.tenant == tenant and j.target == "rescore"
                    and j.status == "running"
                ):
                    raise ValueError(
                        f"tenant '{tenant}' already has a running rescore "
                        f"job ({j.job_id}); wait or cancel it first"
                    )
        resumed = job is not None
        if job is None:
            job = ReplayJob(
                job_id=f"rj-{uuid.uuid4().hex[:12]}",
                tenant=tenant, target=target, ts0=int(ts0), ts1=int(ts1),
                seq_lo=int(seq_lo), seq_hi=seq_hi, device=device,
                force=bool(force), cursor=int(seq_lo),
            )
        job.status = "running"
        # plan NOW (synchronous): the zone-map pruning result is part of
        # the job's identity and the REST response
        segments, pruned = store.measurements.plan(
            job.ts0, job.ts1, job.cursor, job.seq_hi, job.device
        )
        if not resumed:
            # a RESUMED job keeps its persisted plan accounting: the
            # re-plan from the committed cursor prunes segments the job
            # already replayed pre-crash, and counting those as
            # "zone-pruned, zero rows touched" would corrupt both the
            # report and replay_segments_pruned_total
            job.segments_planned = len(segments)
            job.segments_pruned = pruned
            job.plan_seq_end = max(
                (s.seq0 + s.n - 1 for s in segments),
                default=job.cursor - 1,
            )
            self.metrics.counter(
                "replay_segments_pruned_total", tenant=tenant
            ).inc(pruned)
        self.jobs[job.job_id] = job
        self._persist(job)
        if not segments:
            job.status = "done"
            job.finished_ms = time.time() * 1000.0
            self._persist(job)
            return job
        ring = _ReplayRing(self.ring_capacity, self.metrics, tenant)
        loop = asyncio.get_running_loop()
        self._tasks[job.job_id] = [
            loop.create_task(
                self._scan_loop(job, store, segments, ring),
                name=f"replay-scan[{job.job_id}]",
            ),
            loop.create_task(
                self._pump_loop(job, ring), name=f"replay-pump[{job.job_id}]"
            ),
        ]
        return job

    def report(self, job_id: str) -> Optional[dict]:
        job = self.jobs.get(job_id)
        return job.report() if job is not None else None

    def list_jobs(self, tenant: str = "") -> List[dict]:
        return [
            j.report() for j in self.jobs.values()
            if not tenant or j.tenant == tenant
        ]

    async def cancel_job(self, job_id: str) -> bool:
        tasks = self._tasks.pop(job_id, [])
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        job = self.jobs.get(job_id)
        if job is not None and job.status in ("running", "paused"):
            job.status = "cancelled"
            job.finished_ms = time.time() * 1000.0
            self._persist(job)
        return bool(tasks)

    async def cancel_tenant(self, tenant: str) -> int:
        n = 0
        for job_id in [
            j.job_id for j in self.jobs.values() if j.tenant == tenant
        ]:
            if await self.cancel_job(job_id):
                n += 1
        return n

    async def stop(self) -> None:
        """Cancel every running job (cursors persisted — jobs resume)."""
        for job_id in list(self._tasks):
            tasks = self._tasks.pop(job_id, [])
            for t in tasks:
                t.cancel()
            for t in tasks:
                try:
                    await t
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass

    # -- cursor persistence / resume ---------------------------------------
    def _state_path(self, job_id: str) -> Optional[Path]:
        if self.state_dir is None:
            return None
        return self.state_dir / f"{job_id}.json"

    def _persist(self, job: ReplayJob) -> None:
        """Commit the job's cursor + accounting. Called with NO await
        between the batch publish and this write, so a cancellation can
        never observe a published-but-uncommitted batch (the crash/resume
        zero-dup contract); the file replace is atomic for real crashes.
        A job in a terminal state retires instead — its cursor file is
        deleted, never rewritten (a pump still draining buffered slices
        after the scanner failed the job must not resurrect the file)."""
        if job.status not in ("running", "paused"):
            self._retire(job)
            return
        path = self._state_path(job.job_id)
        if path is None:
            return
        tmp = path.with_suffix(".tmp")
        # deliberately sync ON the loop: the publish→commit pair must be
        # await-free (cancellation-atomicity; the zero-dup contract
        # above) — an executor hop here would reopen the window this
        # function exists to close. The payload is a ~300-byte JSON blob.
        tmp.write_text(json.dumps(job.to_dict()))  # async: ok(await-free cursor commit; tiny write)
        tmp.replace(path)

    def _retire(self, job: ReplayJob) -> None:
        """Terminal transition (done/failed/cancelled): a finished job
        never resumes, so its cursor file is deleted rather than
        persisted, and the in-memory report history is bounded to the
        ``max_finished`` most recent — a year of nightly jobs must not
        grow state_dir or the jobs dict without bound."""
        path = self._state_path(job.job_id)
        if path is not None:
            path.unlink(missing_ok=True)
        finished = [
            j for j in self.jobs.values()
            if j.status not in ("running", "paused")
        ]
        if len(finished) > self.max_finished:
            finished.sort(key=lambda j: j.finished_ms)
            for j in finished[: len(finished) - self.max_finished]:
                self.jobs.pop(j.job_id, None)

    def resume_jobs(
        self, stores: Dict[str, object], recover_unscored: bool = False
    ) -> int:
        """Relaunch unfinished jobs from their persisted cursors (called
        by the instance after tenants restore). A mid-replay crash loses
        nothing: scanning restarts at the committed cursor, and rows
        before it were already published exactly once.

        ``recover_unscored`` closes the documented guarantee-boundary
        gap (module doc: the cursor marks PUBLISHED, not scored-and-
        written-back): a NON-graceful restore — the job file still says
        "running"; a graceful stop persists "paused" — can leave rows
        before the cursor published but never written back (the NaN
        window). Opting in REWINDS a resumed rescore job's cursor to
        its window start, which IS the auto-enqueued ``only_unscored``
        rescore of that window: dedupe skips every row whose score
        landed, so only the NaN window re-publishes. (The recovered
        window's rows count into ``replayed`` a second time — the
        accounting trade for exactly-once scoring coverage; forced
        jobs are excluded, a rewind would re-publish their whole
        prefix.)"""
        if self.state_dir is None:
            return 0
        n = 0
        for path in sorted(self.state_dir.glob("rj-*.json")):
            try:
                job = ReplayJob.from_dict(json.loads(path.read_text()))
            except (ValueError, TypeError):
                continue
            if job.job_id in self.jobs:
                continue
            if job.status not in ("running", "paused"):
                # a terminal file only survives a crash inside _retire's
                # tiny window — finish the cleanup, don't resurrect it
                path.unlink(missing_ok=True)
                continue
            store = stores.get(job.tenant)
            if store is None:
                continue
            if (
                recover_unscored
                and job.status == "running"   # hard kill, not stop()
                and job.target == "rescore"
                and not job.force
                and job.cursor > job.seq_lo
            ):
                job.cursor = job.seq_lo
                self.metrics.counter(
                    "replay_recovered_windows_total", tenant=job.tenant
                ).inc()
            self.start_job(job.tenant, store, job=job)
            n += 1
        return n

    # -- the two loops -----------------------------------------------------
    def _throttled(self, tenant: str) -> bool:
        """Low-priority arbitration: live traffic always wins credit.
        Any credit reduction or engaged degradation rung parks replay."""
        ov = self.overload
        if ov is None:
            return False
        return ov.credit(tenant) < 1.0 or ov.level(tenant) > 0

    async def _scan_loop(self, job: ReplayJob, store, segments, ring) -> None:
        """Stream the planned segments' filtered slices into the ring.
        Dedupe (already-scored rows) happens here, per raw window, so the
        pump's cursor commit makes replayed ∪ skipped accounting exact
        across crash/resume."""
        only_unscored = job.target == "rescore" and not job.force
        try:
            for sl in store.measurements.scan(
                job.ts0, job.ts1, job.cursor, job.seq_hi, job.device,
                only_unscored=only_unscored, batch_rows=self.batch_rows,
                segments=segments,
            ):
                if sl.seq_end < job.cursor:
                    continue  # resumed mid-segment: window already covered
                await ring.put(sl)
            await ring.put(None)
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 - a scan fault ends the
            # job visibly instead of wedging the pump forever
            job.status = "failed"
            job.error = repr(exc)
            job.finished_ms = time.time() * 1000.0
            self._persist(job)
            await ring.put(None)

    async def _pump_loop(self, job: ReplayJob, ring) -> None:
        """Publish prepared slices at low priority: park while the tenant
        is under pressure, build + publish one batch per slice, commit
        the cursor (no await between publish and commit)."""
        naming = self.bus.naming
        topic = {
            "rescore": naming.inbound_events,
            "rules": naming.persisted_events,
            "train": naming.train_feed,
        }[job.target](job.tenant)
        ev_c = self.metrics.counter(
            "replay_events_total", tenant=job.tenant, target=job.target
        )
        bytes_c = self.metrics.counter(
            "replay_bytes_total", tenant=job.tenant
        )
        throttled_c = self.metrics.counter(
            "replay_throttled_total", tenant=job.tenant
        )
        # only the tenant's (single, guarded) rescore job drives the lag
        # gauge — a concurrent rules/train backfill finishing would zero
        # it while the rescore job is still behind
        lag_g = (
            self.metrics.gauge("replay_lag_ratio", tenant=job.tenant)
            if job.target == "rescore" else None
        )
        try:
            while True:
                sl = await ring.get()
                if sl is None:
                    break
                while self._throttled(job.tenant):
                    # live traffic holds the credit: park (never drop —
                    # the ring backpressures the scanner behind us)
                    job.throttled += 1
                    throttled_c.inc()
                    if lag_g is not None:
                        lag_g.set(job.report()["lag_ratio"])
                    await asyncio.sleep(self.throttle_tick_s)
                if sl.n:
                    t0 = time.perf_counter()
                    cols = slice_columns(sl)
                    batch = _slice_to_batch(job.tenant, cols, job.target)
                    if self.tracer is not None:
                        # replay is an ingest edge like any transport:
                        # mint per published batch so stage spans (and
                        # the latency ledger's replay cohort) exist —
                        # the "replay" trace mark keeps the batch out of
                        # the live SLO series regardless
                        # priority "replay" keys a SEPARATE ledger
                        # cohort: backfill timings must not blur the
                        # live traffic's attribution or burn its SLO
                        # budget
                        batch.trace_ctx = self.tracer.mint(
                            job.tenant,
                            source_topic=f"replay:{job.target}",
                            priority="replay",
                        )
                    nbytes = (
                        cols["values"].nbytes + cols["scores"].nbytes
                        + cols["event_ts"].nbytes
                        + cols["received_ts"].nbytes
                        + cols["tok"][1].nbytes + cols["name"][1].nbytes
                    )
                    await self.bus.publish(topic, batch)
                    # publish returned → commit, with no await between:
                    # a cancellation cannot split publish from commit
                    job.replayed += sl.n
                    job.bytes_read += nbytes
                    ev_c.inc(sl.n)
                    bytes_c.inc(nbytes)
                    if self.flightrec is not None:
                        self.flightrec.record(
                            "replay", job.tenant,
                            rows=sl.n, target=job.target, job=job.job_id,
                            seq_end=int(sl.seq_end),
                            skipped=int(sl.skipped),
                            build_publish_s=round(
                                time.perf_counter() - t0, 6
                            ),
                        )
                job.skipped_dedupe += sl.skipped
                job.cursor = int(sl.seq_end) + 1
                self._persist(job)
                if lag_g is not None:
                    lag_g.set(job.report()["lag_ratio"])
            # the sentinel also ends a FAILED scan (the scanner already
            # persisted status="failed") — only a clean drain is "done"
            if job.status == "running":
                job.status = "done"
                job.finished_ms = time.time() * 1000.0
                self._persist(job)
                if lag_g is not None:
                    lag_g.set(0.0)
        except asyncio.CancelledError:
            if job.status == "running":
                job.status = "paused"  # resumable from the committed cursor
                self._persist(job)
            raise
        except Exception as exc:  # noqa: BLE001 - fail visibly; the
            # committed cursor stays in the report, so a NEW job over
            # seq_lo=cursor covers the remainder (dedupe makes overlap
            # harmless anyway)
            job.status = "failed"
            job.error = repr(exc)
            job.finished_ms = time.time() * 1000.0
            self._persist(job)
        finally:
            # the pump leaving first (fault/cancel) must not strand the
            # scanner blocked on a full ring — take the sibling down too
            for t in self._tasks.pop(job.job_id, []):
                if t is not asyncio.current_task():
                    t.cancel()
