"""Outbound connectors: fan-out of enriched events to external systems.

Capability parity with the reference's service-outbound-connectors
(``IOutboundConnector`` impls — MQTT publisher, Solr indexer, EventHub/SQS/
RabbitMQ, webhook, Groovy-scripted — each with filter chains and bounded
processing — SURVEY.md §2.2 [U]; reference mount empty, see provenance
banner).

Redesign: connectors are lifecycle components with a filter chain and an
async ``deliver``; network-less equivalents ship in-image (log, file/JSONL,
in-proc MQTT-topic publisher backed by the sim broker, callback) and the
network ones (webhook via aiohttp, real MQTT) activate when their transport
is reachable. Per-connector supervised delivery with bounded concurrency
mirrors the reference's bounded thread pools.
"""

from __future__ import annotations

import asyncio
import json
from pathlib import Path
from typing import Awaitable, Callable, Dict, List, Optional, Sequence

import numpy as np

from sitewhere_tpu.core.batch import MeasurementBatch
from sitewhere_tpu.core.events import DeviceEvent, EventType
from sitewhere_tpu.runtime.bus import (
    CircuitBreaker,
    EventBus,
    RetryingConsumer,
)
from sitewhere_tpu.runtime.config import FaultTolerancePolicy
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent, cancel_and_wait
from sitewhere_tpu.runtime.metrics import MetricsRegistry

EventFilter = Callable[[DeviceEvent], bool]


class CircuitOpenError(RuntimeError):
    """Delivery short-circuited because the connector's breaker is open."""


def type_filter(*types: EventType) -> EventFilter:
    allowed = set(types)
    return lambda e: e.EVENT_TYPE in allowed


def area_filter(*area_tokens: str) -> EventFilter:
    allowed = set(area_tokens)
    return lambda e: e.area_token in allowed


def device_filter(*device_tokens: str) -> EventFilter:
    allowed = set(device_tokens)
    return lambda e: e.device_token in allowed


class OutboundConnector(LifecycleComponent):
    """Base connector: filter chain + async deliver with bounded concurrency."""

    def __init__(
        self,
        name: str,
        filters: Optional[Sequence[EventFilter]] = None,
        concurrency: int = 8,
    ) -> None:
        super().__init__(f"connector[{name}]")
        self.connector_id = name
        self.filters: List[EventFilter] = list(filters or [])
        self._sem = asyncio.Semaphore(concurrency)
        self.delivered = 0
        self.failed = 0
        self.retried = 0
        self.parked = 0  # deliveries short-circuited by an open breaker
        # fault-tolerance bindings (installed by OutboundDispatcher when a
        # FaultTolerancePolicy is configured; None = legacy single-attempt
        # delivery with isolated errors, exactly the pre-policy behavior)
        self.breaker: Optional[CircuitBreaker] = None
        self._ft: Optional[RetryingConsumer] = None
        self._ft_source_topic = ""

    def bind_fault_tolerance(
        self, ft: RetryingConsumer, breaker: CircuitBreaker,
        source_topic: str,
    ) -> None:
        """Install retry budget + breaker + DLQ routing (dispatcher call)."""
        self._ft = ft
        self.breaker = breaker
        self._ft_source_topic = source_topic

    def accepts(self, e: DeviceEvent) -> bool:
        return all(f(e) for f in self.filters)

    async def deliver(self, e: DeviceEvent) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    async def deliver_batch(self, batch: MeasurementBatch) -> int:
        """Columnar delivery. Default: materialize rows and deliver each
        (connectors whose sink is inherently per-message, e.g. MQTT).
        High-volume-friendly connectors override with a bulk write."""
        n = 0
        for e in batch.to_events():
            if self.accepts(e):
                await self.deliver(e)
                n += 1
        return n

    _FAILED = object()  # _attempt sentinel (deliver() legitimately returns None)

    async def _attempt(self, fn, item, kind: str):
        """One delivery under breaker gating + the retry budget; exhausted
        (or breaker-parked) items dead-letter instead of vanishing.
        Returns fn's result, or ``_FAILED`` when delivery failed."""
        max_attempts = max(
            1, self._ft.policy.max_attempts if self._ft is not None else 1
        )
        last: Optional[BaseException] = None
        calls = 0
        for attempt in range(1, max_attempts + 1):
            if self.breaker is not None and not self.breaker.allow():
                # park instead of hammering a dead target: route straight
                # to the connector's DLQ with the breaker named
                self.parked += 1
                last = CircuitOpenError(f"breaker '{self.breaker.name}' open")
                break
            try:
                calls += 1
                result = await fn(item)
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 - connector errors are isolated
                last = exc
                if self.breaker is not None:
                    self.breaker.record_failure()
                if attempt < max_attempts:
                    self.retried += 1
                    await asyncio.sleep(self._ft._backoff(attempt))
                continue
            if self.breaker is not None:
                self.breaker.record_success()
            return result
        self.failed += 1
        self._record_error(kind, last)
        if self._ft is not None:
            await self._ft.dead_letter(item, self._ft_source_topic, calls, last)
        return self._FAILED

    async def process(self, e: DeviceEvent) -> bool:
        if not self.accepts(e):
            return False
        async with self._sem:
            result = await self._attempt(self.deliver, e, "deliver")
            if result is self._FAILED:
                return False
            self.delivered += 1
            return True

    async def process_batch(self, batch: MeasurementBatch) -> int:
        async with self._sem:
            n = await self._attempt(self.deliver_batch, batch, "deliver_batch")
            if n is self._FAILED:
                return 0
            self.delivered += n
            return n


class LogConnector(OutboundConnector):
    """Collects events in memory / logs them — the dev default."""

    def __init__(self, name: str = "log", capacity: int = 10000, **kw) -> None:
        super().__init__(name, **kw)
        self.capacity = capacity
        self.events: List[DeviceEvent] = []
        self.batch_rows = 0

    async def deliver(self, e: DeviceEvent) -> None:
        self.events.append(e)
        if len(self.events) > self.capacity:
            del self.events[: len(self.events) // 2]

    async def deliver_batch(self, batch: MeasurementBatch) -> int:
        if self.filters:
            # filters are per-event predicates; fall back to the
            # materialize-and-filter base path so counts stay honest
            return await super().deliver_batch(batch)
        # count rows + keep a one-row sample; materializing 10^5 rows/s of
        # objects into a dev log would defeat the columnar path
        self.batch_rows += batch.n
        if batch.n:
            sample = batch.select(np.asarray([batch.n - 1]))
            self.events.extend(sample.to_events())
            if len(self.events) > self.capacity:
                del self.events[: len(self.events) // 2]
        return batch.n


class JsonlFileConnector(OutboundConnector):
    """Appends events as JSON lines to a file (the Solr-indexer stand-in)."""

    def __init__(self, name: str, path: str | Path, **kw) -> None:
        super().__init__(name, **kw)
        self.path = Path(path)
        self._fh = None

    async def on_start(self) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fh = self.path.open("a")

    async def on_stop(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    async def deliver(self, e: DeviceEvent) -> None:
        assert self._fh is not None, "connector not started"
        self._fh.write(e.to_json() + "\n")


class MqttTopicConnector(OutboundConnector):
    """Publishes events to per-device topics on the in-proc sim broker
    (``sim.broker.SimBroker``) — the reference's MQTT outbound analog.
    Topic pattern supports {device}, {type}, {tenant} placeholders."""

    def __init__(
        self,
        name: str,
        broker,
        topic_pattern: str = "sitewhere/output/{device}/{type}",
        publish_measurement_batches: bool = False,
        **kw,
    ) -> None:
        super().__init__(name, **kw)
        self.broker = broker
        self.topic_pattern = topic_pattern
        # per-message MQTT fan-out of the full measurement firehose defeats
        # the columnar path; default off — alerts/commands (objects) still
        # publish per event, opt in for full measurement mirroring
        self.publish_measurement_batches = publish_measurement_batches

    async def deliver(self, e: DeviceEvent) -> None:
        topic = self.topic_pattern.format(
            device=e.device_token, type=e.EVENT_TYPE.value, tenant=e.tenant
        )
        await self.broker.publish(topic, e.to_json().encode())

    async def deliver_batch(self, batch: MeasurementBatch) -> int:
        if not self.publish_measurement_batches:
            return 0
        return await super().deliver_batch(batch)


class SearchIndexConnector(OutboundConnector):
    """Local search indexer — the Solr-indexer analog (reference:
    solr outbound connector [U]) without an external service: events index
    into an in-proc inverted index, queryable by term with AND semantics.

    Segment design (bounded memory, columnar-friendly): each delivered
    MeasurementBatch becomes ONE segment carrying the batch's columns plus
    a per-unique-(device,name) term map; object events batch into small
    segments. Queries walk segments newest-first; eviction drops whole
    segments (no per-doc index surgery). Terms are lowercase
    whitespace/punct-split tokens of device token, measurement name,
    alert type/message, area/assignment tokens."""

    def __init__(self, name: str = "search", max_segments: int = 256, **kw) -> None:
        super().__init__(name, **kw)
        self.max_segments = max_segments
        self._segments: List[dict] = []  # newest last
        self.indexed = 0

    @staticmethod
    def _tokens(*fields: str) -> set:
        out: set = set()
        for f in fields:
            if not f:
                continue
            for t in str(f).lower().replace("-", " ").replace("/", " ") \
                    .replace(":", " ").replace("_", " ").split():
                out.add(t)
        return out

    def _push(self, seg: dict) -> None:
        self._segments.append(seg)
        if len(self._segments) > self.max_segments:
            del self._segments[: len(self._segments) - self.max_segments]

    async def deliver(self, e: DeviceEvent) -> None:
        terms = self._tokens(
            e.device_token,
            getattr(e, "name", ""),
            getattr(e, "alert_type", ""),
            getattr(e, "message", ""),
            e.area_token,
            e.assignment_token,
            e.EVENT_TYPE.value,
        )
        self._push({"kind": "event", "event": e, "terms": terms})
        self.indexed += 1

    async def deliver_batch(self, batch: MeasurementBatch) -> int:
        if self.filters:
            return await super().deliver_batch(batch)
        if batch.n == 0:
            return 0
        # one segment per batch: per-unique-pair terms → row indices, no
        # per-row Python (uniques come from the batch's cached indices)
        pair = batch.pair_codes()
        terms_by_pair: Dict[int, set] = {}
        rows_by_pair: Dict[int, list] = {}
        for code in np.unique(pair):
            sel = np.nonzero(pair == code)[0]
            rows_by_pair[int(code)] = sel
            i = sel[0]
            terms_by_pair[int(code)] = self._tokens(
                str(batch.device_tokens[i]), str(batch.names[i]),
                "measurement",
            )
        self._push({
            "kind": "batch", "batch": batch,
            "terms_by_pair": terms_by_pair, "rows_by_pair": rows_by_pair,
        })
        self.indexed += batch.n
        return batch.n

    def search(self, query: str, limit: int = 100) -> List[DeviceEvent]:
        """All-terms-must-match search, newest first."""
        want = self._tokens(query)
        if not want:
            return []
        out: List[DeviceEvent] = []
        for seg in reversed(self._segments):
            if len(out) >= limit:
                break
            if seg["kind"] == "event":
                if want <= seg["terms"]:
                    out.append(seg["event"])
                continue
            batch = seg["batch"]
            for code, terms in seg["terms_by_pair"].items():
                if not want <= terms:
                    continue
                rows = seg["rows_by_pair"][code]
                take = rows[: max(0, limit - len(out))]
                out.extend(batch.select(np.asarray(take)).to_events())
                if len(out) >= limit:
                    break
        return out[:limit]


class QueueConnector(OutboundConnector):
    """Generic queue bridge — the SQS/EventHub/RabbitMQ-connector analog.
    Two backends share the connector:

    - ``bus``: republish onto a named in-proc bus topic (columnar batches
      forwarded as-is — zero-copy fan-out to any in-process consumer);
    - ``amqp``: publish event JSON to a queue over a REAL AMQP 0-9-1
      socket via the in-repo protocol client (``comm.amqp``)."""

    def __init__(
        self,
        name: str,
        backend: str = "bus",
        bus: Optional[EventBus] = None,
        topic: str = "sitewhere.outbound",
        host: str = "127.0.0.1",
        port: int = 5672,
        queue: str = "sitewhere.outbound",
        **kw,
    ) -> None:
        super().__init__(name, **kw)
        if backend not in ("bus", "amqp"):
            raise ValueError(f"unknown queue backend '{backend}'")
        if backend == "bus" and bus is None:
            raise ValueError("bus backend needs a bus")
        self.backend = backend
        self.bus = bus
        self.topic = topic
        self.host, self.port, self.queue = host, port, queue
        self._amqp = None
        self._amqp_lock = asyncio.Lock()  # one dial/drop at a time: the
        # base class runs deliveries concurrently, and a double-connect
        # would leak the overwritten client's socket + read loop

    async def on_stop(self) -> None:
        await self._drop_amqp(None)

    async def _drop_amqp(self, failed) -> None:
        """Close + clear the current client — but only if it IS the one
        that failed (None = unconditional, for shutdown). A concurrent
        delivery may already have re-dialed; its healthy client must not
        be torn down by a late-arriving error from the old one."""
        async with self._amqp_lock:
            if failed is not None and self._amqp is not failed:
                client = failed  # stale: close it, keep the current one
            else:
                client, self._amqp = self._amqp, None
        if client is not None:
            try:
                await client.close()
            except Exception:  # noqa: BLE001 - already broken
                pass

    async def _amqp_client(self):
        async with self._amqp_lock:
            if self._amqp is None:
                from sitewhere_tpu.comm.amqp import AmqpClient

                client = await asyncio.wait_for(
                    AmqpClient(self.host, self.port).connect(), 10.0
                )
                try:
                    await client.queue_declare(self.queue)
                except BaseException:
                    await client.close()
                    raise
                self._amqp = client
            return self._amqp

    async def deliver(self, e: DeviceEvent) -> None:
        if self.backend == "bus":
            await self.bus.publish(self.topic, e)
            return
        client = await self._amqp_client()
        try:
            await client.publish(self.queue, e.to_json().encode())
        except Exception:
            await self._drop_amqp(client)  # close + reconnect next delivery
            raise

    async def deliver_batch(self, batch: MeasurementBatch) -> int:
        if self.filters:
            return await super().deliver_batch(batch)
        if self.backend == "bus":
            # columnar fast path: the batch rides the topic unchanged
            await self.bus.publish(self.topic, batch)
            return batch.n
        # AMQP wire is per-message JSON: one compact message per row
        client = await self._amqp_client()
        n = 0
        try:
            for e in batch.to_events():
                await client.publish(self.queue, e.to_json().encode())
                n += 1
        except Exception:
            await self._drop_amqp(client)
            raise
        return n


class WebhookConnector(OutboundConnector):
    """HTTP POST per event via aiohttp (gated on a reachable endpoint)."""

    def __init__(self, name: str, url: str, timeout_s: float = 5.0, **kw) -> None:
        super().__init__(name, **kw)
        self.url = url
        self.timeout_s = timeout_s
        self._session = None

    async def on_start(self) -> None:
        import aiohttp

        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.timeout_s)
        )

    async def on_stop(self) -> None:
        if self._session:
            await self._session.close()
            self._session = None

    async def deliver(self, e: DeviceEvent) -> None:
        assert self._session is not None, "connector not started"
        async with self._session.post(self.url, json=e.to_dict()) as resp:
            resp.raise_for_status()


class CallbackConnector(OutboundConnector):
    """Invokes a user coroutine per event (the Groovy-scripted analog)."""

    def __init__(
        self, name: str, fn: Callable[[DeviceEvent], Awaitable[None]], **kw
    ) -> None:
        super().__init__(name, **kw)
        self._fn = fn

    async def deliver(self, e: DeviceEvent) -> None:
        await self._fn(e)


class OutboundDispatcher(LifecycleComponent):
    """Per-tenant stage: persisted-events → every registered connector."""

    def __init__(
        self,
        tenant: str,
        bus: EventBus,
        connectors: Optional[Sequence[OutboundConnector]] = None,
        metrics: Optional[MetricsRegistry] = None,
        poll_batch: int = 4096,
        policy: Optional[FaultTolerancePolicy] = None,
        tracer=None,
        overload=None,
    ) -> None:
        super().__init__(f"outbound-connectors[{tenant}]")
        self.tenant = tenant
        self.bus = bus
        self.metrics = metrics or MetricsRegistry()
        self.poll_batch = poll_batch
        self.policy = policy
        self.tracer = tracer
        # overload control: expired measurement batches skip connector
        # fan-out (count-only — they are already persisted), and the
        # 'pause_fanout' degradation rung pauses measurement fan-out
        # entirely while engaged. The terminal span still records either
        # way so tail sampling can seal the trace.
        self.overload = overload
        from sitewhere_tpu.runtime.overload import DeadlineGate
        from sitewhere_tpu.runtime.tracing import StageTimer

        self.deadline_gate = DeadlineGate(
            bus, tenant, "outbound", self.metrics, tracer=tracer,
            controller=overload, route_payload=False,
        )

        # outbound is the TERMINAL stage: its span seals the trace and
        # triggers the tail-based sampling decision (runtime.tracing)
        self.stage_timer = StageTimer(tracer, self.metrics, tenant, "outbound")
        self._task: Optional[asyncio.Task] = None
        for c in connectors or []:
            self.add_child(c)

    @property
    def connectors(self) -> List[OutboundConnector]:
        return [c for c in self.children if isinstance(c, OutboundConnector)]

    def add_connector(self, c: OutboundConnector) -> None:
        self.add_child(c)
        self._bind_connector(c)

    def _bind_connector(self, c: OutboundConnector) -> None:
        """Give one connector its retry budget, breaker, and per-connector
        DLQ (``dead-letter.outbound.<connector_id>``). Requeued entries
        re-enter at the persisted-events topic — the normal path."""
        if self.policy is None or c._ft is not None:
            return
        c.bind_fault_tolerance(
            RetryingConsumer(
                self.bus, self.tenant, f"outbound.{c.connector_id}",
                self.group, policy=self.policy, metrics=self.metrics,
                tracer=self.tracer,
            ),
            CircuitBreaker(
                f"outbound[{self.tenant}].{c.connector_id}",
                policy=self.policy, metrics=self.metrics,
            ),
            self.bus.naming.persisted_events(self.tenant),
        )

    @property
    def group(self) -> str:
        return f"outbound-connectors[{self.tenant}]"

    async def on_start(self) -> None:
        for c in self.connectors:
            self._bind_connector(c)
        self.bus.subscribe(
            self.bus.naming.persisted_events(self.tenant), self.group
        )
        self._task = asyncio.create_task(self._run(), name=self.name)

    async def on_stop(self) -> None:
        await cancel_and_wait(self._task)
        self._task = None

    async def _run(self) -> None:
        import time as _time

        src = self.bus.naming.persisted_events(self.tenant)
        delivered = self.metrics.counter("outbound.delivered")
        skipped = self.metrics.counter("outbound.skipped_degraded")
        while True:
            items = await self.bus.consume(src, self.group, self.poll_batch)
            for item in items:
                t0 = _time.time() * 1000.0
                shed_fanout = False
                if isinstance(item, MeasurementBatch):
                    shed_fanout = self.deadline_gate.check(item) or (
                        self.overload is not None
                        and self.overload.degraded(
                            self.tenant, "pause_fanout"
                        )
                    )
                if shed_fanout:
                    # fan-out shed (expired or degraded): no connector
                    # work, but the TERMINAL span must still seal the
                    # trace or tail sampling would idle-time-out it
                    skipped.inc(item.n)
                    self.stage_timer.observe(
                        item, t0, _time.time() * 1000.0, n_events=item.n,
                        delivered=0, shed="overload",
                    )
                    continue
                if isinstance(item, MeasurementBatch):
                    results = await asyncio.gather(
                        *(c.process_batch(item) for c in self.connectors)
                    )
                    n_del = sum(results)
                    delivered.inc(n_del)
                    n = item.n
                else:
                    results = await asyncio.gather(
                        *(c.process(item) for c in self.connectors)
                    )
                    n_del = sum(bool(r) for r in results)
                    delivered.inc(n_del)
                    n = 1
                self.stage_timer.observe(
                    item, t0, _time.time() * 1000.0, n_events=n,
                    delivered=n_del,
                )
