"""Columnar segment store: the event store's wire-speed persistence layer.

ROADMAP item 5: storage and replay must be a first-class scale axis — the
store and the DLQ/replay paths see the same traffic the scorer does, so
rows have to move the way the PR 4 feed path moves them: as dtype-tagged
raw column buffers, never as per-event Python objects.

Layout (one **segment** = one sealed, immutable row range)::

    b"SWS" | version u8 | meta_len u32 | meta (restricted pickle) | raw cols

``meta`` holds the scalar fields, the object-column vocabularies
(device/assignment/area/name columns ship as vocab + int32 inverse — the
same contract as ``MeasurementBatch.__reduce__``), the lazy event-id
prefix segments, the segment table ``[(field, nbytes), ...]``, and the
**zone map** (device-id set / hash bloom + event-time min/max + seq
range). The raw region is the numeric columns' buffers concatenated in
table order; decode hands out zero-copy ``np.frombuffer`` views — over an
``mmap`` of the file when the store is disk-backed, so a sealed-segment
scan never materializes a per-event object and never copies a column it
does not slice.

Durability (dir mode): a seal writes the segment file, fsyncs it, then
atomically replaces ``manifest.json`` — the **commit point**. Recovery
trusts only the manifest: a committed entry whose file is missing, short,
or undecodable is a torn tail — it (and everything after it) is dropped,
never half-read, and ``next_seq`` keeps the manifest's value so dropped
row seqs are never reused (replay cursors stay unambiguous).

Retention & compaction (``maintain``): segments wholly past the retention
horizon drop; runs of adjacent small segments (checkpoint tail
generations, low-rate tenants) merge into sealed full-size segments so
the zone-map index stays shallow, and segments carrying a score overlay
(write-back after rescore) re-encode so the overlay becomes durable.
``maintain`` runs off the ingest path — the instance history tick,
checkpoint/restore, and explicit calls drive it — so a seal stays
O(chunk) and generational tails don't pay quadratic re-encodes.

Seq contract: every appended row gets a monotonically increasing
store-global sequence number (implicit: a segment's rows are
``seq0 .. seq0+n-1`` in append order). ``plan``/``scan`` prune segments
by zone map and stream filtered column slices — the feed for
``pipeline/replay.py``'s replay-to-rescore engine.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import time
import uuid
import zlib
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from sitewhere_tpu.core.batch import make_event_ids

SEG_MAGIC = b"SWS"
SEG_VERSION = 1
SEG_SUFFIX = ".sws"
_SEG_META = struct.Struct(">I")

# field → required dtype for the raw column region (same discipline as
# core.batch._WIRE_NUMERIC: the decoder refuses anything else, so a
# tampered file can never smuggle object buffers through the raw path)
SEG_NUMERIC = {
    "value": np.dtype(np.float32),
    "score": np.dtype(np.float32),
    "event_ts": np.dtype(np.int64),
    "received_ts": np.dtype(np.int64),
    "tok_inverse": np.dtype(np.int32),
    "name_inverse": np.dtype(np.int32),
    "asg_inverse": np.dtype(np.int32),
    "area_inverse": np.dtype(np.int32),
}

# object column → (inverse raw field, vocab meta key)
OBJ_FIELDS = (
    ("device_token", "tok_inverse", "tok_uniq"),
    ("name", "name_inverse", "name_uniq"),
    ("assignment_token", "asg_inverse", "asg_uniq"),
    ("area_token", "area_inverse", "area_uniq"),
)

# zone map: store the exact device set up to this size, a 64-bit hash
# bloom above it (crc32 — stable across processes, unlike hash())
ZONE_DEVICE_LIST_MAX = 64


class SegmentFormatError(ValueError):
    """A torn, truncated, or out-of-contract segment file."""


def _safepickle():
    from sitewhere_tpu.runtime import safepickle  # lazy: no import cycle

    return safepickle


def _pin_prefix(b) -> str:
    """Pin (or reuse) a batch's lazy event-id prefix (see
    MeasurementBatch.id_prefix for the identity contract)."""
    if b.id_prefix is None:
        b.id_prefix = uuid.uuid4().hex[:16] + "-"
    return b.id_prefix


def _dev_bloom(vocab: Sequence[str]) -> int:
    """64-bit membership bloom over device tokens (1 bit per token)."""
    bits = 0
    for tok in vocab:
        bits |= 1 << (zlib.crc32(str(tok).encode()) & 63)
    return bits


def _zone_map(vocab: Sequence[str], event_ts: np.ndarray,
              seq0: int, n: int) -> dict:
    """The per-segment zone map: device set (exact up to
    ZONE_DEVICE_LIST_MAX, hash bloom always), event-time min/max, seq
    range — everything ``plan`` needs to prune without touching rows."""
    return {
        "ts_min": int(event_ts.min()) if n else 0,
        "ts_max": int(event_ts.max()) if n else 0,
        "seq_min": int(seq0),
        "seq_max": int(seq0 + n - 1) if n else int(seq0),
        "n_devices": len(vocab),
        "devices": (
            sorted(str(t) for t in vocab)
            if len(vocab) <= ZONE_DEVICE_LIST_MAX else None
        ),
        "dev_bloom": _dev_bloom(vocab),
    }


def _vocab_encode(col: Optional[np.ndarray], hint: Optional[tuple]):
    """(vocab list, int32 inverse) for one object column. The hint — a
    precomputed group index inherited from the batch wire (see
    ``SegmentColumns.append_batch``) — skips the object-string sort the
    hot path must never pay; ``np.unique`` is the cold fallback."""
    if hint is not None:
        return list(hint[0]), np.asarray(hint[1], np.int32)
    if col is None or len(col) == 0:
        return [], np.zeros((len(col) if col is not None else 0,), np.int32)
    u, inv = np.unique(col, return_inverse=True)
    return u.tolist(), inv.astype(np.int32)


def encode_segment(
    chunk: Dict[str, object],
    seq0: int,
    tenant: str = "default",
    vocab_hints: Optional[Dict[str, tuple]] = None,
) -> bytes:
    """Serialize one column chunk as a sealed segment.

    ``chunk`` is the store's legacy column-dict shape: numeric columns
    (``value``/``score``/``event_ts``/``received_ts``) plus the four
    object columns, plus either a materialized ``event_id`` array or the
    lazy markers (``_idsegs`` / ``_idp``) the event store's tail carries.
    ``vocab_hints`` maps object-column names to ``(vocab, inverse)``
    pairs computed upstream (the batch wire's free group index)."""
    n = int(len(chunk["value"]))
    hints = vocab_hints or {}
    numeric: List[Tuple[str, np.ndarray]] = []
    for f in ("value", "score", "event_ts", "received_ts"):
        a = np.ascontiguousarray(
            np.asarray(chunk[f]), dtype=SEG_NUMERIC[f]
        )
        if a.shape != (n,):
            raise SegmentFormatError(
                f"column '{f}' is {a.shape}, expected ({n},)"
            )
        numeric.append((f, a))
    meta: Dict[str, object] = {"n": n, "seq0": int(seq0), "tenant": tenant}
    for obj_field, inv_field, uniq_key in OBJ_FIELDS:
        vocab, inv = _vocab_encode(chunk.get(obj_field), hints.get(obj_field))
        if inv.shape != (n,):
            raise SegmentFormatError(
                f"inverse for '{obj_field}' is {inv.shape}, expected ({n},)"
            )
        meta[uniq_key] = vocab
        numeric.append((inv_field, np.ascontiguousarray(inv)))
    # event ids: lazy (prefix, count) spans when the store never had to
    # materialize them; explicit list otherwise (the low-volume path)
    ids = chunk.get("event_id")
    if ids is None:
        segs = chunk.get("_idsegs")
        if segs is None:
            segs = [(chunk["_idp"], n)]
        meta["idsegs"] = [(str(p), int(k)) for p, k in segs]
    else:
        meta["ids"] = [str(x) for x in ids]
    meta["zone"] = _zone_map(meta["tok_uniq"], numeric[2][1], seq0, n)
    meta["segs"] = [(f, int(a.nbytes)) for f, a in numeric]
    import pickle as _pickle

    blob = _pickle.dumps(meta, protocol=_pickle.HIGHEST_PROTOCOL)
    parts = [SEG_MAGIC, bytes([SEG_VERSION]), _SEG_META.pack(len(blob)), blob]
    parts.extend(a.tobytes() for _f, a in numeric)
    return b"".join(parts)


class Segment:
    """One sealed, immutable segment: zone map + zero-copy column views.

    Backed either by the encoded bytes (memory mode — the bytes double as
    the checkpoint payload) or by an ``mmap`` of the segment file (dir
    mode / restore): every numeric column is a ``np.frombuffer`` view
    into the backing buffer, token columns come back as (vocab object
    array, int32 inverse view), and object materialization is a single
    C-level fancy-index fan-out callers pay only when they ask."""

    __slots__ = (
        "n", "seq0", "tenant", "zone", "nbytes", "name", "path",
        "_buf", "_mm", "_meta", "_cols", "_vocab_obj", "_ids",
        "_score_overlay", "ckpt_name",
    )

    def __init__(self, buf, meta: dict, cols: Dict[str, np.ndarray],
                 mm=None, path: Optional[Path] = None,
                 name: str = "") -> None:
        self._buf = buf
        self._mm = mm
        self._meta = meta
        self._cols = cols
        self.path = path
        self.name = name or (path.name if path is not None else "")
        self.n = int(meta["n"])
        self.seq0 = int(meta["seq0"])
        self.tenant = str(meta.get("tenant", "default"))
        self.zone = dict(meta["zone"])
        self.nbytes = len(buf)
        self._vocab_obj: Dict[str, np.ndarray] = {}
        self._ids: Optional[np.ndarray] = None
        self._score_overlay: Optional[np.ndarray] = None
        # name of the committed CHECKPOINT file holding exactly these
        # bytes (set by checkpoint save/load) — the incremental-reuse
        # identity: a maintain() merge/rewrite yields a NEW Segment with
        # ckpt_name None, so the changed bytes re-checkpoint even when
        # row counts line up
        self.ckpt_name: Optional[str] = None

    # -- construction ------------------------------------------------------
    @classmethod
    def from_bytes(cls, data, mm=None, path: Optional[Path] = None,
                   name: str = "") -> "Segment":
        """Decode + validate one segment buffer. Every malformed shape
        raises ``SegmentFormatError`` — a segment is either fully intact
        or rejected whole (the manifest commit point decides which sealed
        files are even attempted)."""
        sp = _safepickle()
        if len(data) < 4 or bytes(data[:3]) != SEG_MAGIC:
            raise SegmentFormatError("not a segment file (bad magic)")
        version = data[3]
        if version != SEG_VERSION:
            raise SegmentFormatError(f"unknown segment version {version}")
        if len(data) < 4 + _SEG_META.size:
            raise SegmentFormatError("torn segment: truncated meta header")
        (meta_len,) = _SEG_META.unpack_from(data, 4)
        col0 = 4 + _SEG_META.size + meta_len
        if col0 > len(data):
            raise SegmentFormatError("torn segment: meta overruns payload")
        try:
            meta = sp.loads(bytes(data[4 + _SEG_META.size: col0]))
        except Exception as exc:  # noqa: BLE001 - safepickle surfaces
            # corrupt bytes as UnpicklingError (NOT ValueError); any meta
            # decode fault must read as a torn/undecodable segment so the
            # recovery contract ("dropped, never half-read") holds
            raise SegmentFormatError(
                f"undecodable segment meta: {exc!r}"
            ) from None
        if not isinstance(meta, dict):
            raise SegmentFormatError("malformed segment meta")
        try:
            n = int(meta["n"])
            segs = list(meta["segs"])
            zone = dict(meta["zone"])
        except (KeyError, TypeError, ValueError) as exc:
            raise SegmentFormatError(f"malformed meta: {exc}") from None
        del zone
        total = 0
        for f, nbytes in segs:
            dt = SEG_NUMERIC.get(f)
            if dt is None:
                raise SegmentFormatError(f"unexpected raw column '{f}'")
            if int(nbytes) != n * dt.itemsize:
                raise SegmentFormatError(
                    f"torn segment: column '{f}' is {nbytes} bytes, "
                    f"expected {n * dt.itemsize}"
                )
            total += int(nbytes)
        if col0 + total != len(data):
            raise SegmentFormatError(
                f"torn segment: {len(data) - col0} column bytes, "
                f"expected {total}"
            )
        cols: Dict[str, np.ndarray] = {}
        off = col0
        for f, nbytes in segs:
            cols[f] = np.frombuffer(data, SEG_NUMERIC[f], count=n, offset=off)
            off += int(nbytes)
        # vocab range validation (hostile index must not read off the end)
        for _obj, inv_field, uniq_key in OBJ_FIELDS:
            inv = cols.get(inv_field)
            uniq = meta.get(uniq_key)
            if inv is None or not isinstance(uniq, list):
                raise SegmentFormatError(f"missing vocab for '{inv_field}'")
            if n and len(inv) and (inv.min() < 0 or inv.max() >= max(len(uniq), 1)):
                raise SegmentFormatError(
                    f"'{inv_field}' index out of vocab range"
                )
        ids = meta.get("ids")
        idsegs = meta.get("idsegs")
        if ids is not None:
            if not isinstance(ids, list) or len(ids) != n:
                raise SegmentFormatError("event-id list length mismatch")
        elif idsegs is not None:
            if sum(int(k) for _p, k in idsegs) != n:
                raise SegmentFormatError("event-id spans do not cover rows")
        elif n:
            raise SegmentFormatError("segment carries no event-id source")
        return cls(data, meta, cols, mm=mm, path=path, name=name)

    @classmethod
    def open(cls, path: str | Path) -> "Segment":
        """mmap a sealed segment file: columns become zero-copy views over
        the mapped region — opening a 1 GB store touches no row bytes."""
        path = Path(path)
        with open(path, "rb") as fh:
            mm = mmap.mmap(fh.fileno(), 0, access=mmap.ACCESS_READ)
        return cls.from_bytes(mm, mm=mm, path=path, name=path.name)

    # -- column access -----------------------------------------------------
    @property
    def encoded(self) -> bytes:
        """The raw segment bytes (checkpoint payload). Memory segments
        return their backing buffer; mmap segments copy (cold path —
        incremental checkpoints never re-encode committed segments)."""
        return self._buf if isinstance(self._buf, bytes) else bytes(self._buf)

    def numeric(self, field: str) -> np.ndarray:
        if field == "score" and self._score_overlay is not None:
            return self._score_overlay
        return self._cols[field]

    @property
    def is_dirty(self) -> bool:
        """True when a score overlay shadows the wire bytes — compaction
        re-encodes dirty segments so the write-back becomes durable."""
        return self._score_overlay is not None

    def writable_scores(self) -> np.ndarray:
        """A mutable copy-on-write score column over the immutable
        segment buffer — the replay write-back target. Readers
        (``numeric``/``scan``/``to_chunk``/compaction) see the overlay;
        the raw wire bytes stay untouched, so ``encoded`` (the
        checkpoint payload) keeps its encode-once identity and the
        overlay becomes durable when compaction re-encodes the segment
        (see docs/STORAGE.md "Score write-back")."""
        if self._score_overlay is None:
            self._score_overlay = np.array(self._cols["score"])
        return self._score_overlay

    def vocab(self, obj_field: str) -> Tuple[np.ndarray, np.ndarray]:
        """(vocab object array, int32 inverse view) for one token column —
        the same shape the batch wire hands consumers, so replay batches
        inherit their group index without a string sort."""
        for of, inv_field, uniq_key in OBJ_FIELDS:
            if of == obj_field:
                u = self._vocab_obj.get(obj_field)
                if u is None:
                    u = self._vocab_obj[obj_field] = np.asarray(
                        self._meta[uniq_key], object
                    )
                return u, self._cols[inv_field]
        raise KeyError(obj_field)

    def obj_column(self, obj_field: str) -> np.ndarray:
        """Materialize one object column (vocab fan-out: one C-level
        fancy-index, no per-row Python)."""
        u, inv = self.vocab(obj_field)
        if len(u) == 0:
            return np.full((self.n,), "", object)
        return u[inv]

    def event_ids(self) -> np.ndarray:
        """Materialize (and cache) the per-row event ids."""
        if self._ids is None:
            ids = self._meta.get("ids")
            if ids is not None:
                self._ids = np.asarray(ids, object)
            else:
                parts = [
                    make_event_ids(p, k) for p, k in self._meta["idsegs"]
                ]
                self._ids = (
                    parts[0] if len(parts) == 1 else np.concatenate(parts)
                )
        return self._ids

    def id_entries(self) -> Tuple[Optional[list], Optional[list]]:
        """(explicit ids | None, idsegs | None) for the O(1) id index."""
        return self._meta.get("ids"), self._meta.get("idsegs")

    def to_chunk(self) -> Dict[str, np.ndarray]:
        """The legacy column-dict view (parquet export, sealed-cache
        concat): numeric views + object fan-outs + materialized ids."""
        out = {"event_id": self.event_ids()}
        for obj_field, _inv, _uk in OBJ_FIELDS:
            out[obj_field] = self.obj_column(obj_field)
        for f in ("value", "score", "event_ts", "received_ts"):
            out[f] = self.numeric(f)  # score reads through the overlay
        return out

    # -- zone pruning ------------------------------------------------------
    def matches(
        self,
        ts0: int = 0,
        ts1: int = 0,
        seq_lo: int = 0,
        seq_hi: Optional[int] = None,
        device: str = "",
    ) -> bool:
        """Zone-map test: can this segment contain a matching row?"""
        z = self.zone
        if self.n == 0:
            return False
        if ts0 and z["ts_max"] < ts0:
            return False
        if ts1 and z["ts_min"] > ts1:
            return False
        if seq_lo and z["seq_max"] < seq_lo:
            return False
        if seq_hi is not None and z["seq_min"] > seq_hi:
            return False
        if device:
            devs = z.get("devices")
            if devs is not None:
                return device in devs
            return bool(z["dev_bloom"] & (1 << (zlib.crc32(device.encode()) & 63)))
        return True

    def close(self) -> None:
        if self._mm is not None:
            # drop the views first? numpy views keep the mmap buffer
            # alive; the map closes when the last view dies. Explicit
            # close is only safe once callers dropped their views — the
            # store calls this on segments it is unlinking.
            try:
                self._mm.close()
            except (BufferError, ValueError):
                pass  # live views: the map dies with them
            self._mm = None


class ScanSlice:
    """One filtered row window of a planned segment: absolute row indices
    (``sel``), the dedupe-skip count inside the raw window, and
    ``seq_end`` — the last RAW seq the window covered, which is what a
    replay cursor commits (resume re-scans nothing before it, re-counts
    nothing after it). Per-row seqs are implicit: ``seg.seq0 + sel``."""

    __slots__ = ("seg", "sel", "skipped", "seq_end")

    def __init__(self, seg: Segment, sel: np.ndarray,
                 skipped: int, seq_end: int) -> None:
        self.seg = seg
        self.sel = sel
        self.skipped = skipped
        self.seq_end = seq_end

    @property
    def n(self) -> int:
        return int(len(self.sel))


def slice_columns(sl: ScanSlice) -> Dict[str, object]:
    """Materialize one scan slice's columns for batch building: numeric
    picks (one fancy-index per column), token columns as (vocab, picked
    inverse) — consumers inherit the group index, never a string sort —
    and the slice's event ids. No per-row Python anywhere."""
    seg, sel = sl.seg, sl.sel
    tok_u, tok_inv = seg.vocab("device_token")
    name_u, name_inv = seg.vocab("name")
    asg_u, asg_inv = seg.vocab("assignment_token")
    area_u, area_inv = seg.vocab("area_token")
    ids = seg.event_ids()
    return {
        "values": seg.numeric("value")[sel],
        "scores": seg.numeric("score")[sel],
        "event_ts": seg.numeric("event_ts")[sel],
        "received_ts": seg.numeric("received_ts")[sel],
        "tok": (tok_u, tok_inv[sel]),
        "name": (name_u, name_inv[sel]),
        "asg": (asg_u, asg_inv[sel]) if len(asg_u) else None,
        "area": (area_u, area_inv[sel]) if len(area_u) else None,
        "event_ids": ids[sel],
    }


class SegmentColumns:
    """Append-only columnar measurement store over sealed segments.

    The drop-in successor to the event store's chunk store: same append
    surface (per-event ``append``, columnar ``append_batch`` parking the
    batch's arrays as one pending chunk — O(1) per batch), same two-level
    read cache (``columns``), but seals produce :class:`Segment` objects
    — zone-mapped, wire-encoded once, durable at seal time when the store
    has a ``directory`` — and reads/replay go through ``plan``/``scan``
    instead of full materialization.
    """

    CHUNK = 65536  # default rows per sealed segment

    def __init__(
        self,
        tenant: str = "default",
        directory: Optional[str | Path] = None,
        rows_per_segment: int = CHUNK,
        retention_ms: float = 0.0,
        lineage: Optional[str] = None,
    ) -> None:
        self.tenant = tenant
        self.rows_per_segment = int(rows_per_segment)
        self.retention_ms = float(retention_ms)
        # lineage id: identifies THIS store's data history across
        # checkpoint/restore cycles — a data dir written by a different
        # lineage must never be incrementally extended
        self.lineage = lineage or uuid.uuid4().hex
        self.directory = Path(directory) if directory is not None else None
        self.segments: List[Segment] = []
        self._cur: Dict[str, list] = self._fresh()
        self._pending: List[Dict[str, object]] = []
        self._pending_rows = 0
        self._materialized: Optional[Dict[str, np.ndarray]] = None
        self._sealed_cache: Optional[Dict[str, np.ndarray]] = None
        self._next_seq = 0
        self._gen = 0
        # O(1) event-id index (activated on first find_row, maintained at
        # seal time): explicit ids → (seg_idx, row); lazy prefixes →
        # (seg_idx, base_row, count). Explicit-id segments queue in
        # _stale_index at seal and build on the next LOOKUP — the per-row
        # dict build must never run on the ingest seal path.
        self._id_map: Optional[Dict[str, Tuple[int, int]]] = None
        self._prefix_map: Optional[Dict[str, Tuple[int, int, int]]] = None
        self._stale_index: List[int] = []
        # maintenance accounting (surfaced via describe / REST)
        self.compactions = 0
        self.compacted_segments = 0
        self.dropped_segments = 0
        self.dropped_rows = 0
        self.torn_dropped = 0
        if self.directory is not None:
            self.directory.mkdir(parents=True, exist_ok=True)
            self._recover()

    # -- append (the persistence hot path) --------------------------------
    @staticmethod
    def _fresh() -> Dict[str, list]:
        return {
            "event_id": [], "device_token": [], "assignment_token": [],
            "area_token": [], "name": [], "value": [], "score": [],
            "event_ts": [], "received_ts": [],
        }

    def append(self, e) -> None:
        c = self._cur
        c["event_id"].append(e.id)
        c["device_token"].append(e.device_token)
        c["assignment_token"].append(e.assignment_token)
        c["area_token"].append(e.area_token)
        c["name"].append(e.name)
        c["value"].append(e.value)
        c["score"].append(e.score if e.score is not None else np.nan)
        c["event_ts"].append(e.event_ts)
        c["received_ts"].append(e.received_ts)
        self._next_seq += 1
        self._materialized = None  # invalidate read cache (tail changed)
        if len(c["value"]) >= self.rows_per_segment:
            self._seal()

    def append_batch(self, b) -> None:
        """Columnar bulk append from a MeasurementBatch: the batch's
        arrays are parked as one pending chunk — O(1) per batch, no
        per-row work on the ingest hot path. The batch's cached group
        indexes (free from the wire codec) ride along as vocab hints so
        the seal never pays an object-string sort for them."""
        n = b.n
        if n == 0:
            return

        def col(a):
            return a if a is not None else np.full((n,), "", object)

        hints: Dict[str, tuple] = {}
        if b.tok_index is not None and b.device_tokens is not None:
            u, inv = b.tok_index
            hints["device_token"] = (u.tolist(), inv)
        if b.name_index is not None and b.names is not None:
            u, inv = b.name_index
            hints["name"] = (u.tolist(), inv)
        self._pending.append(
            {
                # ids stay LAZY (None + the BATCH's pinned prefix) until a
                # seal or read forces them — sharing the batch's prefix
                # keeps the persisted ids identical to any later edge
                # materialization of the same batch (to_events, WS feed)
                "event_id": b.event_ids,
                "_idp": None if b.event_ids is not None else _pin_prefix(b),
                "_vocabs": hints,
                "device_token": col(b.device_tokens),
                "assignment_token": col(b.assignment_tokens),
                "area_token": col(b.area_tokens),
                "name": col(b.names),
                "value": b.values,
                "score": (
                    b.scores
                    if b.scores is not None
                    else np.full((n,), np.nan, np.float32)
                ),
                "event_ts": b.event_ts.astype(np.int64),
                "received_ts": b.received_ts.astype(np.int64),
            }
        )
        self._pending_rows += n
        self._next_seq += n
        self._materialized = None
        if self._pending_rows + len(self._cur["value"]) >= self.rows_per_segment:
            self._seal()

    # -- sealing -----------------------------------------------------------
    OBJ = ("event_id", "device_token", "assignment_token", "area_token", "name")
    DTYPES = {"value": np.float32, "score": np.float32,
              "event_ts": np.int64, "received_ts": np.int64}

    def _cur_arrays(self) -> Dict[str, np.ndarray]:
        """Live per-row tail → typed arrays (the one _cur→array mapping)."""
        return {
            k: np.asarray(v, object if k in self.OBJ else self.DTYPES[k])
            for k, v in self._cur.items()
        }

    @staticmethod
    def _ensure_ids(chunk: Dict[str, object]) -> Dict[str, object]:
        """Materialize a chunk's lazy event ids in place (idempotent)."""
        if chunk.get("event_id") is not None:
            chunk.pop("_idp", None)
            chunk.pop("_idsegs", None)
            return chunk
        segs = chunk.pop("_idsegs", None)
        if segs is None:
            segs = [(chunk.pop("_idp"), len(chunk["value"]))]
        else:
            chunk.pop("_idp", None)
        parts = [make_event_ids(p, k) for p, k in segs]
        chunk["event_id"] = (
            parts[0] if len(parts) == 1 else np.concatenate(parts)
        )
        return chunk

    @staticmethod
    def _merge_vocab_hints(parts: List[Dict[str, object]], field: str):
        """Merge per-chunk (vocab, inverse) hints into one chunk-spanning
        hint — dict merges over vocabs (O(unique)) + one int32 remap per
        part, never a string sort over rows. None when any part lacks the
        hint (the seal then falls back to np.unique)."""
        hints = []
        for p in parts:
            h = (p.get("_vocabs") or {}).get(field)
            if h is None:
                return None
            hints.append(h)
        vocab_map: Dict[str, int] = {}
        remapped = []
        for vocab, inv in hints:
            codes = np.asarray(
                [vocab_map.setdefault(t, len(vocab_map)) for t in vocab],
                np.int32,
            )
            remapped.append(codes[np.asarray(inv, np.int32)])
        merged_inv = (
            remapped[0] if len(remapped) == 1 else np.concatenate(remapped)
        )
        return list(vocab_map), merged_inv

    def _seal(self) -> None:
        """Seal the tail (pending chunks + live rows) into one Segment:
        encode the wire layout once, compute the zone map, write + fsync
        the file and commit the manifest when disk-backed."""
        if not self._cur["value"] and not self._pending:
            return
        parts: List[Dict[str, object]] = list(self._pending)
        if self._cur["value"]:
            parts.append(self._cur_arrays())
        n = sum(len(p["value"]) for p in parts)
        seq0 = self._next_seq - n
        # all-lazy parts seal LAZY: the (prefix, count) spans go into the
        # segment meta instead of paying id generation on the ingest path
        lazy = all(p.get("event_id") is None for p in parts)
        if len(parts) == 1:
            chunk = dict(parts[0])
            hints = dict(chunk.pop("_vocabs", None) or {})
        else:
            if lazy:
                idsegs: List[tuple] = []
                for p in parts:
                    idsegs.extend(
                        p.get("_idsegs") or [(p["_idp"], len(p["value"]))]
                    )
            else:
                parts = [self._ensure_ids(p) for p in parts]
            chunk = {
                k: np.concatenate([np.asarray(p[k]) for p in parts])
                for k in ("device_token", "assignment_token", "area_token",
                          "name", "value", "score", "event_ts",
                          "received_ts")
            }
            hints = {}
            for field in ("device_token", "name"):
                merged = self._merge_vocab_hints(parts, field)
                if merged is not None:
                    hints[field] = merged
            if lazy:
                chunk["event_id"] = None
                chunk["_idsegs"] = idsegs
            else:
                chunk["event_id"] = np.concatenate(
                    [p["event_id"] for p in parts]
                )
        data = encode_segment(chunk, seq0, self.tenant, vocab_hints=hints)
        seg = Segment.from_bytes(data)
        if self.directory is not None:
            seg = self._write_segment(seg)
        self.segments.append(seg)
        self._note_segment(len(self.segments) - 1)
        self._pending = []
        self._pending_rows = 0
        self._cur = self._fresh()
        self._sealed_cache = None
        self._materialized = None
        if self.directory is not None:
            self._commit_manifest()

    def add_segment(self, seg: Segment) -> None:
        """Adopt a decoded segment (restore path): zero per-row work."""
        self.segments.append(seg)
        self._note_segment(len(self.segments) - 1)
        self._next_seq = max(self._next_seq, seg.seq0 + seg.n)
        self._sealed_cache = None
        self._materialized = None

    def add_sealed_chunk(self, chunk: Dict[str, np.ndarray]) -> None:
        """Adopt a pre-built legacy column chunk (parquet import path):
        encoded into a segment once, then immutable."""
        n = len(chunk["value"])
        if n == 0:
            return
        data = encode_segment(dict(chunk), self._next_seq, self.tenant)
        self._next_seq += n
        self.add_segment(Segment.from_bytes(data))

    def encode_tail(self) -> bytes:
        """The unsealed tail (pending + live rows) as segment bytes — the
        checkpoint's generational-tail payload. The tail is NOT sealed by
        this (the live store keeps appending to it)."""
        parts: List[Dict[str, object]] = [dict(p) for p in self._pending]
        if self._cur["value"]:
            parts.append(self._cur_arrays())
        n = sum(len(p["value"]) for p in parts)
        seq0 = self._next_seq - n
        if not parts:
            empty: Dict[str, object] = {
                k: np.zeros((0,), dt) for k, dt in self.DTYPES.items()
            }
            empty.update({k: np.zeros((0,), object) for k in self.OBJ})
            return encode_segment(empty, seq0, self.tenant)
        if len(parts) == 1:
            chunk = dict(parts[0])
            hints = dict(chunk.pop("_vocabs", None) or {})
            return encode_segment(chunk, seq0, self.tenant, vocab_hints=hints)
        parts = [self._ensure_ids(dict(p)) for p in parts]
        chunk = {
            k: np.concatenate([np.asarray(p[k]) for p in parts])
            for k in ("event_id", "device_token", "assignment_token",
                      "area_token", "name", "value", "score", "event_ts",
                      "received_ts")
        }
        return encode_segment(chunk, seq0, self.tenant)

    # -- durability (dir mode) ---------------------------------------------
    def _seg_filename(self, seq0: int) -> str:
        return f"seg-{seq0:012d}-g{self._gen:06d}{SEG_SUFFIX}"

    def _write_segment(self, seg: Segment) -> Segment:
        """Write + fsync one sealed segment, then reopen it mmap'd so the
        resident copy is the page cache, not a second heap buffer."""
        self._gen += 1
        path = self.directory / self._seg_filename(seg.seq0)
        with open(path, "wb") as fh:
            fh.write(seg.encoded)
            fh.flush()
            os.fsync(fh.fileno())
        return Segment.open(path)

    def _manifest_path(self) -> Path:
        return self.directory / "manifest.json"

    def _commit_manifest(self) -> None:
        """Atomic-replace the manifest — THE commit point. ``next_seq``
        is recorded so a torn-tail drop never reuses the dropped rows'
        seqs (replay cursors stay unambiguous across the repair)."""
        doc = {
            "version": 1,
            "lineage": self.lineage,
            "gen": self._gen,
            "next_seq": self._next_seq,
            "segments": [
                {"name": s.name, "n": s.n, "seq0": s.seq0,
                 "nbytes": s.nbytes, "zone": s.zone}
                for s in self.segments
            ],
        }
        path = self._manifest_path()
        tmp = path.with_suffix(".tmp")
        with open(tmp, "w") as fh:
            fh.write(json.dumps(doc))
            fh.flush()
            os.fsync(fh.fileno())
        tmp.replace(path)

    def _recover(self) -> None:
        """Open a store directory trusting ONLY the manifest: committed
        segments whose file is missing/short/undecodable are a torn tail
        — dropped (with everything after them), never half-read. Stray
        files the manifest does not name (a crash between file write and
        commit) are deleted."""
        path = self._manifest_path()
        doc: dict = {}
        if path.exists():
            try:
                doc = json.loads(path.read_text())
            except ValueError:
                doc = {}
        entries = list(doc.get("segments", []))
        self.lineage = doc.get("lineage", self.lineage)
        self._gen = int(doc.get("gen", 0))
        kept: List[Segment] = []
        dropped = 0
        for i, entry in enumerate(entries):
            p = self.directory / str(entry["name"])
            seg = None
            if p.exists() and p.stat().st_size == int(entry["nbytes"]):
                try:
                    seg = Segment.open(p)
                    if seg.n != int(entry["n"]):
                        seg = None
                except (SegmentFormatError, OSError, ValueError):
                    seg = None
            if seg is None:
                # torn tail: this and every later committed entry drop
                dropped = len(entries) - i
                break
            kept.append(seg)
        self.segments = kept
        self.torn_dropped += dropped
        # seqs of dropped rows are NEVER reused: next_seq keeps the
        # manifest's (pre-crash) value, falling back to the kept tail
        self._next_seq = int(doc.get(
            "next_seq",
            kept[-1].seq0 + kept[-1].n if kept else 0,
        ))
        for i in range(len(kept)):
            self._note_segment(i)
        named = {s.name for s in kept}
        for stray in self.directory.glob(f"seg-*{SEG_SUFFIX}"):
            if stray.name not in named:
                stray.unlink(missing_ok=True)
        if dropped:
            self._commit_manifest()  # commit the repair

    # -- retention + compaction --------------------------------------------
    def maintain(
        self,
        now_ms: Optional[float] = None,
        max_units: Optional[int] = None,
    ) -> Dict[str, int]:
        """One maintenance pass: drop segments wholly past the retention
        horizon, merge runs of adjacent small-or-dirty segments
        (generational checkpoint tails, low-rate stores, score
        write-backs) into sealed ones, and re-encode lone dirty segments
        so their overlays become durable. O(segments) when there is
        nothing to do — cheap enough for the instance's background tick;
        never called from the seal path (a hot tenant's ingest must not
        pay re-encodes). ``max_units`` caps RE-ENCODE units per pass
        (each unit is one merge/rewrite bounded at 2x the row budget) —
        the instance tick runs inline on the event loop, and a
        fully-rescored 1M-row store must not re-encode every segment in
        one synchronous pass; the remainder completes on later ticks.
        Retention drops are cheap and never capped."""
        actions = {"dropped": 0, "merged": 0, "rewritten": 0}
        changed = False
        # files to delete AFTER the new manifest commits: unlinking a
        # committed file first would, on a crash inside this pass, make
        # recovery read the OLD manifest, treat the missing file as a
        # torn tail, and drop every committed segment after it
        doomed: List[Path] = []
        if self.retention_ms > 0 and self.segments:
            horizon = (
                now_ms if now_ms is not None else time.time() * 1000.0
            ) - self.retention_ms
            keep: List[Segment] = []
            victims: List[Segment] = []
            for s in self.segments:
                if s.zone["ts_max"] < horizon:
                    victims.append(s)
                else:
                    keep.append(s)
            if victims:
                self.segments = keep
                for s in victims:
                    self.dropped_rows += s.n
                    if s.path is not None:
                        s.close()
                        # only a dir-mode store owns its files; a restored
                        # memory store's segments are mmap'd CHECKPOINT
                        # files (checkpoint.py names them in its seg meta)
                        # — deleting those outside the checkpoint commit
                        # protocol would lose committed rows on the next
                        # restore
                        if self.directory is not None:
                            doomed.append(s.path)
                self.dropped_segments += len(victims)
                actions["dropped"] = len(victims)
                changed = True
        small = max(1, self.rows_per_segment // 2)
        # merged output may exceed the seal budget (generational merge)
        # but never unboundedly: 2x caps the re-encode unit
        cap = 2 * self.rows_per_segment

        def _candidate(s: Segment) -> bool:
            return s.n < small or s.is_dirty

        units = 0
        i = 0
        while i < len(self.segments):
            if max_units is not None and units >= max_units:
                break  # re-encode budget spent; later ticks finish
            run = [self.segments[i]]
            j = i + 1
            while (
                j < len(self.segments)
                and _candidate(self.segments[j])
                and _candidate(run[-1])
                and self.segments[j].seq0 == run[-1].seq0 + run[-1].n
                and sum(s.n for s in run) + self.segments[j].n <= cap
            ):
                run.append(self.segments[j])
                j += 1
            if len(run) >= 2:
                merged = self._merge_run(run, doomed)
                self.segments[i:j] = [merged]
                self.compactions += 1
                self.compacted_segments += len(run)
                actions["merged"] += len(run)
                changed = True
                units += 1
            elif run[0].is_dirty:
                # no mergeable neighbor: re-encode in place so the score
                # overlay survives a restart (write-back durability)
                self.segments[i] = self._merge_run(run, doomed)
                actions["rewritten"] += 1
                changed = True
                units += 1
            i += 1
        if changed:
            self._sealed_cache = None
            self._materialized = None
            self._id_map = None
            self._prefix_map = None
            self._stale_index = []  # positions shifted; activation rebuilds
            if self.directory is not None:
                self._commit_manifest()  # ── commit, THEN delete ──
        for p in doomed:
            p.unlink(missing_ok=True)
        return actions

    def _merge_run(self, run: List[Segment],
                   doomed: List[Path]) -> Segment:
        """Merge adjacent segments into one (vocab dicts merge + one int32
        remap per part — the ``_merge_vocab_hints`` discipline; ids stay
        lazy when every part is lazy). Replaced files are queued on
        ``doomed`` for the caller to delete AFTER the manifest commit."""
        chunk: Dict[str, object] = {}
        for f in ("value", "score", "event_ts", "received_ts"):
            chunk[f] = np.concatenate([s.numeric(f) for s in run])
        hints: Dict[str, tuple] = {}
        for obj_field, _inv, uniq_key in OBJ_FIELDS:
            parts = [
                {"_vocabs": {obj_field: (s._meta[uniq_key],
                                         s._cols[_inv])}}
                for s in run
            ]
            merged = self._merge_vocab_hints(parts, obj_field)
            hints[obj_field] = merged
            chunk[obj_field] = None  # vocab hint carries the column
        idsegs: List[tuple] = []
        lazy = True
        for s in run:
            ids, spans = s.id_entries()
            if ids is not None:
                lazy = False
                break
            idsegs.extend(spans)
        if lazy:
            chunk["event_id"] = None
            chunk["_idsegs"] = idsegs
        else:
            chunk["event_id"] = np.concatenate([s.event_ids() for s in run])
        data = encode_segment(
            chunk, run[0].seq0, self.tenant, vocab_hints=hints
        )
        merged = Segment.from_bytes(data)
        if self.directory is not None:
            merged = self._write_segment(merged)
            for s in run:
                if s.path is not None:
                    s.close()
                    # deleted by maintain() only after the new manifest
                    # commits — until then the OLD manifest + files remain
                    # a complete recoverable set (a crash here leaves the
                    # merged file as a stray that recovery removes).
                    # Memory-mode stores never unlink: their mmap'd
                    # segments are checkpoint-owned files (see maintain()).
                    doomed.append(s.path)
        return merged

    # -- O(1) event-id index (maintained at seal time) ---------------------
    def _note_segment(self, seg_idx: int) -> None:
        """Seal/adopt-time index upkeep. Lazy-id segments index their
        (prefix, count) spans immediately — O(spans). Explicit-id
        segments would need a per-row Python dict build, so they queue
        for the next lookup (DLQ inspection, replay write-back — both
        off the ingest path) instead of stalling the seal."""
        if self._id_map is None:
            return  # index not activated yet (first find_row builds it)
        ids, _spans = self.segments[seg_idx].id_entries()
        if ids is None:
            self._index_segment(seg_idx)
        else:
            self._stale_index.append(seg_idx)

    def _drain_stale_index(self) -> None:
        if self._stale_index:
            for idx in self._stale_index:
                self._index_segment(idx)
            self._stale_index = []

    def _index_segment(self, seg_idx: int) -> None:
        if self._id_map is None:
            return  # index not activated yet (first find_row builds it)
        seg = self.segments[seg_idx]
        ids, idsegs = seg.id_entries()
        if ids is not None:
            for row, ev_id in enumerate(ids):
                self._id_map[ev_id] = (seg_idx, row)
        elif idsegs:
            base = 0
            for prefix, k in idsegs:
                self._prefix_map[prefix] = (seg_idx, base, int(k))
                base += int(k)

    def _activate_id_index(self) -> None:
        self._id_map = {}
        self._prefix_map = {}
        self._stale_index = []
        for i in range(len(self.segments)):
            self._index_segment(i)

    @staticmethod
    def _resolve_lazy(ev_id: str, pmap) -> Optional[Tuple[int, int]]:
        """Resolve a lazy ``'{hex16}-{row}'`` id against a prefix-span
        map ``{prefix: (slot, base, count)}`` → (slot, base+row) or
        None. The 17-char prefix contract is ``core.batch``'s
        ``make_event_ids`` format — THE one parser for it."""
        if len(ev_id) <= 17:
            return None
        span = pmap.get(ev_id[:17])
        if span is None:
            return None
        slot, base, count = span
        rest = ev_id[17:]
        if not rest.isdigit() or int(rest) >= count:
            return None
        return slot, base + int(rest)

    def find_row(self, event_id: str) -> Optional[Dict[str, object]]:
        """O(1) sealed lookup (id index) + bounded tail scan: the row's
        scalar fields, or None. The index activates lazily on first use
        and is maintained at seal time from then on — DLQ requeue
        inspection stays O(1) as the store grows."""
        if self._id_map is None:
            self._activate_id_index()
        self._drain_stale_index()
        hit = self._id_map.get(event_id)
        if hit is None:
            hit = self._resolve_lazy(event_id, self._prefix_map)
        if hit is not None:
            seg_idx, row = hit
            seg = self.segments[seg_idx]
            out = {
                f: seg.numeric(f)[row]
                for f in ("value", "score", "event_ts", "received_ts")
            }
            for obj_field, _inv, _uk in OBJ_FIELDS:
                u, inv = seg.vocab(obj_field)
                out[obj_field] = str(u[inv[row]]) if len(u) else ""
            out["event_id"] = event_id
            return out
        # live tail: bounded by rows_per_segment, so the scan stays O(1)
        # in store size
        tail = self._tail_arrays()
        idx = np.nonzero(tail["event_id"] == event_id)[0]
        if idx.size == 0:
            return None
        i = int(idx[0])
        return {k: tail[k][i] for k in tail}

    def write_back_scores(self, event_ids, scores) -> int:
        """Record freshly computed scores against store rows (the
        persistence stage calls this for replayed-rescore batches, so a
        LATER rescore job's ``only_unscored`` dedupe skips them — no
        re-publish of already-rescored history within a store lifetime).

        Sealed rows land in copy-on-write overlays per segment: the
        immutable wire bytes stay untouched; ``maintain`` re-encodes
        overlays durably. Rows still in the unsealed tail write into the
        pending chunks / live rows directly (the replay plan includes
        the tail, so its rescored rows must teach the dedupe too) and
        become durable at seal. Foreign ids are skipped. Not a hot path:
        replay is the low-priority lane, and the per-id lookups are O(1)
        each (tail resolution is bounded by ``rows_per_segment``)."""
        if self._id_map is None:
            self._activate_id_index()
        self._drain_stale_index()
        sc = np.asarray(scores, np.float32)
        written = 0
        misses: List[int] = []
        # resolve first, then ONE vectorized scatter per segment — not a
        # numpy scalar store per row (this runs in the persistence stage
        # for every replayed batch)
        per_seg: Dict[int, Tuple[List[int], List[int]]] = {}
        for i, ev_id in enumerate(event_ids):
            hit = self._id_map.get(ev_id)
            if hit is None:
                hit = self._resolve_lazy(ev_id, self._prefix_map)
            if hit is None:
                misses.append(i)
                continue
            rows, idxs = per_seg.setdefault(hit[0], ([], []))
            rows.append(hit[1])
            idxs.append(i)
        for seg_idx, (rows, idxs) in per_seg.items():
            self.segments[seg_idx].writable_scores()[
                np.asarray(rows, np.intp)
            ] = sc[np.asarray(idxs, np.intp)]
            written += len(rows)
        if misses and (self._pending or self._cur["value"]):
            written += self._write_back_tail(event_ids, sc, misses)
        if per_seg and self._sealed_cache is not None:
            # only the score column changed: rebuild it alone — dropping
            # the whole sealed cache would make every REST query during a
            # replay re-pay the object fan-outs + id materialization for
            # the full store
            self._sealed_cache["score"] = np.concatenate(
                [s.numeric("score") for s in self.segments]
            )
        if written:
            self._materialized = None
        return written

    def _write_back_tail(self, event_ids, sc: np.ndarray,
                         misses: List[int]) -> int:
        """Resolve id-index misses against the unsealed tail and write
        scores into the pending chunks / live rows (copy-on-write per
        chunk: a chunk's score array may still be the producer batch's
        own buffer)."""
        explicit: Dict[str, Tuple[int, int]] = {}
        prefixes: Dict[str, Tuple[int, int, int]] = {}
        for ci, p in enumerate(self._pending):
            ids = p.get("event_id")
            if ids is not None:
                for r, ev in enumerate(ids):
                    explicit[ev] = (ci, r)
            elif p.get("_idsegs") is not None:
                base = 0
                for prefix, k in p["_idsegs"]:
                    prefixes[prefix] = (ci, base, int(k))
                    base += int(k)
            else:
                prefixes[p["_idp"]] = (ci, 0, len(p["value"]))
        cur_pos = {
            ev: r for r, ev in enumerate(self._cur["event_id"])
        }
        per_chunk: Dict[int, Tuple[List[int], List[int]]] = {}
        written = 0
        for i in misses:
            ev_id = event_ids[i]
            hit = explicit.get(ev_id)
            if hit is None:
                hit = self._resolve_lazy(ev_id, prefixes)
            if hit is not None:
                rows, idxs = per_chunk.setdefault(hit[0], ([], []))
                rows.append(hit[1])
                idxs.append(i)
                written += 1
                continue
            r = cur_pos.get(ev_id)
            if r is not None:
                self._cur["score"][r] = float(sc[i])
                written += 1
        for ci, (rows, idxs) in per_chunk.items():
            p = self._pending[ci]
            # copy-on-write: the chunk may still hold the producer
            # batch's own score buffer
            p["score"] = np.array(p["score"], np.float32)
            p["score"][np.asarray(rows, np.intp)] = sc[
                np.asarray(idxs, np.intp)
            ]
        return written

    # -- reads -------------------------------------------------------------
    def _tail_arrays(self) -> Dict[str, np.ndarray]:
        cur = self._cur_arrays()
        if not self._pending:
            return cur
        # ids materialize on COPIES (like encode_tail): a REST read
        # racing ingest must not de-lazy the pending chunks in place, or
        # the next seal pays the per-row str() loop and ships the full
        # id list instead of (prefix, count) spans
        parts = [self._ensure_ids(dict(p)) for p in self._pending] + (
            [cur] if len(cur["value"]) else []
        )
        if len(parts) == 1:
            return {k: v for k, v in parts[0].items() if not k.startswith("_")}
        return {
            k: np.concatenate([np.asarray(p[k]) for p in parts])
            for k in cur
        }

    def columns(self) -> Dict[str, np.ndarray]:
        """Materialize all rows as one struct-of-arrays dict. Two-level
        cache: sealed segments concat once per seal (not per append), the
        live tail concats on top per read — a REST query racing live
        ingest pays O(tail), not O(total rows)."""
        if self._materialized is not None:
            return self._materialized
        if self._sealed_cache is None and self.segments:
            chunks = [s.to_chunk() for s in self.segments]
            self._sealed_cache = {
                k: np.concatenate([ch[k] for ch in chunks])
                for k in chunks[0]
            }
        tail = self._tail_arrays()
        if self._sealed_cache is None:
            out = tail
        elif len(tail["value"]) == 0:
            out = self._sealed_cache
        else:
            out = {
                k: np.concatenate([self._sealed_cache[k], tail[k]])
                for k in tail
            }
        self._materialized = out
        return out

    def sealed_chunks(self) -> List[Dict[str, np.ndarray]]:
        """Legacy chunk-dict views of the sealed segments (parquet export
        compatibility; checkpoints ride the segment bytes directly)."""
        return [s.to_chunk() for s in self.segments]

    def __len__(self) -> int:
        return (
            sum(s.n for s in self.segments)
            + self._pending_rows
            + len(self._cur["value"])
        )

    @property
    def next_seq(self) -> int:
        return self._next_seq

    # -- zone-planned scans (the replay feed) ------------------------------
    def tail_segment(self) -> Optional[Segment]:
        """The unsealed tail as an in-memory pseudo-segment (scan
        snapshot; rows appended after the call are not seen)."""
        n_tail = self._pending_rows + len(self._cur["value"])
        if n_tail == 0:
            return None
        return Segment.from_bytes(self.encode_tail(), name="<tail>")

    def plan(
        self,
        ts0: int = 0,
        ts1: int = 0,
        seq_lo: int = 0,
        seq_hi: Optional[int] = None,
        device: str = "",
        include_tail: bool = True,
    ) -> Tuple[List[Segment], int]:
        """Zone-map segment planning: (segments that may hold matching
        rows, count pruned without touching a row)."""
        segs = list(self.segments)
        if include_tail:
            tail = self.tail_segment()
            if tail is not None:
                segs.append(tail)
        selected = []
        pruned = 0
        for s in segs:
            if s.matches(ts0, ts1, seq_lo, seq_hi, device):
                selected.append(s)
            else:
                pruned += 1
        return selected, pruned

    def scan(
        self,
        ts0: int = 0,
        ts1: int = 0,
        seq_lo: int = 0,
        seq_hi: Optional[int] = None,
        device: str = "",
        only_unscored: bool = False,
        batch_rows: int = 8192,
        include_tail: bool = True,
        segments: Optional[List[Segment]] = None,
    ) -> Iterator[ScanSlice]:
        """Stream filtered row windows off the planned segments.

        Rows move as vectorized index picks over the zero-copy column
        views — no per-event objects, no list accumulators (registered in
        tools/check_hotpath.py). Windows chunk the RAW seq range, so a
        consumer that commits ``slice.seq_end`` after each window resumes
        exactly (``only_unscored`` dedupe skips are counted per window —
        replayed ∪ skipped accounting stays exact across a crash)."""
        if segments is None:
            segments, _ = self.plan(
                ts0, ts1, seq_lo, seq_hi, device, include_tail
            )
        for seg in segments:
            lo = max(0, int(seq_lo) - seg.seq0) if seq_lo else 0
            hi = seg.n
            if seq_hi is not None:
                hi = min(hi, int(seq_hi) - seg.seq0 + 1)
            ets = seg.numeric("event_ts")
            score = seg.numeric("score")
            tok_u, tok_inv = seg.vocab("device_token")
            dev_code = -1
            if device:
                match = np.nonzero(tok_u == device)[0]
                if match.size == 0:
                    continue  # bloom false positive: no rows here
                dev_code = int(match[0])
            off = lo
            while off < hi:
                end = min(off + int(batch_rows), hi)
                mask = np.ones((end - off,), bool)
                win_ts = ets[off:end]
                if ts0:
                    mask &= win_ts >= ts0
                if ts1:
                    mask &= win_ts <= ts1
                if dev_code >= 0:
                    mask &= tok_inv[off:end] == dev_code
                skipped = 0
                if only_unscored:
                    scored = ~np.isnan(score[off:end]) & mask
                    skipped = int(scored.sum())
                    mask &= ~scored
                sel = np.nonzero(mask)[0] + off
                yield ScanSlice(
                    seg, sel, skipped, seg.seq0 + end - 1,
                )
                off = end

    # -- introspection -----------------------------------------------------
    def describe(self) -> dict:
        return {
            "tenant": self.tenant,
            "segments": len(self.segments),
            "rows": len(self),
            "sealed_rows": sum(s.n for s in self.segments),
            "tail_rows": self._pending_rows + len(self._cur["value"]),
            "next_seq": self._next_seq,
            "disk_bytes": sum(
                s.nbytes for s in self.segments if s.path is not None
            ),
            "rows_per_segment": self.rows_per_segment,
            "retention_ms": self.retention_ms,
            "compactions": self.compactions,
            "compacted_segments": self.compacted_segments,
            "dropped_segments": self.dropped_segments,
            "dropped_rows": self.dropped_rows,
            "torn_dropped": self.torn_dropped,
            "directory": str(self.directory) if self.directory else None,
            "zone_maps": [
                {"name": s.name, "n": s.n, **{
                    k: s.zone[k] for k in
                    ("ts_min", "ts_max", "seq_min", "seq_max", "n_devices")
                }}
                for s in self.segments
            ],
        }
