"""Wire-speed storage subsystem: columnar segment files with zone-map
indexes, a manifest with commit points, mmap zero-copy reads, and tiered
retention with compaction (docs/STORAGE.md)."""

from sitewhere_tpu.storage.segstore import (
    Segment,
    SegmentColumns,
    SegmentFormatError,
    encode_segment,
    slice_columns,
)

__all__ = [
    "Segment",
    "SegmentColumns",
    "SegmentFormatError",
    "encode_segment",
    "slice_columns",
]
