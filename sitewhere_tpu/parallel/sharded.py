"""Stacked multi-tenant scoring under ``shard_map`` — the SPMD hot path.

The 32-tenant concurrent-scoring config (BASELINE.json:10) runs here. Layout
(one model family per stack; SURVEY.md §7 "tenants-on-mesh"):

- params:  every leaf gains a leading stacked-tenant dim ``[T, ...]``,
  sharded along the mesh ``tenant`` axis (T = n_tenant_shards ×
  slots_per_shard).
- window state: ``[T, S, W]`` — T over ``tenant``, stream capacity S over
  ``data`` (each data shard owns a disjoint set of streams, so window
  updates never race across shards and the hot path needs **zero
  collectives**: pure SPMD fan-out, ICI stays free for training traffic).
- batches: ``[T, B]`` with B over ``data``; the micro-batcher routes each
  stream to its owning (tenant-slot, data-shard) lane and uses *local*
  stream ids, so device code never translates indices.
- active mask ``[T]``: tenants start/stop by flipping a mask bit — no
  recompile (SURVEY.md §7 hard parts: "handle tenant start/stop without
  recompiling the world").

``shard_map`` + vmap-over-slots is the whole trick: each device scores its
resident tenants' events against its resident window state.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import jax

from sitewhere_tpu.compat import shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from sitewhere_tpu.models import ModelSpec
from sitewhere_tpu.models.common import (
    DEFAULT_SCORE_RANGE,
    PARAM_DTYPES,
    SKETCH_NBINS,
    clamp_fuse_k,
    quantize_params,
    sketch_edges,
)
from sitewhere_tpu.ops.windows import (
    WindowState,
    gather_windows,
    init_window_state,
    update_and_gather,
    update_gather_ranked,
    update_windows,
)
from sitewhere_tpu.parallel.mesh import AXIS_DATA, AXIS_TENANT, MeshManager

Params = Any

# Fused megabatch kernels kill switch (mirrors core.batch.WIRE_CODEC_ENABLED):
# flip to False BEFORE scorer construction to build the legacy
# vmap-over-slots step — bit for bit the pre-fusion path (fuse_k/param_dtype
# are ignored there: single-step scores, full-width f32 master weights).
# The rollback knob for a numerics incident in production.
FUSED_STEP_ENABLED = True

# Device-side score sketch kill switch (same pattern): flip to False
# BEFORE scorer construction to build steps that emit no per-slot score
# histogram — the rollback knob if the sketch's segment_sum ever shows up
# in a device profile, and the bench's control twin for measuring the
# sketch's step-time overhead (``scorehealth_pct``).
SCORE_SKETCH_ENABLED = True

# Continual-learning train lane kill switch (same pattern): flip to
# False BEFORE scorer construction to disable the fused stacked train
# step AND the service's async train lane — training then runs the
# pre-lane path bitwise: the legacy per-slot vmap ``_build_train_step``
# dispatched INLINE from the scoring loop every ``every_n_flushes``
# (docs/PERFORMANCE.md "Continual learning lane" → rollback).
TRAIN_LANE_ENABLED = True

# After a param hot-swap (``activate(params=...)``) an armed canary
# shadow-scores its configured fraction of the next this-many flushes, so
# freshly swapped weights get immediate divergence coverage (see
# ``canary_take`` / docs/OBSERVABILITY.md "Score health & canaries").
CANARY_SWAP_FLUSHES = 64


def stack_params(params_list: List[Params]) -> Params:
    """[pytree, ...] → pytree with leading stacked-tenant dim."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


def unstack_slot(stacked: Params, idx: int) -> Params:
    return jax.tree_util.tree_map(lambda x: x[idx], stacked)


def set_slot(stacked: Params, idx: int, params: Params) -> Params:
    """Write one tenant's params into its slot (donate under jit for
    in-place HBM update — how tenant hot-swap avoids recompiles)."""
    return jax.tree_util.tree_map(
        lambda s, p: s.at[idx].set(p.astype(s.dtype)), stacked, params
    )


def init_stacked_state(
    n_slots: int, max_streams: int, window: int
) -> WindowState:
    """Stacked window state [T, S, W]; S is the *global* stream capacity
    (split across data shards inside shard_map)."""
    st = init_window_state(max_streams, window)
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x[None], (n_slots,) + x.shape).copy(), st
    )


class ShardedScorer:
    """Compiled multi-tenant scoring step over the mesh.

    One instance per model family. Host-side state (params, windows) lives
    as sharded jax.Arrays owned by this object; ``step`` is the only device
    round-trip on the hot path.
    """

    def __init__(
        self,
        mm: MeshManager,
        spec: ModelSpec,
        cfg,
        slots_per_shard: int = 8,
        max_streams: int = 4096,
        window: int = 32,
        seed: int = 0,
        wire_dtype: str = "f32",
        fuse_k: int = 1,
        param_dtype: str = "f32",
    ) -> None:
        if spec.score is None:
            raise ValueError(f"model '{spec.name}' has no scorer contract")
        self.mm = mm
        self.spec = spec
        self.cfg = cfg
        # -- fused megabatch kernels (docs/PERFORMANCE.md "Fused tenant
        # kernels"): slot axis folded into the gate contractions via the
        # family's score_stacked entry point. Captured at BUILD time so
        # FUSED_STEP_ENABLED=False reconstructs the legacy path exactly.
        if param_dtype not in PARAM_DTYPES:
            raise ValueError(
                f"param_dtype must be one of {PARAM_DTYPES}, got "
                f"{param_dtype!r}"
            )
        if int(fuse_k) < 1:
            raise ValueError(f"fuse_k must be >= 1, got {fuse_k}")
        self.fused = bool(FUSED_STEP_ENABLED and spec.score_stacked is not None)
        self.fuse_k = int(fuse_k)
        # effective knobs: the legacy path ignores both (pre-fusion
        # semantics — newest-position scores off f32 master weights)
        self.k_steps = clamp_fuse_k(self.fuse_k, window) if self.fused else 1
        self.requested_param_dtype = param_dtype  # family-pin conflict checks
        self.param_dtype = param_dtype if self.fused else "f32"
        self._kernel_params = None   # quantized sidecar (lazy; see below)
        self._kernel_dirty = True
        self._quantize_jit = None
        # -- device-side score sketch (score-quality observability) ------
        # per-slot fixed-bin score histogram emitted by the jitted step
        # (both fused and legacy branches) and materialized by the result
        # reaper; edges are log-spaced over the family's declared score
        # range. Captured at BUILD time like the fused kill switch.
        # -- continual-learning train lane (captured at BUILD time like
        # the fused kill switch): the fused stacked train step + the
        # replay-fed feed state only exist when the family has a
        # loss_stacked contract AND the scorer runs the fused path —
        # the lane's grads must lower through the SAME stacked einsums
        # as scoring, or the MXU win evaporates. False ⇒ the service
        # keeps the inline every_n_flushes path bitwise.
        self.train_lane = bool(
            TRAIN_LANE_ENABLED
            and self.fused
            and getattr(spec, "loss_stacked", None) is not None
        )
        self._train_fused = None       # built by init_optimizer
        self._train_feed_state = None  # replay-fed windows (lazy)
        self._ingest = None            # counts-mode feed scatter jit
        self.sketch = bool(SCORE_SKETCH_ENABLED)
        self.nbins = SKETCH_NBINS
        lo, hi = getattr(spec, "score_range", DEFAULT_SCORE_RANGE)
        self.sketch_edges = sketch_edges(lo, hi, self.nbins)
        self.last_sketch = None  # the latest dispatch's i32[T, D, NBINS]
        # -- shadow-scoring canary (previous-variant divergence) ---------
        # fraction of flushes shadow-scored with the legacy f32 step while
        # a canary condition holds (non-f32 / K>1 variant, or a recent
        # hot-swap); set by the service from TenantEngineConfig.canary_frac
        self.canary_frac = 0.0
        self._canary_tick = 0
        self._canary_countdown = 0
        self._shadow_step_fn = None  # built lazily / at prewarm
        self.slots_per_shard = slots_per_shard
        self.n_slots = mm.n_tenant_shards * slots_per_shard
        if max_streams % mm.n_data_shards:
            raise ValueError(
                f"max_streams {max_streams} must divide across "
                f"{mm.n_data_shards} data shards"
            )
        self.max_streams = max_streams
        self.window = window
        # -- wire format for step_counts (the host↔device byte diet) ------
        # Host↔device bandwidth is a real budget (PCIe on-prem; ~10 MB/s on
        # the tunneled bench rig, where it IS the e2e ceiling): stream ids
        # ship as u16 when the per-shard capacity fits, values/scores ship
        # as bf16/f16 when the tenant opts in, and the bool valid-mask is
        # replaced by one i32 count per (slot, data-shard) lane — 6 bytes
        # per event instead of 36 at slots_per_shard=4.
        import numpy as _np
        try:
            import ml_dtypes as _mld
            _bf16 = _mld.bfloat16
        except ImportError:  # pragma: no cover - ml_dtypes ships with jax
            _bf16 = _np.float32
        if wire_dtype not in ("f32", "bf16", "f16"):
            raise ValueError(f"wire_dtype must be f32|bf16|f16, got {wire_dtype}")
        self.wire_dtype = wire_dtype
        local_cap = max_streams // mm.n_data_shards
        self.ids_np_dtype = _np.uint16 if local_cap <= 65536 else _np.int32
        self.vals_np_dtype = {
            "f32": _np.float32, "bf16": _bf16, "f16": _np.float16,
        }[wire_dtype]

        # identical init per slot; per-tenant training diverges them later
        key = jax.random.PRNGKey(seed)
        base = spec.init(key, cfg)
        self._base_params = base  # pristine copy for slot recycling
        stacked = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (self.n_slots,) + x.shape).copy(),
            base,
        )
        t_shard = mm.tenant_stacked()
        # param placement by PARTITION RULES (parallel.partition — the
        # SNIPPETS [2][3] match_partition_rules pattern): leaf paths map
        # to PartitionSpecs, the stacked slot dim rides the tenant axis,
        # and big dense kernels offer their output dim to the model axis
        # when it exists. On model=1 meshes every spec degenerates to
        # P(tenant) — bit-compatible with the blanket stacked placement.
        from sitewhere_tpu.parallel.partition import (
            DEFAULT_RULES,
            make_shard_and_gather_fns,
            shard_tree,
            stacked_specs,
        )

        self.partition_rules = getattr(spec, "partition_rules", None) or (
            DEFAULT_RULES
        )
        self.param_specs = stacked_specs(
            self.partition_rules, stacked, mm.mesh
        )
        self._param_shard_fns, self._param_gather_fns = (
            make_shard_and_gather_fns(mm.mesh, self.param_specs)
        )
        self.params = shard_tree(stacked, self._param_shard_fns)
        # the compiled step consumes kernel_params(): for quantized
        # variants that tree has a DIFFERENT structure (qw/scale sidecar
        # nodes), so its in_specs come from a shape-only template of the
        # quantized tree — same rules, matched against the sidecar paths
        if self.fused and self.param_dtype != "f32":
            _pd = self.param_dtype
            kernel_template = jax.eval_shape(
                lambda p: quantize_params(p, _pd), stacked
            )
            self.step_param_specs = stacked_specs(
                self.partition_rules, kernel_template, mm.mesh
            )
        else:
            self.step_param_specs = self.param_specs
        state = init_stacked_state(self.n_slots, max_streams, window)
        st_sharding = mm.sharding(AXIS_TENANT, AXIS_DATA)
        self.state = WindowState(
            values=jax.device_put(state.values, st_sharding),
            pos=jax.device_put(state.pos, st_sharding),
            count=jax.device_put(state.count, st_sharding),
        )
        self.active = jax.device_put(
            jnp.zeros((self.n_slots,), bool), t_shard
        )
        # which slots may TRAIN (tenants opt in via TrainingConfig): slots
        # sharing the stack with training disabled score normally but are
        # masked out of train_resident's gradient step
        self.train_mask = jax.device_put(
            jnp.zeros((self.n_slots,), bool), t_shard
        )
        # per-slot learning rate: tenants sharing a family stack keep
        # their OWN lr (the optimizer is scale_by_adam; the lr multiplies
        # the transformed update per slot inside the train step)
        self.slot_lr = jax.device_put(
            jnp.ones((self.n_slots,), jnp.float32), t_shard
        )
        self._step = self._build_step()
        self._step_counts = self._build_step(counts_mode=True)
        # input shardings for the counts wire (ids/vals [T, D*B], counts
        # [T, D] — both tenant×data): stage_inputs puts flush buffers onto
        # these so the jit never reshards and the h2d copy can overlap a
        # previous flush's dispatch
        self._wire_sharding = mm.sharding(AXIS_TENANT, AXIS_DATA)
        # lazy per-slot (unstacked) shard fns for weight paging's
        # stage_slot_params — most scorers never page and must not pay
        self._slot_shard_fns = None

    # -- fused kernel param view -----------------------------------------
    def _invalidate_kernel(self) -> None:
        """Mark the quantized sidecar stale — call after ANY mutation of
        ``self.params`` (activate/set_slot/reset/train/rebuild) so the
        next flush scores against the tenant's current weights."""
        self._kernel_dirty = True

    def kernel_params(self) -> Params:
        """The param tree the compiled step consumes. ``f32`` (or the
        legacy path) reads the master stack directly; ``bf16``/``int8``
        read a lazily re-derived quantized sidecar (per-slot per-channel
        scales — models.common.quantize_params). Deriving is one jitted
        elementwise tree-map dispatched asynchronously, so a post-train
        refresh rides the device queue like any other dispatch; the
        master f32 params stay the single source of truth for training,
        checkpointing, and slot swaps."""
        if not self.fused or self.param_dtype == "f32":
            return self.params
        if self._kernel_dirty or self._kernel_params is None:
            if self._quantize_jit is None:
                pd = self.param_dtype
                self._quantize_jit = jax.jit(
                    lambda p: quantize_params(p, pd)
                )
            self._kernel_params = self._quantize_jit(self.params)
            self._kernel_dirty = False
        return self._kernel_params

    # -- h2d staging (double-buffered feed path) -------------------------
    def stage_inputs(self, stream_ids, values, counts):
        """Asynchronously stage one flush's wire buffers onto the step's
        input shardings. ``jax.device_put`` returns immediately with the
        transfer in flight, so the caller can issue flush N+1's copy while
        flush N's dispatch is still executing — transfer overlaps compute.
        The HOST buffers must stay unmodified until the returned arrays
        are ready (the service rotates staging buffers to guarantee it).
        Returns (ids, vals, counts) device arrays for ``step_counts``."""
        s = self._wire_sharding
        return jax.device_put((stream_ids, values, counts), (s, s, s))

    @staticmethod
    def stage_nbytes(staged) -> int:
        """Host→device bytes one staged flush moves (feed observability)."""
        return int(sum(a.nbytes for a in staged))

    # -- device-time / MFU attribution -----------------------------------
    def flops_per_row(self, b_lane: int = 0) -> float:
        """Analytic matmul FLOPs the device executes per lane row of one
        scoring step (``models.common`` — the family's declared
        ``flops_per_row`` at this scorer's window). ``b_lane`` rides the
        contract for future bucket-dependent models; the window-scan
        models here are bucket-independent."""
        fn = getattr(self.spec, "flops_per_row", None)
        if fn is None:
            return 0.0
        if self.fused:
            # the fused kernel's honest count: heads apply to the last
            # k_steps positions only, and quantized weight matmuls count
            # at their real MAC width (models.common.QUANT_MAC_WIDTH)
            return float(fn(
                self.cfg, self.window,
                k=self.k_steps, param_dtype=self.param_dtype,
            ))
        return float(fn(self.cfg, self.window))

    def flops_per_flush(self, b_lane: int) -> float:
        """FLOPs one flush at lane bucket ``b_lane`` executes: the FULL
        padded plane (every slot × data-shard × lane row runs through the
        model, valid or not) × per-row flops. This is what feeds
        ``tpu_flops_total{family}`` — executed work, the honest MFU
        numerator for a padded-static-shape engine."""
        plane_rows = self.n_slots * self.mm.n_data_shards * int(b_lane)
        return plane_rows * self.flops_per_row(b_lane)

    @property
    def device_label(self) -> str:
        """Metric label for the device that anchors this scorer's result
        path (the gather consolidation target — mesh device 0). Per-flush
        device attribution on a multi-device mesh stamps this; finer
        per-shard attribution arrives with the mesh promotion (ROADMAP
        item 1)."""
        d = self.mm.mesh.devices.flat[0]
        return f"{d.platform}:{d.id}"

    # -- d2h result path (device-side row gather) ------------------------
    # smallest compiled gather size: flushes smaller than this pad up to
    # it (a few KB of d2h — noise), and the ladder stays short enough to
    # prewarm every size per bucket
    GATHER_FLOOR = 2048

    def gather_ladder(self, b_lane: int) -> List[int]:
        """Padded gather output sizes compiled for one bucket's score
        plane: powers of two from GATHER_FLOOR up to the full plane.
        A flush's d2h volume is the smallest rung ≥ its row count, so
        padding waste is < 2× while the compile count stays O(log).
        Cached per bucket — gather_rows runs per flush, and the ladder
        is fixed by (n_slots, data shards, b_lane)."""
        ladders = getattr(self, "_ladders", None)
        if ladders is None:
            ladders = self._ladders = {}
        cached = ladders.get(b_lane)
        if cached is not None:
            return cached
        plane = self.n_slots * self.mm.n_data_shards * b_lane
        sizes: List[int] = []
        g = min(self.GATHER_FLOOR, plane)
        while g < plane:
            sizes.append(g)
            g *= 2
        sizes.append(plane)
        ladders[b_lane] = sizes
        return sizes

    def _gather_fn(self) -> Callable:
        if getattr(self, "_gather", None) is None:
            def gather(scores, counts, size):
                # scores [T, D*B] wire dtype, counts i32[T, D]; the valid
                # rows are front-contiguous per (slot, data-shard) lane,
                # so their COMPACTION indices are derivable on device —
                # no index upload, the counts wire already crossed h2d.
                # Output order is (slot, data-shard, lane position): the
                # flush packs its host-side seqs/rows bookkeeping in the
                # same sorted order (see _flush_slice).
                t, l = scores.shape
                d = counts.shape[1]
                b = l // d
                lanepos = jnp.arange(b, dtype=jnp.int32)
                valid = (
                    lanepos[None, None, :] < counts[:, :, None]
                ).reshape(-1)
                pos = jnp.cumsum(valid.astype(jnp.int32)) - 1
                idx = jnp.where(valid, pos, size)  # pads scatter-drop
                out = jnp.full((size,), jnp.nan, scores.dtype)
                return out.at[idx].set(scores.reshape(-1), mode="drop")

            self._gather = jax.jit(gather, static_argnums=2)
        return self._gather

    def gather_rows(self, scores_dev, counts_dev, n_rows: int):
        """Compact one flush's scored rows out of the [T, D*B] plane ON
        DEVICE: returns a wire-dtype device vector of the smallest ladder
        size ≥ ``n_rows`` (entries past ``n_rows`` are NaN padding).
        This is what makes d2h volume rows-proportional instead of
        tenant-count-proportional — the caller materializes rows×2 bytes
        per flush, never the T×lane score plane."""
        t, l = scores_dev.shape
        b_lane = l // self.mm.n_data_shards
        size = next(
            (s for s in self.gather_ladder(b_lane) if s >= n_rows), t * l
        )
        if self.mm.mesh.devices.size > 1:
            # consolidate onto one device BEFORE the jitted compaction:
            # the cumsum/scatter crosses shards, and letting GSPMD emit
            # an AllGather gang-schedules a rendezvous across every
            # device per flush — on the CPU backend (8 virtual devices
            # on few cores) concurrent flush dispatches deadlock that
            # rendezvous, and on a pod it serializes the mesh. A
            # device_put is point-to-point (d2d/ICI, no rendezvous),
            # rides the same async dispatch, and the single-chip
            # production mesh skips it entirely.
            dev = self.mm.mesh.devices.flat[0]
            scores_dev, counts_dev = jax.device_put(
                (scores_dev, counts_dev), dev
            )
        return self._gather_fn()(scores_dev, counts_dev, size)

    # -- compiled step ---------------------------------------------------
    def _build_step(
        self, counts_mode: bool = False, shadow: bool = False
    ) -> Callable:
        """The scoring jit. Variants sharing this builder:

        - mask mode (``step``): per-row bool valid mask, f32 wire — the
          fully general path (tests, arbitrary row patterns).
        - counts mode (``step_counts``): rows are front-contiguous per
          (slot, data-shard) lane, so validity is ONE i32 count per lane,
          derived on device; ids/values arrive in the thin wire dtypes and
          scores return in the wire dtype. The service hot path uses this.
        - ``shadow``: the canary's reference step — FORCES the legacy
          vmap branch (f32 master weights, single-step scores: exactly
          what the FUSED_STEP_ENABLED kill switch would restore), does
          NOT donate the window state (its state output is discarded —
          the primary step dispatched after it owns the commit), and
          emits no sketch. Dispatch order guarantees the shadow reads
          the pre-flush windows the primary is about to consume.

        Unless ``shadow`` (or the SCORE_SKETCH_ENABLED kill switch is
        off), the step also emits the per-slot score sketch: an
        ``i32[T, D, NBINS]`` fixed-bin histogram of the masked scores,
        accumulated with one ``segment_sum`` over the local score plane
        per data shard — zero collectives; the host merges the D partials
        (a 64-int add per slot). NaN scores are excluded on device (the
        resolve path counts them separately).
        """
        mesh = self.mm.mesh
        spec, cfg = self.spec, self.cfg
        fused = self.fused and not shadow
        k_steps = self.k_steps if not shadow else 1
        emit_sketch = self.sketch and not shadow
        nbins = self.nbins
        edges = jnp.asarray(self.sketch_edges)
        score_dtype = (
            {"f32": jnp.float32, "bf16": jnp.bfloat16, "f16": jnp.float16}[
                self.wire_dtype
            ]
            if counts_mode
            else jnp.float32
        )

        def sketch_of(s, valid):
            # s [T_loc, B_loc] scores, valid bool[T_loc, B_loc]: per-slot
            # histogram via ONE segment_sum over the masked plane. Bin =
            # searchsorted side='right' (np.histogram's left-closed bins);
            # invalid/NaN rows map to the dropped overflow segment.
            t = s.shape[0]
            sf = s.astype(jnp.float32)
            b = jnp.searchsorted(edges, sf, side="right").astype(jnp.int32)
            b = jnp.where(valid & ~jnp.isnan(sf), b, nbins)
            flat = (
                jnp.arange(t, dtype=jnp.int32)[:, None] * (nbins + 1) + b
            ).reshape(-1)
            hist = jax.ops.segment_sum(
                jnp.ones_like(flat), flat, num_segments=t * (nbins + 1)
            )
            return hist.reshape(t, nbins + 1)[:, :nbins]

        def local_step(params, state, active, ids, vals, validity):
            # local shapes: params [T_loc, ...], state [T_loc, S_loc, W],
            # ids/vals [T_loc, B_loc]; validity is bool[T_loc, B_loc]
            # (mask mode) or i32[T_loc, 1] lane counts (counts mode)
            if counts_mode:
                m = (
                    jnp.arange(ids.shape[1], dtype=jnp.int32)[None, :]
                    < validity
                )
            else:
                m = validity
            if not fused:
                def one(p, st, act, i, v, m1):
                    i = i.astype(jnp.int32)
                    v = v.astype(jnp.float32)
                    st2, w, n = update_and_gather(st, i, v, m1)
                    s1 = spec.score(p, cfg, w, n)
                    return st2, jnp.where(act & m1, s1, 0.0).astype(
                        score_dtype
                    )

                st2, s = jax.vmap(one)(params, state, active, ids, vals, m)
            else:
                # fused megabatch path: the window scatter/gather (memory
                # ops, no matmuls) stays vmapped per slot, but scoring
                # runs ONE weight-stacked kernel over the whole
                # [T_loc, B_loc] tenant plane (spec.score_stacked — a
                # single wide einsum per gate contraction instead of
                # T_loc small matmuls)
                def upd(st, i, v, m1):
                    i = i.astype(jnp.int32)
                    v = v.astype(jnp.float32)
                    st2, w, n, later = update_gather_ranked(st, i, v, m1)
                    return st2, w, n, later

                st2, w, n, later = jax.vmap(upd)(state, ids, vals, m)
                sk = spec.score_stacked(params, cfg, w, n, k=k_steps)
                if k_steps > 1:
                    # per-row timestep resolution: a row with ``later``
                    # valid same-stream rows after it in this flush sits
                    # at window position W-1-later, i.e. K-step column
                    # K-1-later; rows older than the K window take the
                    # oldest column
                    idx = jnp.clip(k_steps - 1 - later, 0, k_steps - 1)
                    s = jnp.take_along_axis(sk, idx[..., None], axis=-1)[
                        ..., 0
                    ]
                else:
                    s = sk[..., 0]
                s = jnp.where(active[:, None] & m, s, 0.0).astype(
                    score_dtype
                )
            if emit_sketch:
                hist = sketch_of(s, active[:, None] & m)
                return st2, s, hist[:, None, :]
            return st2, s

        out_specs = [
            P(AXIS_TENANT, AXIS_DATA),       # new state
            P(AXIS_TENANT, AXIS_DATA),       # scores
        ]
        if emit_sketch:
            # each data shard contributes its local partial histogram
            # along axis 1 — no cross-shard reduction on device
            out_specs.append(P(AXIS_TENANT, AXIS_DATA, None))
        # the primary step reads the (possibly quantized) kernel tree;
        # the shadow canary always reads the f32 MASTER tree
        p_specs = self.param_specs if shadow else self.step_param_specs
        smapped = shard_map(
            local_step,
            mesh=mesh,
            in_specs=(
                p_specs,                     # params (per-leaf rules)
                P(AXIS_TENANT, AXIS_DATA),   # window state (S over data)
                P(AXIS_TENANT),              # active mask
                P(AXIS_TENANT, AXIS_DATA),   # stream ids (B over data)
                P(AXIS_TENANT, AXIS_DATA),   # values
                P(AXIS_TENANT, AXIS_DATA),   # valid mask / lane counts
            ),
            out_specs=tuple(out_specs),
        )
        if shadow:
            return jax.jit(smapped)  # NO donation: state stays live
        return jax.jit(smapped, donate_argnums=(1,))

    def prewarm(self, lane_sizes) -> None:
        """Compile every bucketed batch shape up front (counts wire — the
        service hot path). A first-use compile inside the scoring loop
        blocks the event loop for seconds (tens of seconds on TPU) and
        torpedoes p99 — pay it at startup instead. Zero-count lanes leave
        window state untouched (scatter mode=drop)."""
        import numpy as _np

        t, d = self.n_slots, self.mm.n_data_shards
        for b in sorted(set(int(x) for x in lane_sizes)):
            ids = _np.zeros((t, d * b), self.ids_np_dtype)
            vals = _np.zeros((t, d * b), self.vals_np_dtype)
            counts = _np.zeros((t, d), _np.int32)
            # prewarm THROUGH the staging path: committed device arrays
            # and host numpy args hit different jit cache entries, and the
            # hot path always stages first
            ids, vals, counts = self.stage_inputs(ids, vals, counts)
            s = self.step_counts(ids, vals, counts)
            _np.asarray(s)
            # the result path's device-side gather: compile every ladder
            # size for this bucket's plane — a mid-loop gather compile
            # would stall the pipeline exactly like a step compile
            for g in self.gather_ladder(b):
                _np.asarray(self.gather_rows(s, counts, g))
            if self.last_sketch is not None:
                # the sketch rides the same executable; settle its output
                # so nothing compiles lazily later
                _np.asarray(self.last_sketch)
            if self.fused and self.canary_frac > 0:
                # canary-capable scorer: compile the shadow (legacy) step
                # + its gather sizes too — a hot-swap can arm the canary
                # at any time, and its first shadow flush must not pay a
                # mid-traffic compile
                sh = self.shadow_step_counts(ids, vals, counts)
                _np.asarray(sh)
                for g in self.gather_ladder(b):
                    _np.asarray(self.gather_rows(sh, counts, g))
            if t > 1:
                # the single-used-slot d2h slice the flush path takes
                # (see TpuInferenceService._flush_slice) — same rule:
                # never compile inside the scoring loop
                # int32 index: the flush path slices with np.unique of
                # int32 slot ids — dtype must match or it recompiles
                _np.asarray(s[_np.zeros((1,), _np.int32)])

    # chaos knob: >0 makes the next N step() calls raise (fault-injection
    # hook for the auto-failover path, like the bus FaultPlan)
    fault_steps: int = 0

    def step(
        self,
        stream_ids: jnp.ndarray,  # i32[T, B] LOCAL ids per data shard lane
        values: jnp.ndarray,      # f32[T, B]
        valid: jnp.ndarray,       # bool[T, B]
    ) -> jnp.ndarray:
        """Score one stacked micro-batch; returns f32[T, B] scores."""
        if self.fault_steps > 0:
            self.fault_steps -= 1
            raise RuntimeError("injected scorer fault (chaos)")
        out = self._step(
            self.kernel_params(), self.state, self.active,
            stream_ids, values, valid,
        )
        if self.sketch:
            self.state, scores, self.last_sketch = out
        else:
            self.state, scores = out
        return scores

    def step_counts(
        self,
        stream_ids,  # ids_np_dtype[T, D*B] LOCAL ids, front-contiguous/lane
        values,      # vals_np_dtype[T, D*B]
        counts,      # i32[T, D] valid rows per (slot, data-shard) lane
    ) -> jnp.ndarray:
        """Wire-thin scoring step: validity is one count per lane (rows
        fill each lane from the front), so no bool mask crosses
        host→device and ids/values ride the compact wire dtypes. Returns
        scores in the wire dtype, f32[T, D*B]-shaped."""
        if self.fault_steps > 0:
            self.fault_steps -= 1
            raise RuntimeError("injected scorer fault (chaos)")
        out = self._step_counts(
            self.kernel_params(), self.state, self.active,
            stream_ids, values, counts,
        )
        if self.sketch:
            self.state, scores, self.last_sketch = out
        else:
            self.state, scores = out
        return scores

    # -- shadow-scoring canary -------------------------------------------
    def arm_canary(self) -> None:
        """A param hot-swap landed: shadow-score the configured fraction
        of the next CANARY_SWAP_FLUSHES flushes (no-op while
        ``canary_frac`` is 0 or the scorer runs the legacy path)."""
        self._canary_countdown = CANARY_SWAP_FLUSHES

    def canary_active(self) -> bool:
        """A canary condition holds: the stack scores through a variant
        the legacy step would not produce (quantized weights / K-step
        fusion) or a hot-swap recently landed."""
        if not self.fused or self.canary_frac <= 0 or self.spec.score is None:
            return False
        return (
            self.param_dtype != "f32"
            or self.k_steps > 1
            or self._canary_countdown > 0
        )

    def canary_take(self) -> bool:
        """Per-flush decision: True ⇔ this flush also shadow-scores.
        Deterministic stride at ``canary_frac`` (1.0 = every flush);
        the post-swap countdown burns down per flush while armed."""
        if not self.canary_active():
            return False
        if self._canary_countdown > 0:
            self._canary_countdown -= 1
        self._canary_tick += 1
        stride = max(1, int(round(1.0 / min(1.0, self.canary_frac))))
        return self._canary_tick % stride == 0

    def shadow_step_counts(self, stream_ids, values, counts):
        """Score one staged flush with the PREVIOUS variant: the legacy
        vmap step over the f32 MASTER params (exactly the program the
        FUSED_STEP_ENABLED kill switch would restore). Reads — never
        donates or commits — the window state, so it must dispatch
        BEFORE the primary ``step_counts`` consumes the same state
        buffer (dispatch order on one device queue guarantees the read
        sees the pre-flush windows). Returns the wire-dtype score plane;
        the caller gathers it with the same counts for pick-aligned
        comparison."""
        if self._shadow_step_fn is None:
            self._shadow_step_fn = self._build_step(
                counts_mode=True, shadow=True
            )
        _st, scores = self._shadow_step_fn(
            self.params, self.state, self.active,
            stream_ids, values, counts,
        )
        return scores

    def shadow_flops_per_flush(self, b_lane: int) -> float:
        """FLOPs one SHADOW flush executes (legacy full-width count over
        the padded plane). Attributed to ``tpu_shadow_flops_total`` —
        never to ``tpu_flops_total``/``tpu_mfu_pct``, which must reflect
        serving work only."""
        fn = getattr(self.spec, "flops_per_row", None)
        if fn is None:
            return 0.0
        plane = self.n_slots * self.mm.n_data_shards * int(b_lane)
        return plane * float(fn(self.cfg, self.window))

    # -- slot management -------------------------------------------------
    def activate(
        self,
        global_slot: int,
        params: Params = None,
        trainable: bool = True,
        lr: Optional[float] = None,
    ) -> None:
        if params is not None:
            self.params = jax.jit(set_slot, static_argnums=1, donate_argnums=0)(
                self.params, global_slot, params
            )
            self._invalidate_kernel()
            # a hot-swap landed: the canary (if configured) shadow-scores
            # the next window of flushes against the swapped weights
            self.arm_canary()
        self.active = self.active.at[global_slot].set(True)
        self.train_mask = self.train_mask.at[global_slot].set(trainable)
        if lr is not None:
            self.slot_lr = self.slot_lr.at[global_slot].set(lr)

    def deactivate(self, global_slot: int) -> None:
        self.active = self.active.at[global_slot].set(False)
        self.train_mask = self.train_mask.at[global_slot].set(False)

    def reset_slot(self, global_slot: int) -> None:
        """Wipe a slot's window state + params + optimizer moments back to
        pristine — a recycled slot must not leak the previous tenant's
        history, trained weights, or Adam momentum."""
        self.deactivate(global_slot)
        self.slot_lr = self.slot_lr.at[global_slot].set(1.0)
        self.params = set_slot(self.params, global_slot, self._base_params)
        self._invalidate_kernel()
        self.state = WindowState(
            values=self.state.values.at[global_slot].set(0.0),
            pos=self.state.pos.at[global_slot].set(0),
            count=self.state.count.at[global_slot].set(0),
        )
        if getattr(self, "_opt_state", None) is not None:
            self._opt_state = jax.tree_util.tree_map(
                lambda s, f: s.at[global_slot].set(f.astype(s.dtype)),
                self._opt_state,
                self._fresh_opt,
            )
        if self._train_feed_state is not None:
            # a recycled slot must not leak the previous tenant's
            # replayed training windows either
            self._train_feed_state = WindowState(
                values=self._train_feed_state.values.at[global_slot].set(0.0),
                pos=self._train_feed_state.pos.at[global_slot].set(0),
                count=self._train_feed_state.count.at[global_slot].set(0),
            )

    def slot_params(self, global_slot: int) -> Params:
        return unstack_slot(self.params, global_slot)

    # -- weight paging (runtime.paging / docs/PERFORMANCE.md) ------------
    def stage_slot_params(self, params: Params) -> Params:
        """Asynchronously stage ONE tenant's unstacked param tree onto
        the slice mesh ahead of ``activate`` — the ``stage_inputs``
        double-buffer pattern applied to weights: ``device_put`` returns
        with the h2d copy in flight, so a page-in's transfer overlaps
        the previous flush's dispatch and ``set_slot`` consumes
        already-device-resident leaves instead of blocking the
        activation (and the flush critical path) on the copy. Specs are
        the partition rules matched WITHOUT the tenant-axis prepend
        (parallel.partition.unstacked_specs)."""
        if self._slot_shard_fns is None:
            from sitewhere_tpu.parallel.partition import (
                make_shard_and_gather_fns,
                unstacked_specs,
            )

            specs = unstacked_specs(
                self.partition_rules, self._base_params, self.mm.mesh
            )
            self._slot_shard_fns, _ = make_shard_and_gather_fns(
                self.mm.mesh, specs
            )
        from sitewhere_tpu.parallel.partition import shard_tree

        return shard_tree(params, self._slot_shard_fns)

    def slot_opt_state(self, global_slot: int):
        """One slot's optimizer state as COPIED host numpy (None while
        no optimizer is attached). Must run ON THE EVENT-LOOP THREAD:
        train steps donate the stacked opt buffer, so a worker-thread
        zero-copy view would be the same use-after-free
        ``checkpoint.host_copy_params`` guards params against."""
        if getattr(self, "_opt_state", None) is None:
            return None
        import numpy as np

        return jax.tree_util.tree_map(
            lambda x: np.array(x[global_slot], copy=True), self._opt_state
        )

    def restore_slot_opt(self, global_slot: int, opt) -> None:
        """Write one slot's saved optimizer moments back after a
        page-in, so a train-lane tenant resumes mid-descent instead of
        restarting Adam cold. No-op when either side has no optimizer
        state (the family-pinned optimizer is identical across slices,
        so saved/live tree structures always match)."""
        if opt is None or getattr(self, "_opt_state", None) is None:
            return
        self._opt_state = jax.tree_util.tree_map(
            lambda s, o: s.at[global_slot].set(jnp.asarray(o).astype(s.dtype)),
            self._opt_state,
            opt,
        )

    def rebuild_runtime(self) -> None:
        """Recover from a poisoned device runtime: re-materialize params
        host-side if they still answer (else pristine), allocate FRESH
        window/opt state (the step donates its state buffer, so a failed
        dispatch can leave ``self.state`` invalidated), and re-build the
        jitted step. Window history is lost — it rebuilds from live
        traffic; correctness (exactly-once, routing) is unaffected."""
        import numpy as np

        from sitewhere_tpu.parallel.partition import shard_tree

        t_shard = self.mm.tenant_stacked()

        def rematerialize(tree, fallback, shard_fns=None):
            try:
                host = jax.tree_util.tree_map(
                    lambda x: np.array(x, copy=True), tree
                )
                if shard_fns is not None:
                    return shard_tree(host, shard_fns)
                return jax.device_put(host, t_shard)
            except Exception:  # noqa: BLE001 - buffers may be dead
                return fallback()

        def pristine_params():
            stacked = jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(
                    x[None], (self.n_slots,) + x.shape
                ).copy(),
                self._base_params,
            )
            return shard_tree(stacked, self._param_shard_fns)

        self.params = rematerialize(
            self.params, pristine_params, self._param_shard_fns
        )
        self.active = rematerialize(
            self.active,
            lambda: jax.device_put(jnp.zeros((self.n_slots,), bool), t_shard),
        )
        self.train_mask = rematerialize(
            self.train_mask,
            lambda: jax.device_put(jnp.zeros((self.n_slots,), bool), t_shard),
        )
        self.slot_lr = rematerialize(
            self.slot_lr,
            lambda: jax.device_put(
                jnp.ones((self.n_slots,), jnp.float32), t_shard
            ),
        )
        state = init_stacked_state(self.n_slots, self.max_streams, self.window)
        st_sharding = self.mm.sharding(AXIS_TENANT, AXIS_DATA)
        self.state = WindowState(
            values=jax.device_put(state.values, st_sharding),
            pos=jax.device_put(state.pos, st_sharding),
            count=jax.device_put(state.count, st_sharding),
        )
        self._step = self._build_step()
        self._step_counts = self._build_step(counts_mode=True)
        self._kernel_params = None   # may reference dead buffers
        self._kernel_dirty = True
        self._quantize_jit = None
        self._gather = None  # fresh jit cache for the result-path gather
        self._shadow_step_fn = None  # rebuilt lazily on next canary flush
        self.last_sketch = None      # may reference dead buffers
        self._wire_sharding = self.mm.sharding(AXIS_TENANT, AXIS_DATA)
        self._slot_shard_fns = None  # rebuilt lazily on next page-in
        if getattr(self, "_optimizer", None) is not None:
            from sitewhere_tpu.parallel.partition import (
                make_shard_and_gather_fns,
                stacked_specs,
            )

            opt_state = jax.vmap(self._optimizer.init)(self.params)
            self._opt_specs = stacked_specs(
                self.partition_rules, opt_state, self.mm.mesh
            )
            opt_shard_fns, _ = make_shard_and_gather_fns(
                self.mm.mesh, self._opt_specs
            )
            self._opt_state = shard_tree(opt_state, opt_shard_fns)
            self._train = self._build_train_step(
                self._optimizer, self._lr_sign
            )
            if self.train_lane:
                self._train_fused = self._build_train_step_fused(
                    self._optimizer, self._lr_sign
                )
        # the train lane's feed state may reference dead buffers too:
        # drop it — replayed history re-accumulates from the feed (the
        # same windows-rebuild-from-traffic posture as the serve state)
        had_feed = self._train_feed_state is not None
        self._train_feed_state = None
        self._ingest = None
        if had_feed:
            self.init_train_feed()

    # -- training (per-tenant divergence) --------------------------------
    def init_optimizer(self, optimizer=None) -> None:
        """Attach an optimizer; opt state is stacked per slot and sharded
        along the tenant axis like the params.

        Default (None): ``optax.scale_by_adam`` with the PER-SLOT learning
        rates in ``self.slot_lr`` applied inside the train step — tenants
        sharing a family stack each train at their own lr. A custom
        optimizer is also accepted (its update already encodes -lr);
        ``slot_lr`` then acts as a per-slot multiplier (default 1.0)."""
        import optax

        if optimizer is None:
            optimizer = optax.scale_by_adam()
            lr_sign = -1.0   # update is gradient-signed: descend
        else:
            lr_sign = 1.0    # update already encodes the step direction
        self._optimizer = optimizer
        opt_state = jax.vmap(optimizer.init)(self.params)
        # optimizer state placed by the SAME partition rules as the
        # params it mirrors (adam moments share the param paths; the
        # per-slot step count matches no trailing dims → tenant-only)
        from sitewhere_tpu.parallel.partition import (
            make_shard_and_gather_fns,
            shard_tree,
            stacked_specs,
        )

        self._opt_specs = stacked_specs(
            self.partition_rules, opt_state, self.mm.mesh
        )
        opt_shard_fns, _ = make_shard_and_gather_fns(
            self.mm.mesh, self._opt_specs
        )
        self._opt_state = shard_tree(opt_state, opt_shard_fns)
        self._fresh_opt = optimizer.init(self._base_params)  # for reset_slot
        self._lr_sign = lr_sign
        self._train = self._build_train_step(optimizer, lr_sign)
        if self.train_lane:
            self._train_fused = self._build_train_step_fused(
                optimizer, lr_sign
            )

    def _build_train_step(self, optimizer, lr_sign: float = 1.0) -> Callable:
        """Train every slot on its RESIDENT window state — the windows
        already live sharded on device, so training moves ZERO bytes over
        host↔device; grads ride ICI via a single pmean over the data axis
        (the one collective in the whole framework's steady state)."""
        mesh = self.mm.mesh
        spec, cfg, window = self.spec, self.cfg, self.window

        def local_step(params, opt_state, values, pos, count, active, lr):
            # params/opt [T_loc, ...], values [T_loc, S_loc, W], active [T_loc]
            def one(p, o, vals, ps, cnt, act, lr1):
                st = WindowState(values=vals, pos=ps, count=cnt)
                ids = jnp.arange(vals.shape[0], dtype=jnp.int32)
                windows, n = gather_windows(st, ids)
                # only streams with a full-enough history contribute; a
                # masked per-row mean keeps cold/garbage windows out of the
                # gradient and stays well-defined with 0 live streams
                mask = (n >= jnp.minimum(window, 8)).astype(jnp.float32) * act
                def masked_loss(pp):
                    per_row = jax.vmap(
                        lambda w: spec.loss(pp, cfg, w[None])
                    )(windows)  # [S_loc]
                    # psum numerator and denominator SEPARATELY across data
                    # shards: a local mean + pmean would weight shards
                    # equally regardless of how many live streams each holds
                    num = jax.lax.psum((per_row * mask).sum(), AXIS_DATA)
                    den = jnp.maximum(jax.lax.psum(mask.sum(), AXIS_DATA), 1.0)
                    return num / den
                l, grads = jax.value_and_grad(masked_loss)(p)
                # masked_loss is already globally normalized, so the full
                # gradient is the SUM of the shards' partials
                grads = jax.lax.psum(grads, AXIS_DATA)
                updates, o2 = optimizer.update(grads, o, p)
                step_scale = lr_sign * lr1  # per-slot lr (see init_optimizer)
                p2 = jax.tree_util.tree_map(
                    lambda a, u: (a + step_scale * u).astype(a.dtype),
                    p, updates,
                )
                # inactive slots keep pristine params AND optimizer state
                # (an advancing Adam step count would skew bias correction
                # when the slot later activates)
                p2 = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(act > 0, new, old), p2, p
                )
                o2 = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(act > 0, new, old), o2, o
                )
                return p2, o2, l
            act_f = active.astype(jnp.float32)
            return jax.vmap(one)(
                params, opt_state, values, pos, count, act_f, lr
            )

        smapped = shard_map(
            local_step,
            mesh=mesh,
            in_specs=(
                self.param_specs,            # params (per-leaf rules)
                self._opt_specs,             # opt state (same rules)
                P(AXIS_TENANT, AXIS_DATA),   # window values [T, S, W]
                P(AXIS_TENANT, AXIS_DATA),   # pos
                P(AXIS_TENANT, AXIS_DATA),   # count
                P(AXIS_TENANT),              # active mask
                P(AXIS_TENANT),              # per-slot lr
            ),
            out_specs=(self.param_specs, self._opt_specs, P(AXIS_TENANT)),
        )
        return jax.jit(smapped, donate_argnums=(0, 1))

    def train_resident(
        self, slots_mask: Optional[jnp.ndarray] = None
    ) -> jnp.ndarray:
        """One optimizer step for every trainable active slot on its
        resident window state; returns per-slot loss f32[T]. Call
        ``init_optimizer`` first. ``slots_mask`` (bool[T]) further
        restricts which slots step — per-tenant training CADENCE in a
        shared stack rides this."""
        if getattr(self, "_train", None) is None:
            raise RuntimeError("call init_optimizer() first")
        mask = self.active & self.train_mask
        if slots_mask is not None:
            mask = mask & slots_mask
        self.params, self._opt_state, losses = self._train(
            self.params, self._opt_state,
            self.state.values, self.state.pos, self.state.count,
            mask, self.slot_lr,
        )
        # live weights changed: the next flush's fused step must score
        # against a re-quantized sidecar (hot-swap between flushes)
        self._invalidate_kernel()
        return losses

    # -- fused stacked training (the continual-learning train lane) -------
    def _build_train_step_fused(
        self, optimizer, lr_sign: float = 1.0
    ) -> Callable:
        """The train-lane twin of ``_build_train_step``: same masked-mean
        loss semantics (psum'd num/den across data shards, per-slot lr,
        inactive slots frozen), but the loss — and therefore the whole
        BACKWARD pass — runs through the family's ``loss_stacked``
        contract: one wide weight-stacked einsum chain over the [S·B]
        tenant plane per scan step, slot-count-invariant, instead of S
        per-slot vmapped matmuls (tools/check_fusion.py lints the grad
        jaxpr). Slot s's loss depends only on slot s's param slices, so
        the stacked gradient IS the per-slot gradients. The optax
        transform is elementwise, so vmapping it over the slot axis
        stays fused elementwise code — no dots re-enter. Params and opt
        state are DONATED: the step updates HBM in place rather than
        doubling resident weights for the training copy. Window state is
        read-only (never donated), so one compiled step trains on EITHER
        the resident serve windows or the replay-fed feed state."""
        mesh = self.mm.mesh
        spec, cfg, window = self.spec, self.cfg, self.window

        def local_step(params, opt_state, values, pos, count, active, lr):
            # params/opt [T_loc, ...], values [T_loc, S_loc, W]
            def gather_one(vals, ps, cnt):
                st = WindowState(values=vals, pos=ps, count=cnt)
                ids = jnp.arange(vals.shape[0], dtype=jnp.int32)
                return gather_windows(st, ids)

            # window materialization is memory ops (gather/roll) — it
            # stays vmapped per slot like the scoring step's scatter
            windows, n = jax.vmap(gather_one)(values, pos, count)
            act_f = active.astype(jnp.float32)
            # same per-row gate as the legacy step: only streams with a
            # full-enough history contribute, masked mean stays
            # well-defined with 0 live streams
            mask = (
                (n >= jnp.minimum(window, 8)).astype(jnp.float32)
                * act_f[:, None]
            )

            def stacked_loss(p):
                per_row = spec.loss_stacked(p, cfg, windows)  # [T_loc, S_loc]
                # psum numerator and denominator SEPARATELY across data
                # shards (the legacy step's normalization, verbatim)
                num = jax.lax.psum((per_row * mask).sum(-1), AXIS_DATA)
                den = jnp.maximum(
                    jax.lax.psum(mask.sum(-1), AXIS_DATA), 1.0
                )
                per_slot = num / den                          # [T_loc]
                # sum over slots: grads of independent per-slot losses
                # land in their own param slices — one backward pass
                return per_slot.sum(), per_slot

            (_total, per_slot_loss), grads = jax.value_and_grad(
                stacked_loss, has_aux=True
            )(params)
            grads = jax.lax.psum(grads, AXIS_DATA)
            updates, o2 = jax.vmap(
                lambda g, o, p: optimizer.update(g, o, p)
            )(grads, opt_state, params)
            step_scale = lr_sign * lr                         # [T_loc]

            def apply(a, u):
                sc = step_scale.reshape(
                    (-1,) + (1,) * (u.ndim - 1)
                )
                return (a + sc * u).astype(a.dtype)

            p2 = jax.tree_util.tree_map(apply, params, updates)
            # inactive slots keep pristine params AND optimizer state
            # (same freeze as the legacy step)
            def keep_active(new, old):
                sel = active.reshape((-1,) + (1,) * (new.ndim - 1))
                return jnp.where(sel, new, old)

            p2 = jax.tree_util.tree_map(keep_active, p2, params)
            o2 = jax.tree_util.tree_map(keep_active, o2, opt_state)
            return p2, o2, per_slot_loss

        smapped = shard_map(
            local_step,
            mesh=mesh,
            in_specs=(
                self.param_specs,            # params (per-leaf rules)
                self._opt_specs,             # opt state (same rules)
                P(AXIS_TENANT, AXIS_DATA),   # window values [T, S, W]
                P(AXIS_TENANT, AXIS_DATA),   # pos
                P(AXIS_TENANT, AXIS_DATA),   # count
                P(AXIS_TENANT),              # active mask
                P(AXIS_TENANT),              # per-slot lr
            ),
            out_specs=(self.param_specs, self._opt_specs, P(AXIS_TENANT)),
        )
        return jax.jit(smapped, donate_argnums=(0, 1))

    def init_train_feed(self) -> None:
        """Allocate the replay-fed TRAIN window state — the same
        [T, S, W] stacked rings as serving, fed by the train lane's
        replayed microbatches instead of live traffic, so continual
        learning sees windows BEYOND the resident serve state. Lazy:
        only a slice with a replay-fed trainable tenant pays the HBM."""
        if self._train_feed_state is not None:
            return
        state = init_stacked_state(
            self.n_slots, self.max_streams, self.window
        )
        st_sharding = self.mm.sharding(AXIS_TENANT, AXIS_DATA)
        self._train_feed_state = WindowState(
            values=jax.device_put(state.values, st_sharding),
            pos=jax.device_put(state.pos, st_sharding),
            count=jax.device_put(state.count, st_sharding),
        )
        self._ingest = self._build_ingest_step()

    def _build_ingest_step(self) -> Callable:
        """Counts-mode window scatter WITHOUT scoring: replayed rows ride
        the identical staging wire (ids/vals/counts through
        ``stage_inputs``) into the train feed state. Donates the feed
        state — in-place ring update, zero extra resident memory."""
        mesh = self.mm.mesh

        def local_ingest(state, ids, vals, validity):
            m = (
                jnp.arange(ids.shape[1], dtype=jnp.int32)[None, :]
                < validity
            )

            def upd(st, i, v, m1):
                return update_windows(
                    st, i.astype(jnp.int32), v.astype(jnp.float32), m1
                )

            return jax.vmap(upd)(state, ids, vals, m)

        smapped = shard_map(
            local_ingest,
            mesh=mesh,
            in_specs=(
                P(AXIS_TENANT, AXIS_DATA),   # feed window state
                P(AXIS_TENANT, AXIS_DATA),   # stream ids (B over data)
                P(AXIS_TENANT, AXIS_DATA),   # values
                P(AXIS_TENANT, AXIS_DATA),   # lane counts
            ),
            out_specs=P(AXIS_TENANT, AXIS_DATA),
        )
        return jax.jit(smapped, donate_argnums=(0,))

    def train_feed_ingest(self, stream_ids, values, counts) -> None:
        """Scatter one staged replay microbatch into the train feed
        windows (async dispatch; same wire/staging contract as
        ``step_counts``)."""
        self.init_train_feed()
        self._train_feed_state = self._ingest(
            self._train_feed_state, stream_ids, values, counts
        )

    def train_lane_step(
        self,
        slots_mask: Optional[jnp.ndarray] = None,
        replay: bool = False,
    ) -> jnp.ndarray:
        """One FUSED optimizer step on the train lane: resident serve
        windows (``replay=False`` — live adaptation) or the replay-fed
        feed state (``replay=True`` — history beyond the resident
        state). Async jit dispatch; returns the per-slot loss device
        array the caller rides through the completion reaper.

        Unlike ``train_resident`` this does NOT invalidate the serving
        kernel sidecar: the lane's weight updates stay invisible to
        scoring until ``commit_swap`` re-derives the kernel view every
        ``swap_every`` steps — the zero-stall hot-swap boundary."""
        if self._train_fused is None:
            raise RuntimeError(
                "train lane not built — call init_optimizer() on a "
                "train_lane-capable scorer first"
            )
        mask = self.active & self.train_mask
        if slots_mask is not None:
            mask = mask & slots_mask
        st = self._train_feed_state if replay else self.state
        self.params, self._opt_state, losses = self._train_fused(
            self.params, self._opt_state,
            st.values, st.pos, st.count,
            mask, self.slot_lr,
        )
        return losses

    def prewarm_train_lane(self, lane_sizes=()) -> None:
        """Compile the train lane's executables BEFORE traffic — the
        same no-mid-loop-compile rule as ``prewarm``. Runs the REAL
        programs with no observable effect: a zero-count ingest per
        bucket size (scatter drops every row) and one all-False-mask
        train step (the inactive-slot freeze passes params and opt
        state through ``jnp.where`` bitwise). Requires
        ``init_optimizer`` to have run."""
        import numpy as _np

        if self._train_fused is None:
            raise RuntimeError(
                "call init_optimizer() before prewarm_train_lane()"
            )
        self.init_train_feed()
        t, d = self.n_slots, self.mm.n_data_shards
        for b in sorted(set(int(x) for x in lane_sizes)) or [64]:
            ids = _np.zeros((t, d * b), self.ids_np_dtype)
            vals = _np.zeros((t, d * b), self.vals_np_dtype)
            counts = _np.zeros((t, d), _np.int32)
            self.train_feed_ingest(*self.stage_inputs(ids, vals, counts))
        none = _np.zeros((self.n_slots,), bool)
        for replay in (False, True):
            _np.asarray(self.train_lane_step(none, replay=replay))

    def commit_swap(self) -> None:
        """The train lane's between-flush weight commit — the tail of
        ``activate(params=...)``: the fused train steps already updated
        the master stack in place (buffer donation), so committing means
        re-deriving the serving kernel view (the quantized sidecar —
        for bf16/int8 stacks scoring keeps the PREVIOUS weights until
        this runs) and arming the PR 9 shadow canary so the freshly
        swapped weights get immediate divergence coverage. f32 fused
        stacks read the master directly (kernel view == master), so for
        them the commit is the canary arm + observability cadence."""
        self._invalidate_kernel()
        self.arm_canary()

    def train_flops_per_step(self) -> float:
        """Analytic matmul FLOPs ONE fused train step executes: the full
        padded stream plane (every slot × stream row gathers a window
        and runs the teacher-forced loss, live or not) × per-row forward
        FLOPs × 3 (the standard fwd+bwd multiplier: backward re-runs
        ~2× the forward's matmul work). Feeds
        ``tpu_train_flops_total{family}`` — kept OUT of the serving MFU
        account (``tpu_mfu_pct`` means serving work), summed beside it
        by the bench's overlap-MFU column."""
        fn = getattr(self.spec, "flops_per_row", None)
        if fn is None:
            return 0.0
        plane = self.n_slots * self.max_streams
        return 3.0 * plane * float(fn(self.cfg, self.window))
