"""Device-mesh management: axis conventions + construction.

Axis conventions for the whole framework (SURVEY.md §1 "TPU-rebuild layer
correspondence"):

- ``tenant``  — shards of the multitenant axis; per-tenant model params are
  stacked along it and never cross it (no collectives on this axis in the
  scoring hot path → pure SPMD fan-out, ICI silent).
- ``data``    — data parallelism inside a tenant shard (batch split; psum
  for training grads).
- ``model``   — tensor parallelism for the big models (ViT/transformer
  heads/mlp split; all_gather/reduce_scatter ride ICI).

A v5e-8 defaults to (tenant=4, data=2, model=1) for the 32-tenant config
[BASELINE.json:10]; tests use 8 virtual CPU devices via
``--xla_force_host_platform_device_count=8``.
"""

from __future__ import annotations

import logging
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger("sitewhere.mesh")

AXIS_TENANT = "tenant"
AXIS_DATA = "data"
AXIS_MODEL = "model"


def default_mesh(
    tenant: int = 0,
    data: int = 0,
    model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the canonical 3-axis mesh over available devices.

    Zero for ``tenant``/``data`` means "infer": model axis is honored first,
    then tenants get as many shards as possible (the north-star metric is
    tenants/chip), data parallelism absorbs the remainder.
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if model < 1 or n % model:
        raise ValueError(f"model axis {model} does not divide {n} devices")
    rest = n // model
    if tenant == 0 and data == 0:
        tenant, data = rest, 1
    elif tenant == 0:
        tenant = rest // data
    elif data == 0:
        data = rest // tenant
    if tenant * data * model != n:
        raise ValueError(
            f"mesh axes tenant={tenant} data={data} model={model} "
            f"!= {n} devices"
        )
    arr = np.asarray(devs).reshape(tenant, data, model)
    return Mesh(arr, (AXIS_TENANT, AXIS_DATA, AXIS_MODEL))


class MeshManager:
    """Owns the instance's Mesh and hands out shardings.

    Lifecycle-wise this sits in the instance (one mesh per process);
    tenant engines get their shard index from the ``TenantRouter``.
    """

    def __init__(
        self,
        tenant: int = 0,
        data: int = 0,
        model: int = 1,
        devices: Optional[Sequence[jax.Device]] = None,
    ) -> None:
        self.mesh = default_mesh(tenant, data, model, devices)

    @property
    def n_tenant_shards(self) -> int:
        return self.mesh.shape[AXIS_TENANT]

    @property
    def n_data_shards(self) -> int:
        return self.mesh.shape[AXIS_DATA]

    @property
    def n_devices(self) -> int:
        return math.prod(self.mesh.shape.values())

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def tenant_stacked(self) -> NamedSharding:
        """Sharding for arrays with a leading stacked-tenant dim: shard dim 0
        across the tenant axis, replicate across data/model."""
        return self.sharding(AXIS_TENANT)

    def replicated(self) -> NamedSharding:
        return self.sharding()

    def device_labels(self) -> list:
        """Short stable metric-label strings for the mesh's devices
        (``"cpu:0"`` / ``"tpu:3"``), in mesh-flat order. Bounded by the
        mesh size by construction, so stamping them on metric families
        keeps label cardinality device-count-bounded — the per-device
        attribution ROADMAP item 1's mesh promotion needs."""
        return [
            f"{d.platform}:{d.id}" for d in self.mesh.devices.flat
        ]

    # -- tenant-axis slices (multi-chip serving) -------------------------
    @property
    def n_slices(self) -> int:
        """Independent serving slices = tenant-axis shards. Each slice
        owns the (data × model) devices at one tenant coordinate and
        serves its resident tenants with zero cross-slice traffic on the
        hot path (docs/PERFORMANCE.md "Multi-chip serving")."""
        return self.n_tenant_shards

    def slice_manager(self, sl: int) -> "MeshManager":
        """The sub-mesh MeshManager for tenant-axis slice ``sl``: a
        (tenant=1, data=D, model=M) mesh over exactly that slice's
        devices. Per-slice scorers built on these sub-meshes dispatch,
        transfer, and reap independently — one slow chip never
        serializes another slice's flushes. Cached: slice identity is
        stable for the lifetime of the mesh."""
        slices = getattr(self, "_slices", None)
        if slices is None:
            slices = self._slices = {}
        mm = slices.get(sl)
        if mm is None:
            if not 0 <= sl < self.n_tenant_shards:
                raise ValueError(
                    f"slice {sl} out of range (mesh has "
                    f"{self.n_tenant_shards} tenant shards)"
                )
            devs = list(self.mesh.devices[sl].flat)
            mm = slices[sl] = MeshManager(
                tenant=1,
                data=self.mesh.shape[AXIS_DATA],
                model=self.mesh.shape[AXIS_MODEL],
                devices=devs,
            )
        return mm

    def slice_device_label(self, sl: int) -> str:
        """Metric label for the slice's anchor device (its result-path
        consolidation target — slice-mesh device 0). Cached: callers
        include per-flush hot paths (reap gauges, device counters)."""
        labels = getattr(self, "_slice_labels", None)
        if labels is None:
            labels = self._slice_labels = {}
        lbl = labels.get(sl)
        if lbl is None:
            d = self.mesh.devices[sl].flat[0]
            lbl = labels[sl] = f"{d.platform}:{d.id}"
        return lbl

    def describe(self) -> dict:
        return {
            "devices": self.n_devices,
            "platform": jax.devices()[0].platform,
            "axes": dict(self.mesh.shape),
        }
