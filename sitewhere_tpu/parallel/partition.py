"""Partition rules: regex path → PartitionSpec for param/opt-state trees.

The exemplar pattern (SNIPPETS.md [2][3]: ``match_partition_rules`` /
``make_shard_and_gather_fns``) for placing a model's parameter pytree
onto a mesh by NAME instead of by hand: each leaf's tree path is matched
against an ordered rule list, the first hit's ``PartitionSpec`` wins,
and per-leaf shard/gather callables carry arrays on/off the mesh.

The scoring engine stacks per-tenant params along a leading slot dim
sharded over the mesh ``tenant`` axis, so the serving entry point here
is :func:`stacked_specs`: match the rules against the UNSTACKED leaf
dims, prepend ``AXIS_TENANT``, and drop any named axis that does not
exist in the mesh or does not divide the leaf dim (a rule must never
turn into a resharding surprise — an indivisible ask degrades to
replicated-within-shard, exactly the pre-rules placement).

Optimizer state reuses the same rules: adam moments mirror the param
tree (same paths → same specs); scalar-per-slot leaves (e.g. the adam
step count) match no trailing dims and come out ``P(AXIS_TENANT)``-only
by construction.
"""

from __future__ import annotations

import re
from typing import Callable, List, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sitewhere_tpu.parallel.mesh import AXIS_MODEL, AXIS_TENANT

# ordered (path regex, PartitionSpec over the UNSTACKED leaf dims).
# Default serving rules: every leaf replicates within its tenant shard —
# the stacked scoring kernels consume FULL per-slot weights, so a
# model-axis split here would silently hand each model-parallel device a
# kernel chunk. Families whose math IS tensor-parallel-aware opt in by
# declaring ``ModelSpec.partition_rules`` (e.g. MODEL_PARALLEL_RULES
# below); the stacked_specs guard still drops the axis on model=1
# meshes and on indivisible dims.
DEFAULT_RULES: Tuple[Tuple[str, P], ...] = (
    (r".*", P()),
)

# opt-in rule set for TP-aware families: dense kernels ("<node>/w")
# shard their output dim over the model axis, biases replicate.
MODEL_PARALLEL_RULES: Tuple[Tuple[str, P], ...] = (
    (r".*/w$", P(None, AXIS_MODEL)),
    (r".*", P()),
)


def tree_paths(tree, sep: str = "/") -> List[str]:
    """Flat ``sep``-joined key paths of ``tree``'s leaves, in leaf order."""
    paths, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [
        sep.join(str(getattr(k, "key", getattr(k, "idx", k))) for k in kp)
        for kp, _leaf in paths
    ]


def named_tree_map(fn: Callable, tree, sep: str = "/"):
    """``tree_map`` handing ``fn`` the leaf's joined key path first —
    the naming hook ``match_partition_rules`` matches against."""
    return jax.tree_util.tree_map_with_path(
        lambda kp, leaf: fn(
            sep.join(str(getattr(k, "key", getattr(k, "idx", k))) for k in kp),
            leaf,
        ),
        tree,
    )


def _first_match(rules: Sequence[Tuple[str, P]], name: str) -> P:
    for rule, spec in rules:
        if re.search(rule, name) is not None:
            return spec
    raise ValueError(f"no partition rule matched param '{name}'")


def match_partition_rules(rules: Sequence[Tuple[str, P]], tree):
    """Pytree of PartitionSpec per leaf: first rule whose regex matches
    the leaf's path wins; scalar leaves never partition."""

    def get_spec(name: str, leaf) -> P:
        if np.ndim(leaf) == 0 or int(np.prod(np.shape(leaf))) == 1:
            return P()
        return _first_match(rules, name)

    return named_tree_map(get_spec, tree)


def stacked_specs(rules: Sequence[Tuple[str, P]], tree, mesh: Mesh):
    """Serving placement for a slot-STACKED tree: per leaf, match the
    rules against the unstacked dims, prepend the tenant axis, and keep
    a named axis only when the mesh has it with size > 1 AND it divides
    the leaf dim it shards — otherwise that dim replicates. The result
    is always a valid sharding for ``[T, ...]`` stacked leaves and
    degenerates to ``P(AXIS_TENANT)`` everywhere on model=1 meshes
    (bit-compatible with the pre-rules placement)."""
    mesh_shape = dict(mesh.shape)

    def keeps(axis, dim: int) -> bool:
        return (
            axis is not None
            and mesh_shape.get(axis, 1) > 1
            and dim % mesh_shape[axis] == 0
        )

    def stack_one(name: str, leaf) -> P:
        base = tuple(_first_match(rules, name))
        # .shape-first so abstract leaves (jax.eval_shape templates for
        # derived trees, e.g. the quantized kernel sidecar) work too
        leaf_shape = tuple(getattr(leaf, "shape", None) or np.shape(leaf))
        dims = leaf_shape[1:]  # unstacked dims (leading dim = slots)
        base = base[: len(dims)] + (None,) * (len(dims) - len(base))
        kept = tuple(
            ax if keeps(ax, d) else None for ax, d in zip(base, dims)
        )
        return P(AXIS_TENANT, *kept)

    return named_tree_map(stack_one, tree)


def unstacked_specs(rules: Sequence[Tuple[str, P]], tree, mesh: Mesh):
    """Placement for ONE slot's UNSTACKED tree on a slice mesh: the
    :func:`stacked_specs` logic minus the tenant-axis prepend. This is
    the weight-paging staging surface (``ShardedScorer
    .stage_slot_params``): a page-in ``device_put``s one tenant's param
    tree onto these shardings asynchronously — double-buffered like
    ``stage_inputs`` — so ``set_slot`` consumes already-device-resident
    leaves instead of blocking activation on the h2d copy. Same
    degradation guard: a named axis survives only when the mesh has it
    with size > 1 AND it divides the dim it shards."""
    mesh_shape = dict(mesh.shape)

    def keeps(axis, dim: int) -> bool:
        return (
            axis is not None
            and mesh_shape.get(axis, 1) > 1
            and dim % mesh_shape[axis] == 0
        )

    def one(name: str, leaf) -> P:
        leaf_shape = tuple(getattr(leaf, "shape", None) or np.shape(leaf))
        if len(leaf_shape) == 0 or int(np.prod(leaf_shape)) == 1:
            return P()
        base = tuple(_first_match(rules, name))
        base = base[: len(leaf_shape)] + (None,) * (len(leaf_shape) - len(base))
        return P(*(
            ax if keeps(ax, d) else None for ax, d in zip(base, leaf_shape)
        ))

    return named_tree_map(one, tree)


def make_shard_and_gather_fns(mesh: Mesh, specs):
    """Per-leaf (shard, gather) callables from a spec pytree — the
    SNIPPETS [2][3] surface. ``shard_fns`` place host/replicated arrays
    onto the mesh (async ``device_put``); ``gather_fns`` pull them back
    to host numpy (checkpoint/export)."""
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    shard_fns = jax.tree_util.tree_map(
        lambda sh: (lambda x, _sh=sh: jax.device_put(x, _sh)), shardings
    )
    gather_fns = jax.tree_util.tree_map(
        lambda _sh: (lambda x: np.asarray(x)), shardings
    )
    return shard_fns, gather_fns


def shard_tree(tree, shard_fns):
    """Apply a ``make_shard_and_gather_fns`` shard pytree to an array
    pytree (leaf-wise device_put onto the rule-derived shardings)."""
    return jax.tree_util.tree_map(lambda fn, x: fn(x), shard_fns, tree)
