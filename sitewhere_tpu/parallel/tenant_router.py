"""Tenant → mesh-shard router.

The north star's centerpiece: "the multitenant gRPC tenant-engine router
maps tenants onto TPU mesh axes so per-tenant models co-reside on chip"
(BASELINE.json north_star; no reference counterpart — the reference routes
tenants to JVM tenant engines, SURVEY.md §2.3).

Placement model: the mesh's ``tenant`` axis has N shards; each shard hosts a
fixed number of *slots* per model family (XLA's static-shape world: stacked
params are [slots, ...] per shard, so slot count is a compile-time constant
— SURVEY.md §7 "tenants-on-mesh"). A tenant is placed at (family, shard,
slot); heterogeneous families never mix in one stack. Start/stop of a tenant
flips a slot's active mask — no recompile.

Failover: ``failover(tenant)`` re-places a tenant on a different shard
(SURVEY.md §5 "tenant-engine failover to a different mesh shard").
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

logger = logging.getLogger("sitewhere.tenant_router")


@dataclass(frozen=True)
class TenantPlacement:
    tenant: str
    family: str     # model-zoo key; tenants stack only with their own family
    shard: int      # index along the mesh tenant axis
    slot: int       # index within the shard's stacked params
    generation: int = 0  # bumped on failover/re-place


class PlacementError(RuntimeError):
    pass


class TenantRouter:
    """Allocates (shard, slot) per tenant, balancing tenants across shards."""

    def __init__(self, n_shards: int, slots_per_shard: int = 8) -> None:
        if n_shards < 1 or slots_per_shard < 1:
            raise ValueError("n_shards and slots_per_shard must be >= 1")
        self.n_shards = n_shards
        self.slots_per_shard = slots_per_shard
        self._placements: Dict[str, TenantPlacement] = {}
        # family → shard → set of used slots
        self._used: Dict[str, List[Set[int]]] = {}
        # family → shards under quarantine (the flush supervisor's
        # SUSPECT verdict): place/failover/rebalance route around them
        # until probation re-admits the slice (docs/ROBUSTNESS.md
        # "Device fault domains")
        self._quarantined: Dict[str, Set[int]] = {}

    # -- quarantine (fault-domain supervision) ---------------------------
    def quarantine(self, family: str, shard: int) -> None:
        """Mark one (family, shard) SUSPECT: no new placements, no
        failover landings, no rebalance receivers until ``readmit``."""
        self._quarantined.setdefault(family, set()).add(shard)

    def readmit(self, family: str, shard: int) -> None:
        """Probation passed (or an operator lifecycle event): the shard
        serves the family again."""
        q = self._quarantined.get(family)
        if q is not None:
            q.discard(shard)
            if not q:
                del self._quarantined[family]

    def quarantined(self, family: str) -> Set[int]:
        return set(self._quarantined.get(family, ()))

    def _avoided(self, family: str) -> Set[int]:
        return self._quarantined.get(family, set())

    # -- capacity --------------------------------------------------------
    @property
    def capacity_per_family(self) -> int:
        return self.n_shards * self.slots_per_shard

    def shard_load(self, family: str) -> List[int]:
        used = self._used.get(family)
        if used is None:
            return [0] * self.n_shards
        return [len(s) for s in used]

    def tenants_on(self, shard: int, family: Optional[str] = None) -> List[str]:
        return sorted(
            t
            for t, p in self._placements.items()
            if p.shard == shard and (family is None or p.family == family)
        )

    def global_slot(self, p: TenantPlacement) -> int:
        return p.shard * self.slots_per_shard + p.slot

    # -- placement -------------------------------------------------------
    def place(
        self, tenant: str, family: str = "lstm_ad", prefer_shard: Optional[int] = None
    ) -> TenantPlacement:
        if tenant in self._placements:
            return self._placements[tenant]
        used = self._used.setdefault(
            family, [set() for _ in range(self.n_shards)]
        )
        avoid = self._avoided(family)
        # quarantined shards sort last (never skipped entirely: a fleet
        # with EVERY shard quarantined still places — degraded beats
        # unplaceable, and the serving layer passes the slice's events
        # through unscored until probation heals it)
        order = sorted(
            range(self.n_shards),
            key=lambda s: (s in avoid, len(used[s]), s),
        )
        if prefer_shard is not None:
            order = [prefer_shard] + [s for s in order if s != prefer_shard]
        for shard in order:
            if len(used[shard]) < self.slots_per_shard:
                slot = min(set(range(self.slots_per_shard)) - used[shard])
                used[shard].add(slot)
                p = TenantPlacement(tenant, family, shard, slot)
                self._placements[tenant] = p
                logger.info("placed tenant %s → %s/%d.%d", tenant, family, shard, slot)
                return p
        raise PlacementError(
            f"no capacity for tenant '{tenant}' (family={family}, "
            f"{self.capacity_per_family} slots all used)"
        )

    def remove(self, tenant: str) -> None:
        p = self._placements.pop(tenant, None)
        if p is not None:
            self._used[p.family][p.shard].discard(p.slot)

    def placement(self, tenant: str) -> Optional[TenantPlacement]:
        return self._placements.get(tenant)

    def failover(self, tenant: str) -> TenantPlacement:
        """Move a tenant off its current shard (e.g. shard marked unhealthy)."""
        old = self._placements.get(tenant)
        if old is None:
            raise PlacementError(f"tenant '{tenant}' is not placed")
        used = self._used[old.family]
        avoid = self._avoided(old.family)
        # a failover must LAND somewhere healthy — quarantined shards
        # are excluded outright (moving a tenant from one sick slice to
        # another is churn, not healing; with no healthy capacity the
        # PlacementError below leaves the tenant in place, where the
        # quarantined slice degrades it to unscored pass-through until
        # probation re-admits)
        candidates = sorted(
            (
                s for s in range(self.n_shards)
                if s != old.shard and s not in avoid
            ),
            key=lambda s: (len(used[s]), s),
        )
        for shard in candidates:
            if len(used[shard]) < self.slots_per_shard:
                used[old.shard].discard(old.slot)
                slot = min(set(range(self.slots_per_shard)) - used[shard])
                used[shard].add(slot)
                p = TenantPlacement(
                    tenant, old.family, shard, slot, generation=old.generation + 1
                )
                self._placements[tenant] = p
                logger.warning(
                    "failover tenant %s: shard %d → %d", tenant, old.shard, shard
                )
                return p
        raise PlacementError(f"no shard available for failover of '{tenant}'")

    def rebalance(self, family: Optional[str] = None) -> List[
        "Tuple[TenantPlacement, TenantPlacement]"
    ]:
        """Even out per-shard load after removes: repeatedly move one
        tenant from the most-loaded shard to the least-loaded while the
        gap exceeds one slot (a gap of 1 is already optimal — moving
        would just swap the imbalance). Deterministic: donor = highest
        load then highest index, receiver = lowest load then lowest
        index, migrant = lexicographically-first tenant on the donor,
        landing slot = lowest free. Returns ``[(old, new), ...]``
        placements; the CALLER owns migrating live state — the serving
        layer applies each move through its FIFO-preserving slice fence
        (``TpuInferenceService.apply_rebalance``)."""
        moves: List[Tuple[TenantPlacement, TenantPlacement]] = []
        families = [family] if family is not None else sorted(self._used)
        for fam in families:
            used = self._used.get(fam)
            if used is None:
                continue
            avoid = self._avoided(fam)
            healthy = [s for s in range(self.n_shards) if s not in avoid]
            if len(healthy) < 2:
                continue  # nowhere to balance between
            while True:
                load = [len(s) for s in used]
                # quarantined shards neither donate (their tenants are
                # the supervisor's job, moved through failover) nor
                # receive (no landings while SUSPECT) — readmission is
                # what triggers the rebalance-back
                donor = max(healthy, key=lambda s: (load[s], s))
                recv = min(healthy, key=lambda s: (load[s], s))
                if load[donor] - load[recv] <= 1:
                    break
                tenant = min(self.tenants_on(donor, fam))
                old = self._placements[tenant]
                slot = min(set(range(self.slots_per_shard)) - used[recv])
                used[donor].discard(old.slot)
                used[recv].add(slot)
                new = TenantPlacement(
                    tenant, fam, recv, slot, generation=old.generation + 1
                )
                self._placements[tenant] = new
                moves.append((old, new))
                logger.info(
                    "rebalance tenant %s: shard %d.%d → %d.%d",
                    tenant, old.shard, old.slot, recv, slot,
                )
        return moves

    # -- introspection ---------------------------------------------------
    def describe(self) -> dict:
        return {
            "n_shards": self.n_shards,
            "slots_per_shard": self.slots_per_shard,
            "quarantined": {
                fam: sorted(shards)
                for fam, shards in sorted(self._quarantined.items())
                if shards
            },
            "placements": {
                t: {"family": p.family, "shard": p.shard, "slot": p.slot}
                for t, p in sorted(self._placements.items())
            },
        }
