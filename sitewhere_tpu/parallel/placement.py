"""Host-aware tenant placement: the coordinator's view of which serving
process owns which mesh shards (docs/ROBUSTNESS.md "Host fault domains").

:class:`TenantRouter` balances tenants across *shards*; multi-host
serving adds one more fact — shards live on hosts, and hosts die whole.
:class:`HostPlacement` layers that fact on without changing any
single-host behavior: ``register_host`` declares the host → shard
ownership map, ``mark_suspect`` extends the PR 13 quarantine verdict
from one (family, shard) to every shard the host owns, ``adopt`` moves
the host's tenants onto survivors through the same ``failover`` the
device domain uses (quarantined shards can't receive, so adoptions land
only on live hosts), and ``readmit_host`` + ``rebalance`` bring tenants
home after probation.

Cross-host fences mirror ``_SliceFence``: ``adopt`` opens a per-tenant
fence recording where the tenant came from; the supervisor lifts them
(``lift_fences``) only after the adopter confirmed it resumed from the
last committed cursor. FIFO holds across the move because the old
host's later writes are already epoch-fenced at the broker — the fence
here guards the *adopter's* side (no serving the tenant until the
handoff landed), the epoch guards the *zombie's* side.

A deployment that never calls ``register_host`` is a plain
``TenantRouter`` bit for bit — the suspect-shard union is empty and
every inherited method runs unchanged.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Set, Tuple

from sitewhere_tpu.parallel.tenant_router import (
    PlacementError,
    TenantPlacement,
    TenantRouter,
)

logger = logging.getLogger("sitewhere.placement")


class HostPlacement(TenantRouter):
    """A :class:`TenantRouter` that knows which host owns each shard."""

    def __init__(self, n_shards: int, slots_per_shard: int = 8) -> None:
        super().__init__(n_shards, slots_per_shard)
        # host → {"shards": set, "state": "live"|"suspect", "reason": str}
        self._hosts: Dict[str, dict] = {}
        # tenant → cross-host fence opened by adopt(), lifted by the
        # supervisor once the adopter confirmed the handoff
        self._fences: Dict[str, dict] = {}

    # -- host registry ---------------------------------------------------
    def register_host(self, host: str, shards) -> None:
        """Declare (or re-declare) the shards a serving process owns.
        Shard sets must be disjoint across hosts and in range."""
        shard_set = set(int(s) for s in shards)
        for s in shard_set:
            if not (0 <= s < self.n_shards):
                raise PlacementError(
                    f"host '{host}': shard {s} out of range 0..{self.n_shards - 1}"
                )
            owner = self.host_of(s)
            if owner is not None and owner != host:
                raise PlacementError(
                    f"host '{host}': shard {s} already owned by '{owner}'"
                )
        st = self._hosts.setdefault(host, {"state": "live", "reason": ""})
        st["shards"] = shard_set
        logger.info("registered host %s → shards %s", host, sorted(shard_set))

    def host_of(self, shard: int) -> Optional[str]:
        for host, st in self._hosts.items():
            if shard in st.get("shards", ()):
                return host
        return None

    def hosts(self) -> Dict[str, dict]:
        return {
            h: {
                "state": st["state"],
                "shards": sorted(st.get("shards", ())),
                "reason": st.get("reason", ""),
            }
            for h, st in sorted(self._hosts.items())
        }

    def host_state(self, host: str) -> str:
        return self._hosts.get(host, {}).get("state", "unknown")

    def tenants_on_host(self, host: str) -> List[str]:
        shards = self._hosts.get(host, {}).get("shards", set())
        return sorted(
            t for t, p in self._placements.items() if p.shard in shards
        )

    def _suspect_shards(self) -> Set[int]:
        out: Set[int] = set()
        for st in self._hosts.values():
            if st["state"] == "suspect":
                out |= st.get("shards", set())
        return out

    def _avoided(self, family: str) -> Set[int]:
        # the device-domain quarantine PLUS every shard on a suspect
        # host — new families placed after the suspicion route around
        # the dead host without per-family bookkeeping
        return super()._avoided(family) | self._suspect_shards()

    # -- the SUSPECT verdict ---------------------------------------------
    def mark_suspect(self, host: str, reason: str = "lease_expired") -> None:
        """Extend the quarantine verdict to every shard the host owns:
        no new placements, no failover landings, no rebalance receivers
        until ``readmit_host``."""
        st = self._hosts.setdefault(
            host, {"state": "live", "reason": "", "shards": set()}
        )
        st["state"] = "suspect"
        st["reason"] = reason
        for fam in list(self._used):
            for shard in st["shards"]:
                self.quarantine(fam, shard)
        logger.warning(
            "host SUSPECT: %s (%s) — shards %s quarantined",
            host, reason, sorted(st["shards"]),
        )

    def adopt(self, host: str) -> List[Tuple[TenantPlacement, TenantPlacement]]:
        """Move every tenant on the suspect host's shards onto survivors
        via ``failover`` (suspect shards are in ``_avoided``, so landings
        are live-host only). Opens a cross-host fence per moved tenant.
        A tenant with no healthy capacity stays put, degraded — the same
        "degraded beats unplaceable" stance the device domain takes."""
        moves: List[Tuple[TenantPlacement, TenantPlacement]] = []
        for tenant in self.tenants_on_host(host):
            old = self._placements[tenant]
            try:
                new = self.failover(tenant)
            except PlacementError:
                logger.warning(
                    "adoption of tenant %s from host %s: no healthy "
                    "capacity — left in place (degraded)", tenant, host,
                )
                continue
            self._fences[tenant] = {
                "from_host": host,
                "from_shard": old.shard,
                "to_shard": new.shard,
                "since": time.monotonic(),
            }
            moves.append((old, new))
        return moves

    # -- cross-host fences -----------------------------------------------
    def fenced(self, tenant: str) -> bool:
        return tenant in self._fences

    def fences(self, host: Optional[str] = None) -> Dict[str, dict]:
        return {
            t: dict(f) for t, f in self._fences.items()
            if host is None or f["from_host"] == host
        }

    def lift_fence(self, tenant: str) -> bool:
        return self._fences.pop(tenant, None) is not None

    def lift_fences(self, host: Optional[str] = None) -> int:
        """Release the adoption fences (all, or one host's worth).
        Returns how many lifted."""
        doomed = [
            t for t, f in self._fences.items()
            if host is None or f["from_host"] == host
        ]
        for t in doomed:
            del self._fences[t]
        return len(doomed)

    # -- probation passed --------------------------------------------------
    def readmit_host(self, host: str) -> List[
        Tuple[TenantPlacement, TenantPlacement]
    ]:
        """Probation passed: lift the host's shard quarantine and compute
        the rebalance-home moves. The CALLER owns executing them through
        the FIFO-preserving apply path (``apply_rebalance``), exactly as
        with device readmission."""
        st = self._hosts.get(host)
        if st is None:
            return []
        st["state"] = "live"
        st["reason"] = ""
        for fam in list(self._quarantined):
            for shard in list(st.get("shards", ())):
                self.readmit(fam, shard)
        moves = self.rebalance()
        logger.info(
            "host readmitted: %s — %d rebalance-home move(s)",
            host, len(moves),
        )
        return moves

    # -- introspection ---------------------------------------------------
    def describe(self) -> dict:
        out = super().describe()
        out["hosts"] = self.hosts()
        out["fences"] = {
            t: {"from_host": f["from_host"], "from_shard": f["from_shard"],
                "to_shard": f["to_shard"]}
            for t, f in sorted(self._fences.items())
        }
        return out
