"""Parallelism layer: mesh management, tenant routing, sharded scoring.

The reference scales by Kafka partitions + k8s replicas and has no ML
parallelism (SURVEY.md §2 parallelism census [U]). The rebuild's distributed
story is jax.sharding over a device Mesh:

- ``mesh``          Mesh construction (real TPU or virtual CPU devices),
                    axis conventions (tenant/data/model).
- ``tenant_router`` tenant → mesh-shard placement (the north star's
                    "tenant-engine router maps tenants onto TPU mesh axes").
- ``placement``     host-aware placement on top of the router: which serving
                    process owns which shards, host suspicion/adoption for
                    the host fault domain (docs/ROBUSTNESS.md).
- ``sharded``       stacked per-tenant params + shard_map scoring across the
                    tenant axis; dp/tp helpers for the bigger models.
- ``ring``          ring attention (sequence parallelism) for long-history
                    forecasting.
"""

from sitewhere_tpu.parallel.mesh import MeshManager, default_mesh
from sitewhere_tpu.parallel.placement import HostPlacement
from sitewhere_tpu.parallel.tenant_router import TenantRouter, TenantPlacement

__all__ = [
    "MeshManager",
    "default_mesh",
    "HostPlacement",
    "TenantRouter",
    "TenantPlacement",
]
