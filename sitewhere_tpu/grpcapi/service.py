"""gRPC servicers over a live SiteWhereInstance + the method registry.

The reference generates servicer/stub plumbing with protoc's grpc plugin;
this image has none, so the registry below (``METHODS``) is the single
source of truth the server and client build their plumbing from — keep it
in sync with the service blocks in protos/sitewhere.proto.

Scoping/auth contract (mirrors the REST plane and the reference's JWT
propagation over gRPC metadata [U]):

- metadata ``tenant``: tenant token for tenant-scoped services,
- metadata ``authorization``: ``Bearer <jwt>`` from UserManagement;
  reads need a valid token, mutations additionally need the authority
  listed in METHODS.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

import grpc

from sitewhere_tpu.core.events import now_ms
from sitewhere_tpu.grpcapi import converters as cv
from sitewhere_tpu.grpcapi import sitewhere_pb2 as pb
from sitewhere_tpu.services.event_store import EventQuery
from sitewhere_tpu.services.user_management import (
    AUTH_ADMIN,
    AUTH_DEVICE_MANAGE,
    AUTH_EVENT_VIEW,
    AUTH_TENANT_ADMIN,
    AuthError,
    AuthorityError,
)


@dataclass(frozen=True)
class MethodSpec:
    service: str
    name: str
    request_cls: type
    response_cls: type
    authority: Optional[str] = None   # None = any valid token
    tenant_scoped: bool = True


def _paginate(items, paging) -> Tuple[list, int]:
    """Shared in-servicer pagination (1-based page, default size 100)."""
    page = paging.page or 1
    size = paging.page_size or 100
    lo = (page - 1) * size
    return items[lo:lo + size], len(items)


class _Ctx:
    """Per-call resolved context: claims + tenant runtime."""

    __slots__ = ("claims", "runtime")

    def __init__(self, claims, runtime) -> None:
        self.claims = claims
        self.runtime = runtime


class DeviceManagementServicer:
    SERVICE = "sitewhere.grpc.DeviceManagement"

    def __init__(self, instance) -> None:
        self.instance = instance

    async def CreateDevice(self, req: pb.Device, ctx: _Ctx) -> pb.Device:
        d = ctx.runtime.device_management.create_device(cv.device_from_proto(req))
        return cv.device_to_proto(d)

    async def GetDevice(self, req: pb.TokenRequest, ctx: _Ctx) -> pb.Device:
        d = ctx.runtime.device_management.get_device(req.token)
        if d is None:
            raise KeyError(req.token)
        return cv.device_to_proto(d)

    async def ListDevices(self, req: pb.DeviceListRequest, ctx: _Ctx) -> pb.DeviceList:
        page = req.paging.page or 1
        size = req.paging.page_size or 100
        items, total = ctx.runtime.device_management.list_devices(
            page=page, page_size=size, device_type=req.device_type_token
        )
        return pb.DeviceList(
            devices=[cv.device_to_proto(d) for d in items], total=total
        )

    async def DeleteDevice(self, req: pb.TokenRequest, ctx: _Ctx) -> pb.Empty:
        ctx.runtime.device_management.delete_device(req.token)
        return pb.Empty()

    async def CreateDeviceType(self, req: pb.DeviceType, ctx: _Ctx) -> pb.DeviceType:
        dt = ctx.runtime.device_management.create_device_type(
            cv.device_type_from_proto(req)
        )
        return cv.device_type_to_proto(dt)

    async def ListDeviceTypes(self, req: pb.Paging, ctx: _Ctx) -> pb.DeviceTypeList:
        dm = ctx.runtime.device_management
        items, total = dm.device_types.page(req.page or 1, req.page_size or 100)
        return pb.DeviceTypeList(
            device_types=[cv.device_type_to_proto(t) for t in items], total=total
        )

    async def CreateAssignment(
        self, req: pb.DeviceAssignment, ctx: _Ctx
    ) -> pb.DeviceAssignment:
        a = ctx.runtime.device_management.create_assignment(
            cv.assignment_from_proto(req)
        )
        return cv.assignment_to_proto(a)

    async def GetAssignment(self, req: pb.TokenRequest, ctx: _Ctx) -> pb.DeviceAssignment:
        a = ctx.runtime.device_management.get_assignment(req.token)
        if a is None:
            raise KeyError(req.token)
        return cv.assignment_to_proto(a)

    async def ListAssignments(
        self, req: pb.AssignmentListRequest, ctx: _Ctx
    ) -> pb.AssignmentList:
        from sitewhere_tpu.core.model import AssignmentStatus

        status = AssignmentStatus(req.status) if req.status else None
        items, total = ctx.runtime.device_management.list_assignments(
            page=req.paging.page or 1,
            page_size=req.paging.page_size or 100,
            device_token=req.device_token,
            status=status,
        )
        return pb.AssignmentList(
            assignments=[cv.assignment_to_proto(a) for a in items], total=total
        )

    async def ReleaseAssignment(
        self, req: pb.TokenRequest, ctx: _Ctx
    ) -> pb.DeviceAssignment:
        a = ctx.runtime.device_management.release_assignment(req.token)
        return cv.assignment_to_proto(a)

    async def CreateArea(self, req: pb.Area, ctx: _Ctx) -> pb.Area:
        a = ctx.runtime.device_management.create_area(cv.area_from_proto(req))
        return cv.area_to_proto(a)

    async def ListAreas(self, req: pb.Paging, ctx: _Ctx) -> pb.AreaList:
        dm = ctx.runtime.device_management
        items, total = dm.areas.page(req.page or 1, req.page_size or 100)
        return pb.AreaList(areas=[cv.area_to_proto(a) for a in items], total=total)


class EventManagementServicer:
    SERVICE = "sitewhere.grpc.EventManagement"

    def __init__(self, instance) -> None:
        self.instance = instance

    async def ListMeasurements(
        self, req: pb.MeasurementQuery, ctx: _Ctx
    ) -> pb.MeasurementList:
        q = EventQuery(
            assignment_token=req.assignment_token,
            device_token=req.device_token,
            area_token=req.area_token,
            name=req.name,
            start_ts=req.start_ts,
            end_ts=req.end_ts,
            page=req.paging.page or 1,
            page_size=req.paging.page_size or 100,
        )
        items, total = ctx.runtime.event_store.list_measurements(q)
        return pb.MeasurementList(
            measurements=[cv.measurement_to_proto(m) for m in items], total=total
        )

    async def AddMeasurements(
        self, req: pb.AddMeasurementsRequest, ctx: _Ctx
    ) -> pb.AddMeasurementsResponse:
        """Ingest through the pipeline: requests enter at the
        decoded-events topic — the same insertion point as an event source
        (SURVEY.md §3.1), so they get inbound validation, TPU scoring,
        persistence, and rules like any device-originated event."""
        bus = self.instance.bus
        tenant = ctx.runtime.tenant
        topic = bus.naming.decoded_events(tenant)
        now = now_ms()
        accepted = 0
        tracer = getattr(self.instance, "tracer", None)
        # gRPC is an ingest edge like any event source: mint here so
        # pipeline spans trace API-originated events too (guarded — a
        # tracing-disabled tenant pays no per-measurement mint)
        traced = tracer is not None and tracer.enabled_for(tenant)
        for m in req.measurements:
            r = {
                "type": "measurement",
                "device_token": m.device_token,
                "name": m.name,
                "value": m.value,
                "event_ts": m.event_ts or now,
                "received_ts": now,
            }
            if traced:
                r["_trace"] = tracer.mint(
                    tenant, device=m.device_token, source_topic="grpc"
                )
            await bus.publish(topic, r)
            accepted += 1
        return pb.AddMeasurementsResponse(accepted=accepted)


class TenantManagementServicer:
    SERVICE = "sitewhere.grpc.TenantManagement"

    def __init__(self, instance) -> None:
        self.instance = instance

    async def CreateTenant(self, req: pb.TenantCreateRequest, ctx: _Ctx) -> pb.Tenant:
        t = await self.instance.tenant_management.create_tenant(
            req.token, name=req.name, template=req.template or "default"
        )
        await self.instance.drain_tenant_updates()
        return cv.tenant_to_proto(t)

    async def GetTenant(self, req: pb.TokenRequest, ctx: _Ctx) -> pb.Tenant:
        t = self.instance.tenant_management.get_tenant(req.token)
        if t is None:
            raise KeyError(req.token)
        return cv.tenant_to_proto(t)

    async def ListTenants(self, req: pb.Empty, ctx: _Ctx) -> pb.TenantList:
        return pb.TenantList(
            tenants=[
                cv.tenant_to_proto(t)
                for t in self.instance.tenant_management.list_tenants()
            ]
        )

    async def UpdateTenant(self, req: pb.TenantUpdateRequest, ctx: _Ctx) -> pb.Tenant:
        kw = {}
        if req.name:
            kw["name"] = req.name
        if req.template:
            kw["template"] = req.template
        t = await self.instance.tenant_management.update_tenant(req.token, **kw)
        await self.instance.drain_tenant_updates()
        return cv.tenant_to_proto(t)

    async def DeleteTenant(self, req: pb.TokenRequest, ctx: _Ctx) -> pb.Empty:
        await self.instance.tenant_management.delete_tenant(req.token)
        await self.instance.drain_tenant_updates()
        return pb.Empty()


class AssetManagementServicer:
    SERVICE = "sitewhere.grpc.AssetManagement"

    def __init__(self, instance) -> None:
        self.instance = instance

    async def CreateAssetType(self, req: pb.AssetType, ctx: _Ctx) -> pb.AssetType:
        at = ctx.runtime.asset_management.create_asset_type(
            cv.asset_type_from_proto(req)
        )
        return cv.asset_type_to_proto(at)

    async def ListAssetTypes(self, req: pb.Paging, ctx: _Ctx) -> pb.AssetTypeList:
        items, total = ctx.runtime.asset_management.list_asset_types(
            page=req.page or 1, page_size=req.page_size or 100
        )
        return pb.AssetTypeList(
            asset_types=[cv.asset_type_to_proto(t) for t in items], total=total
        )

    async def CreateAsset(self, req: pb.Asset, ctx: _Ctx) -> pb.Asset:
        a = ctx.runtime.asset_management.create_asset(cv.asset_from_proto(req))
        return cv.asset_to_proto(a)

    async def GetAsset(self, req: pb.TokenRequest, ctx: _Ctx) -> pb.Asset:
        a = ctx.runtime.asset_management.get_asset(req.token)
        if a is None:
            raise KeyError(req.token)
        return cv.asset_to_proto(a)

    async def ListAssets(self, req: pb.AssetListRequest, ctx: _Ctx) -> pb.AssetList:
        items, total = ctx.runtime.asset_management.list_assets(
            page=req.paging.page or 1, page_size=req.paging.page_size or 100,
            asset_type=req.asset_type_token,
        )
        return pb.AssetList(
            assets=[cv.asset_to_proto(a) for a in items], total=total
        )

    async def DeleteAsset(self, req: pb.TokenRequest, ctx: _Ctx) -> pb.Empty:
        ctx.runtime.asset_management.delete_asset(req.token)
        return pb.Empty()


class ScheduleManagementServicer:
    SERVICE = "sitewhere.grpc.ScheduleManagement"

    def __init__(self, instance) -> None:
        self.instance = instance

    async def CreateSchedule(self, req: pb.Schedule, ctx: _Ctx) -> pb.Schedule:
        s = ctx.runtime.schedules.create_schedule(cv.schedule_from_proto(req))
        return cv.schedule_to_proto(s)

    async def GetSchedule(self, req: pb.TokenRequest, ctx: _Ctx) -> pb.Schedule:
        s = ctx.runtime.schedules.get_schedule(req.token)
        if s is None:
            raise KeyError(req.token)
        return cv.schedule_to_proto(s)

    async def ListSchedules(self, req: pb.Paging, ctx: _Ctx) -> pb.ScheduleList:
        page, total = _paginate(ctx.runtime.schedules.list_schedules(), req)
        return pb.ScheduleList(
            schedules=[cv.schedule_to_proto(s) for s in page], total=total,
        )

    async def DeleteSchedule(self, req: pb.TokenRequest, ctx: _Ctx) -> pb.Empty:
        ctx.runtime.schedules.delete_schedule(req.token)
        return pb.Empty()


class BatchManagementServicer:
    SERVICE = "sitewhere.grpc.BatchManagement"

    def __init__(self, instance) -> None:
        self.instance = instance

    async def CreateBatchOperation(
        self, req: pb.BatchCreateRequest, ctx: _Ctx
    ) -> pb.BatchOperation:
        op = ctx.runtime.batch.create_operation(
            req.command_token,
            device_tokens=list(req.device_tokens) or None,
            group_token=req.group_token,
            role=req.role,
            parameters=dict(req.parameters),
        )
        if req.submit:
            await ctx.runtime.batch.submit(op.token)
        return cv.batch_op_to_proto(op)

    async def GetBatchOperation(
        self, req: pb.TokenRequest, ctx: _Ctx
    ) -> pb.BatchOperation:
        op = ctx.runtime.batch.get_operation(req.token)
        if op is None:
            raise KeyError(req.token)
        return cv.batch_op_to_proto(op)

    async def ListBatchOperations(
        self, req: pb.Paging, ctx: _Ctx
    ) -> pb.BatchOperationList:
        ops = sorted(
            ctx.runtime.batch.operations.values(),
            key=lambda o: o.created_ts,
        )
        page, total = _paginate(ops, req)
        return pb.BatchOperationList(
            operations=[cv.batch_op_to_proto(o) for o in page], total=total,
        )

    async def CancelBatchOperation(
        self, req: pb.TokenRequest, ctx: _Ctx
    ) -> pb.BatchOperation:
        ctx.runtime.batch.cancel(req.token)
        op = ctx.runtime.batch.get_operation(req.token)
        if op is None:
            raise KeyError(req.token)
        return cv.batch_op_to_proto(op)


class UserManagementServicer:
    SERVICE = "sitewhere.grpc.UserManagement"

    def __init__(self, instance) -> None:
        self.instance = instance

    async def CreateUser(self, req: pb.UserCreateRequest, ctx: _Ctx) -> pb.User:
        u = self.instance.users.create_user(
            req.username, req.password, list(req.authorities),
            first_name=req.first_name, last_name=req.last_name,
        )
        return cv.user_to_proto(u)

    async def GetUser(self, req: pb.TokenRequest, ctx: _Ctx) -> pb.User:
        u = self.instance.users.get_user(req.token)
        if u is None:
            raise KeyError(req.token)
        return cv.user_to_proto(u)

    async def ListUsers(self, req: pb.Paging, ctx: _Ctx) -> pb.UserList:
        page, total = _paginate(self.instance.users.list_users(), req)
        return pb.UserList(
            users=[cv.user_to_proto(u) for u in page], total=total,
        )

    async def DeleteUser(self, req: pb.TokenRequest, ctx: _Ctx) -> pb.Empty:
        self.instance.users.delete_user(req.token)
        return pb.Empty()


class CommandManagementServicer:
    SERVICE = "sitewhere.grpc.CommandManagement"

    def __init__(self, instance) -> None:
        self.instance = instance

    async def AddCommand(self, req: pb.AddCommandRequest, ctx: _Ctx) -> pb.DeviceCommand:
        cmd = ctx.runtime.device_management.add_command(
            req.device_type_token, cv.command_from_proto(req.command)
        )
        return cv.command_to_proto(cmd)

    async def InvokeCommand(
        self, req: pb.InvokeCommandRequest, ctx: _Ctx
    ) -> pb.CommandInvocationAck:
        """The §3.2 write path over gRPC: create + dispatch an invocation
        through the command-invocations topic (same as the REST plane)."""
        from sitewhere_tpu.core.events import DeviceCommandInvocation

        rt = ctx.runtime
        asg = rt.device_management.get_assignment(req.assignment_token)
        if asg is None:
            raise KeyError(req.assignment_token)
        inv = DeviceCommandInvocation(
            device_token=asg.device_token,
            assignment_token=asg.token,
            tenant=rt.tenant,
            command_token=req.command_token,
            initiator=req.initiator or "grpc",
            initiator_id=ctx.claims.get("sub", ""),
            parameters=dict(req.parameters),
        )
        # persist BEFORE dispatch, like the REST plane: the device's later
        # command_response references this id, and the invocation must be
        # visible to event queries (the cloud→device audit trail)
        rt.event_store.add_event(inv)
        await self.instance.bus.publish(
            self.instance.bus.naming.command_invocations(rt.tenant), inv
        )
        return pb.CommandInvocationAck(invocation_id=inv.id)


# ---------------------------------------------------------------- registry
# (service class, method name, request, response, authority-for-mutations,
# tenant-scoped). Keep in sync with protos/sitewhere.proto.

METHODS: Tuple[MethodSpec, ...] = (
    # DeviceManagement
    MethodSpec("sitewhere.grpc.DeviceManagement", "CreateDevice",
               pb.Device, pb.Device, AUTH_DEVICE_MANAGE),
    MethodSpec("sitewhere.grpc.DeviceManagement", "GetDevice",
               pb.TokenRequest, pb.Device),
    MethodSpec("sitewhere.grpc.DeviceManagement", "ListDevices",
               pb.DeviceListRequest, pb.DeviceList),
    MethodSpec("sitewhere.grpc.DeviceManagement", "DeleteDevice",
               pb.TokenRequest, pb.Empty, AUTH_DEVICE_MANAGE),
    MethodSpec("sitewhere.grpc.DeviceManagement", "CreateDeviceType",
               pb.DeviceType, pb.DeviceType, AUTH_DEVICE_MANAGE),
    MethodSpec("sitewhere.grpc.DeviceManagement", "ListDeviceTypes",
               pb.Paging, pb.DeviceTypeList),
    MethodSpec("sitewhere.grpc.DeviceManagement", "CreateAssignment",
               pb.DeviceAssignment, pb.DeviceAssignment, AUTH_DEVICE_MANAGE),
    MethodSpec("sitewhere.grpc.DeviceManagement", "GetAssignment",
               pb.TokenRequest, pb.DeviceAssignment),
    MethodSpec("sitewhere.grpc.DeviceManagement", "ListAssignments",
               pb.AssignmentListRequest, pb.AssignmentList),
    MethodSpec("sitewhere.grpc.DeviceManagement", "ReleaseAssignment",
               pb.TokenRequest, pb.DeviceAssignment, AUTH_DEVICE_MANAGE),
    MethodSpec("sitewhere.grpc.DeviceManagement", "CreateArea",
               pb.Area, pb.Area, AUTH_DEVICE_MANAGE),
    MethodSpec("sitewhere.grpc.DeviceManagement", "ListAreas",
               pb.Paging, pb.AreaList),
    # EventManagement
    MethodSpec("sitewhere.grpc.EventManagement", "ListMeasurements",
               pb.MeasurementQuery, pb.MeasurementList, AUTH_EVENT_VIEW),
    MethodSpec("sitewhere.grpc.EventManagement", "AddMeasurements",
               pb.AddMeasurementsRequest, pb.AddMeasurementsResponse,
               AUTH_DEVICE_MANAGE),
    # TenantManagement (instance-scoped)
    MethodSpec("sitewhere.grpc.TenantManagement", "CreateTenant",
               pb.TenantCreateRequest, pb.Tenant, AUTH_TENANT_ADMIN, False),
    MethodSpec("sitewhere.grpc.TenantManagement", "GetTenant",
               pb.TokenRequest, pb.Tenant, None, False),
    MethodSpec("sitewhere.grpc.TenantManagement", "ListTenants",
               pb.Empty, pb.TenantList, None, False),
    MethodSpec("sitewhere.grpc.TenantManagement", "UpdateTenant",
               pb.TenantUpdateRequest, pb.Tenant, AUTH_TENANT_ADMIN, False),
    MethodSpec("sitewhere.grpc.TenantManagement", "DeleteTenant",
               pb.TokenRequest, pb.Empty, AUTH_TENANT_ADMIN, False),
    # AssetManagement
    MethodSpec("sitewhere.grpc.AssetManagement", "CreateAssetType",
               pb.AssetType, pb.AssetType, AUTH_DEVICE_MANAGE),
    MethodSpec("sitewhere.grpc.AssetManagement", "ListAssetTypes",
               pb.Paging, pb.AssetTypeList),
    MethodSpec("sitewhere.grpc.AssetManagement", "CreateAsset",
               pb.Asset, pb.Asset, AUTH_DEVICE_MANAGE),
    MethodSpec("sitewhere.grpc.AssetManagement", "GetAsset",
               pb.TokenRequest, pb.Asset),
    MethodSpec("sitewhere.grpc.AssetManagement", "ListAssets",
               pb.AssetListRequest, pb.AssetList),
    MethodSpec("sitewhere.grpc.AssetManagement", "DeleteAsset",
               pb.TokenRequest, pb.Empty, AUTH_DEVICE_MANAGE),
    # ScheduleManagement
    MethodSpec("sitewhere.grpc.ScheduleManagement", "CreateSchedule",
               pb.Schedule, pb.Schedule, AUTH_DEVICE_MANAGE),
    MethodSpec("sitewhere.grpc.ScheduleManagement", "GetSchedule",
               pb.TokenRequest, pb.Schedule),
    MethodSpec("sitewhere.grpc.ScheduleManagement", "ListSchedules",
               pb.Paging, pb.ScheduleList),
    MethodSpec("sitewhere.grpc.ScheduleManagement", "DeleteSchedule",
               pb.TokenRequest, pb.Empty, AUTH_DEVICE_MANAGE),
    # BatchManagement
    MethodSpec("sitewhere.grpc.BatchManagement", "CreateBatchOperation",
               pb.BatchCreateRequest, pb.BatchOperation, AUTH_DEVICE_MANAGE),
    MethodSpec("sitewhere.grpc.BatchManagement", "GetBatchOperation",
               pb.TokenRequest, pb.BatchOperation),
    MethodSpec("sitewhere.grpc.BatchManagement", "ListBatchOperations",
               pb.Paging, pb.BatchOperationList),
    MethodSpec("sitewhere.grpc.BatchManagement", "CancelBatchOperation",
               pb.TokenRequest, pb.BatchOperation, AUTH_DEVICE_MANAGE),
    # UserManagement (instance-scoped). ADMIN on every method, matching
    # the REST plane: CreateUser accepts arbitrary authorities, so any
    # weaker gate is a privilege-escalation path, and user enumeration is
    # admin-only on REST too
    MethodSpec("sitewhere.grpc.UserManagement", "CreateUser",
               pb.UserCreateRequest, pb.User, AUTH_ADMIN, False),
    MethodSpec("sitewhere.grpc.UserManagement", "GetUser",
               pb.TokenRequest, pb.User, AUTH_ADMIN, False),
    MethodSpec("sitewhere.grpc.UserManagement", "ListUsers",
               pb.Paging, pb.UserList, AUTH_ADMIN, False),
    MethodSpec("sitewhere.grpc.UserManagement", "DeleteUser",
               pb.TokenRequest, pb.Empty, AUTH_ADMIN, False),
    # CommandManagement
    MethodSpec("sitewhere.grpc.CommandManagement", "AddCommand",
               pb.AddCommandRequest, pb.DeviceCommand, AUTH_DEVICE_MANAGE),
    MethodSpec("sitewhere.grpc.CommandManagement", "InvokeCommand",
               pb.InvokeCommandRequest, pb.CommandInvocationAck,
               AUTH_DEVICE_MANAGE),
)

SERVICERS = {
    "sitewhere.grpc.DeviceManagement": DeviceManagementServicer,
    "sitewhere.grpc.EventManagement": EventManagementServicer,
    "sitewhere.grpc.TenantManagement": TenantManagementServicer,
    "sitewhere.grpc.AssetManagement": AssetManagementServicer,
    "sitewhere.grpc.ScheduleManagement": ScheduleManagementServicer,
    "sitewhere.grpc.BatchManagement": BatchManagementServicer,
    "sitewhere.grpc.UserManagement": UserManagementServicer,
    "sitewhere.grpc.CommandManagement": CommandManagementServicer,
}


def build_rpc_handlers(instance) -> list:
    """Generic handlers for grpc.aio.Server — the hand-written analog of
    protoc-generated ``add_*Servicer_to_server`` glue, plus the auth +
    tenant-resolution wrapper every method shares."""
    servicers = {name: cls(instance) for name, cls in SERVICERS.items()}
    by_service: Dict[str, Dict[str, grpc.RpcMethodHandler]] = {}

    def make_handler(spec: MethodSpec, bound: Callable):
        async def handler(request, context):
            md = dict(context.invocation_metadata() or ())
            auth = md.get("authorization", "")
            if not auth.startswith("Bearer "):
                await context.abort(
                    grpc.StatusCode.UNAUTHENTICATED, "missing bearer token"
                )
            try:
                claims = instance.users.validate_token(auth[7:])
                if spec.authority is not None:
                    instance.users.require_authority(claims, spec.authority)
            except AuthorityError as exc:
                await context.abort(
                    grpc.StatusCode.PERMISSION_DENIED, str(exc)
                )
            except AuthError as exc:
                await context.abort(
                    grpc.StatusCode.UNAUTHENTICATED, str(exc)
                )
            runtime = None
            if spec.tenant_scoped:
                tenant = md.get("tenant", "")
                runtime = instance.tenants.get(tenant)
                if runtime is None:
                    await context.abort(
                        grpc.StatusCode.NOT_FOUND, f"unknown tenant '{tenant}'"
                    )
            try:
                return await bound(request, _Ctx(claims, runtime))
            except KeyError as exc:
                await context.abort(grpc.StatusCode.NOT_FOUND, str(exc))
            except ValueError as exc:
                await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))

        return grpc.unary_unary_rpc_method_handler(
            handler,
            request_deserializer=spec.request_cls.FromString,
            response_serializer=lambda m: m.SerializeToString(),
        )

    for spec in METHODS:
        bound = getattr(servicers[spec.service], spec.name)
        by_service.setdefault(spec.service, {})[spec.name] = make_handler(
            spec, bound
        )
    return [
        grpc.method_handlers_generic_handler(service, methods)
        for service, methods in by_service.items()
    ]
