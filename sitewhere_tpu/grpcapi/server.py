"""gRPC server lifecycle component (reference: ``GrpcServer`` in
sitewhere-microservice — SURVEY.md §2.1 [U]; reference mount empty, see
provenance banner). Runs beside the REST surface over the same
SiteWhereInstance."""

from __future__ import annotations

from typing import Optional

import grpc

from sitewhere_tpu.grpcapi.service import build_rpc_handlers
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent


class GrpcServer(LifecycleComponent):
    """grpc.aio server exposing the device/event/tenant services."""

    def __init__(self, instance, host: str = "127.0.0.1", port: int = 0) -> None:
        super().__init__(f"grpc-server[{instance.config.instance_id}]")
        self.instance = instance
        self.host = host
        self.port = port          # 0 = ephemeral; bound port in .bound_port
        self.bound_port: Optional[int] = None
        self._server: Optional[grpc.aio.Server] = None

    async def on_start(self) -> None:
        server = grpc.aio.server()
        server.add_generic_rpc_handlers(tuple(build_rpc_handlers(self.instance)))
        self.bound_port = server.add_insecure_port(f"{self.host}:{self.port}")
        await server.start()
        self._server = server

    async def on_stop(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=2.0)
            self._server = None
            self.bound_port = None


async def serve_grpc(instance, host: str = "127.0.0.1", port: int = 50051) -> GrpcServer:
    """Convenience: start a GrpcServer for a running instance."""
    srv = GrpcServer(instance, host, port)
    await srv.initialize()
    await srv.start()
    return srv
