"""Typed gRPC client (reference: ``ApiChannel`` per-service clients in
sitewhere-microservice — SURVEY.md §2.1 [U]; reference mount empty, see
provenance banner). Built on unary multicallables from the shared METHODS
registry — the hand-written analog of protoc-generated stubs."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import grpc

from sitewhere_tpu.grpcapi.service import METHODS, MethodSpec


class SiteWhereGrpcClient:
    """One channel, all three services; per-call tenant + JWT metadata.

    Usage::

        async with SiteWhereGrpcClient("127.0.0.1:50051", token=jwt) as c:
            dev = await c.call("DeviceManagement", "GetDevice",
                               pb.TokenRequest(token="d1"), tenant="acme")
    """

    def __init__(self, target: str, token: str = "", tenant: str = "") -> None:
        self.target = target
        self.token = token
        self.tenant = tenant
        self._channel: Optional[grpc.aio.Channel] = None
        self._calls: Dict[Tuple[str, str], grpc.aio.UnaryUnaryMultiCallable] = {}

    async def __aenter__(self) -> "SiteWhereGrpcClient":
        await self.connect()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    async def connect(self) -> None:
        self._channel = grpc.aio.insecure_channel(self.target)
        for spec in METHODS:
            self._calls[(spec.service.rsplit(".", 1)[-1], spec.name)] = (
                self._channel.unary_unary(
                    f"/{spec.service}/{spec.name}",
                    request_serializer=lambda m: m.SerializeToString(),
                    response_deserializer=spec.response_cls.FromString,
                )
            )

    async def close(self) -> None:
        if self._channel is not None:
            await self._channel.close()
            self._channel = None
            self._calls.clear()

    def _metadata(self, tenant: Optional[str]) -> tuple:
        md = []
        if self.token:
            md.append(("authorization", f"Bearer {self.token}"))
        t = tenant if tenant is not None else self.tenant
        if t:
            md.append(("tenant", t))
        return tuple(md)

    async def call(self, service: str, method: str, request,
                   tenant: Optional[str] = None):
        """Invoke ``service.method`` (short service name) with metadata."""
        try:
            fn = self._calls[(service, method)]
        except KeyError:
            raise KeyError(
                f"unknown rpc {service}/{method}; known: "
                f"{sorted(set(s for s, _ in self._calls))}"
            ) from None
        return await fn(request, metadata=self._metadata(tenant))


def method_specs() -> Tuple[MethodSpec, ...]:
    return METHODS
