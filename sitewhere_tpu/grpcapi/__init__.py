"""gRPC request/response plane (reference: sitewhere-grpc-* modules,
SURVEY.md §2.1 [U]): protobuf model + converters + aio server/client.

The REST surface (api/rest.py) and this plane expose the same platform;
the reference's microservices talk to each other exclusively over gRPC
(ApiChannel/ApiDemux), which this package's typed clients mirror.
"""

from sitewhere_tpu.grpcapi import sitewhere_pb2 as pb  # noqa: F401
