"""Dataclass ↔ protobuf converters (reference: `*ModelConverter` classes in
sitewhere-grpc-model — SURVEY.md §2.1 [U]; reference mount empty, see
provenance banner). One pair of functions per wire entity; converters are
total in both directions so a round-trip is lossless for the fields the
wire carries."""

from __future__ import annotations

import math
from typing import List, Optional

from sitewhere_tpu.core.events import AlertLevel, DeviceAlert, DeviceMeasurement
from sitewhere_tpu.core.model import (
    Area,
    AssignmentStatus,
    Device,
    DeviceAssignment,
    DeviceStatus,
    DeviceType,
    Tenant,
)
from sitewhere_tpu.grpcapi import sitewhere_pb2 as pb


# -- device model ---------------------------------------------------------

def device_to_proto(d: Device) -> pb.Device:
    return pb.Device(
        token=d.token,
        name=d.name,
        description=d.description,
        device_type_token=d.device_type_token,
        status=d.status.value,
        comments=d.comments,
        parent_device_token=d.parent_device_token,
        metadata=dict(d.metadata),
        created_ts=d.created_ts,
        updated_ts=d.updated_ts,
    )


def device_from_proto(p: pb.Device) -> Device:
    kw = {}
    if p.token:
        kw["token"] = p.token
    if p.created_ts:
        kw["created_ts"] = p.created_ts
    if p.updated_ts:
        kw["updated_ts"] = p.updated_ts
    return Device(
        name=p.name,
        description=p.description,
        device_type_token=p.device_type_token,
        status=DeviceStatus(p.status) if p.status else DeviceStatus.ACTIVE,
        comments=p.comments,
        parent_device_token=p.parent_device_token,
        metadata=dict(p.metadata),
        **kw,
    )


def device_type_to_proto(dt: DeviceType) -> pb.DeviceType:
    return pb.DeviceType(
        token=dt.token,
        name=dt.name,
        description=dt.description,
        container_policy=dt.container_policy,
        image_url=dt.image_url,
        metadata=dict(dt.metadata),
    )


def device_type_from_proto(p: pb.DeviceType) -> DeviceType:
    kw = {"token": p.token} if p.token else {}
    return DeviceType(
        name=p.name,
        description=p.description,
        container_policy=p.container_policy or "standalone",
        image_url=p.image_url,
        metadata=dict(p.metadata),
        **kw,
    )


def assignment_to_proto(a: DeviceAssignment) -> pb.DeviceAssignment:
    return pb.DeviceAssignment(
        token=a.token,
        device_token=a.device_token,
        customer_token=a.customer_token,
        area_token=a.area_token,
        asset_token=a.asset_token,
        status=a.status.value,
        active_date=a.active_date,
        released_date=a.released_date or 0,
        metadata=dict(a.metadata),
    )


def assignment_from_proto(p: pb.DeviceAssignment) -> DeviceAssignment:
    kw = {"token": p.token} if p.token else {}
    if p.active_date:
        kw["active_date"] = p.active_date
    if p.released_date:
        kw["released_date"] = p.released_date
    return DeviceAssignment(
        device_token=p.device_token,
        customer_token=p.customer_token,
        area_token=p.area_token,
        asset_token=p.asset_token,
        status=AssignmentStatus(p.status) if p.status else AssignmentStatus.ACTIVE,
        metadata=dict(p.metadata),
        **kw,
    )


def area_to_proto(a: Area) -> pb.Area:
    return pb.Area(
        token=a.token,
        name=a.name,
        description=a.description,
        area_type_token=a.area_type_token,
        parent_token=a.parent_token,
        bounds=[pb.LatLon(latitude=lat, longitude=lon) for lat, lon in a.bounds],
    )


def area_from_proto(p: pb.Area) -> Area:
    kw = {"token": p.token} if p.token else {}
    return Area(
        name=p.name,
        description=p.description,
        area_type_token=p.area_type_token,
        parent_token=p.parent_token,
        bounds=[(b.latitude, b.longitude) for b in p.bounds],
        **kw,
    )


def tenant_to_proto(t: Tenant) -> pb.Tenant:
    return pb.Tenant(
        token=t.token,
        name=t.name,
        template=t.template,
        auth_token=t.auth_token,
        logo_url=t.logo_url,
        mesh_shard=t.mesh_shard,
    )


# -- events ---------------------------------------------------------------

def measurement_to_proto(m: DeviceMeasurement) -> pb.DeviceMeasurement:
    return pb.DeviceMeasurement(
        id=m.id,
        device_token=m.device_token,
        assignment_token=m.assignment_token,
        area_token=m.area_token,
        name=m.name,
        value=m.value,
        score=m.score if m.score is not None else math.nan,
        has_score=m.score is not None,
        event_ts=m.event_ts,
        received_ts=m.received_ts,
    )


def measurement_from_proto(p: pb.DeviceMeasurement) -> DeviceMeasurement:
    return DeviceMeasurement(
        id=p.id,
        device_token=p.device_token,
        assignment_token=p.assignment_token,
        area_token=p.area_token,
        name=p.name,
        value=p.value,
        score=p.score if p.has_score and not math.isnan(p.score) else None,
        event_ts=p.event_ts,
        received_ts=p.received_ts,
    )


def alert_to_proto(a: DeviceAlert) -> pb.DeviceAlert:
    return pb.DeviceAlert(
        id=a.id,
        device_token=a.device_token,
        assignment_token=a.assignment_token,
        level=a.level.value,
        alert_type=a.alert_type,
        message=a.message,
        event_ts=a.event_ts,
    )


def alert_from_proto(p: pb.DeviceAlert) -> DeviceAlert:
    return DeviceAlert(
        id=p.id,
        device_token=p.device_token,
        assignment_token=p.assignment_token,
        level=AlertLevel(p.level) if p.level else AlertLevel.INFO,
        alert_type=p.alert_type,
        message=p.message,
        event_ts=p.event_ts,
    )


# -- asset / schedule / batch / user / command planes (round-5 parity) ----

def asset_type_to_proto(at) -> pb.AssetType:
    return pb.AssetType(
        token=at.token, name=at.name, description=at.description,
        asset_category=at.asset_category,
    )


def asset_type_from_proto(p: pb.AssetType):
    from sitewhere_tpu.core.model import AssetType

    kw = {"token": p.token} if p.token else {}
    return AssetType(
        name=p.name, description=p.description,
        asset_category=p.asset_category or "device", **kw,
    )


def asset_to_proto(a) -> pb.Asset:
    return pb.Asset(
        token=a.token, name=a.name, description=a.description,
        asset_type_token=a.asset_type_token, image_url=a.image_url,
    )


def asset_from_proto(p: pb.Asset):
    from sitewhere_tpu.core.model import Asset

    kw = {"token": p.token} if p.token else {}
    return Asset(
        name=p.name, description=p.description,
        asset_type_token=p.asset_type_token, image_url=p.image_url, **kw,
    )


def schedule_to_proto(s) -> pb.Schedule:
    return pb.Schedule(
        token=s.token, name=s.name, at_ts=s.at_ts, every_s=s.every_s,
        cron=s.cron, end_ts=s.end_ts, command_token=s.command_token,
        device_tokens=list(s.device_tokens),
        parameters=dict(s.parameters), enabled=s.enabled,
        fire_count=s.fire_count,
    )


def schedule_from_proto(p: pb.Schedule):
    from sitewhere_tpu.services.schedule_management import Schedule

    kw = {"token": p.token} if p.token else {}
    # proto3-optional: unset → dataclass default True (see the .proto note)
    enabled = p.enabled if p.HasField("enabled") else True
    return Schedule(
        name=p.name, at_ts=p.at_ts, every_s=p.every_s, cron=p.cron,
        end_ts=p.end_ts, command_token=p.command_token,
        device_tokens=list(p.device_tokens),
        parameters=dict(p.parameters), enabled=enabled, **kw,
    )


def batch_op_to_proto(op) -> pb.BatchOperation:
    return pb.BatchOperation(
        token=op.token, command_token=op.command_token,
        parameters=dict(op.parameters), status=op.status.value,
        elements=[
            pb.BatchElement(
                device_token=el.device_token, status=el.status.value,
                error=el.error, processed_ts=el.processed_ts,
            )
            for el in op.elements
        ],
        created_ts=op.created_ts, finished_ts=op.finished_ts,
    )


def user_to_proto(u) -> pb.User:
    # never carries password material (hash/salt stay server-side)
    return pb.User(
        username=u.username, first_name=u.first_name, last_name=u.last_name,
        authorities=list(u.authorities), enabled=u.enabled,
        created_ts=u.created_ts,
    )


def command_to_proto(c) -> pb.DeviceCommand:
    return pb.DeviceCommand(
        token=c.token, name=c.name, namespace=c.namespace,
        description=c.description,
        parameters=[
            pb.CommandParameter(
                name=p.get("name", ""), type=p.get("type", "string"),
                required=str(p.get("required", "false")).lower() == "true",
            )
            for p in c.parameters
        ],
    )


def command_from_proto(p: pb.DeviceCommand):
    from sitewhere_tpu.core.model import DeviceCommand

    kw = {"token": p.token} if p.token else {}
    return DeviceCommand(
        name=p.name, namespace=p.namespace or "default",
        description=p.description,
        parameters=[
            {"name": cp.name, "type": cp.type or "string",
             "required": "true" if cp.required else "false"}
            for cp in p.parameters
        ],
        **kw,
    )
