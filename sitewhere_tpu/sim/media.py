"""Synthetic camera-frame generator shared by bench config 5 and the
media-wire test suite.

One definition on purpose: these frames ENCODE the "naturalistic camera
content" contract the compressed media wire is sized for — smooth
structure plus mild sensor noise, so JPEG quantization leaves a zigzag
spectral extent well under 64 and the coefficient truncation ladder
(ops/dct.py COEF_BUCKETS) actually bites. Pure white noise has a flat
spectrum, forces k=64, and certifies nothing a camera ever ships; if
the content recipe needs tuning, tune it HERE so the bench's wire-diet
columns and the parity/e2e tests keep certifying the same contract.
"""

from __future__ import annotations

from typing import List

import numpy as np


def camera_frame(size: int, phase: float, seed: int = 5) -> np.ndarray:
    """One uint8[size, size, 3] frame: low-frequency color structure
    (phase-shifted so consecutive frames differ) + sigma-4 sensor noise."""
    rng = np.random.RandomState(seed + int(phase * 1000) % 99991)
    xx, yy = np.meshgrid(np.arange(size), np.arange(size))
    img = np.stack([
        128 + 96 * np.sin(xx / 19 + phase) * np.cos(yy / 23),
        128 + 80 * np.cos(xx / 13 + phase * 1.3),
        128 + 88 * np.sin((xx + yy) / 31 + phase),
    ], -1)
    img = img + rng.randn(size, size, 3) * 4.0
    return np.clip(img, 0, 255).astype(np.uint8)


def camera_frames(size: int, n: int = 8, seed: int = 5) -> List[np.ndarray]:
    """``n`` consecutive frames of the synthetic feed."""
    return [camera_frame(size, i * 0.7, seed) for i in range(n)]
