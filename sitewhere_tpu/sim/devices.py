"""Synthetic device fleet: temperature sensors over the sim broker.

The CPU-baseline config's generator — "MQTT temperature-sensor simulator
(100 devices) → threshold rule → MQTT outbound" (BASELINE.json:7). Each
device publishes JSON (or binary) measurements on its own topic with a
sinusoidal daily profile + noise; anomaly injection spikes selected
devices so the LSTM/threshold paths have something to catch. Devices also
subscribe to their command topic and ack invocations back through ingest
(the §3.2 loop).
"""

from __future__ import annotations

import asyncio
import json
import math
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from sitewhere_tpu.core.events import now_ms
from sitewhere_tpu.pipeline.decoders import (
    encode_measurement_binary,
    encode_measurements_bulk_binary,
)
from sitewhere_tpu.sim.broker import SimBroker


@dataclass
class SimProfile:
    n_devices: int = 100
    measurement: str = "temperature"
    base: float = 21.0
    daily_amplitude: float = 4.0
    noise: float = 0.15
    period_s: float = 60.0          # compressed "day" for fast tests
    interval_s: float = 0.05        # per-device publish interval
    anomaly_rate: float = 0.0       # probability per sample of a spike
    anomaly_magnitude: float = 12.0
    wire: str = "json"              # json | binary
    token_prefix: str = "dev"
    seed: int = 0
    # samples batched into ONE wire message (devices commonly buffer and
    # send telemetry in bursts; the JSON {"device","events":[...]} form)
    samples_per_message: int = 1


class DeviceSimulator:
    """Publishes synthetic telemetry for a fleet; tracks what it sent."""

    def __init__(
        self,
        broker: SimBroker,
        profile: Optional[SimProfile] = None,
        topic_pattern: str = "sitewhere/input/{device}",
    ) -> None:
        self.broker = broker
        self.profile = profile or SimProfile()
        self.topic_pattern = topic_pattern
        self.rng = random.Random(self.profile.seed)
        self.sent = 0
        self.anomalies_injected: List[Dict] = []
        self.command_acks: List[Dict] = []
        self._tasks: List[asyncio.Task] = []
        self._phase: Dict[str, float] = {}

    def device_tokens(self) -> List[str]:
        return [
            f"{self.profile.token_prefix}-{i:05d}"
            for i in range(self.profile.n_devices)
        ]

    def _value(self, token: str, t: float, force_anomaly: bool = False) -> tuple:
        p = self.profile
        phase = self._phase.setdefault(token, self.rng.uniform(0, 2 * math.pi))
        v = (
            p.base
            + p.daily_amplitude * math.sin(2 * math.pi * t / p.period_s + phase)
            + self.rng.gauss(0, p.noise)
        )
        is_anomaly = force_anomaly or (
            p.anomaly_rate > 0 and self.rng.random() < p.anomaly_rate
        )
        if is_anomaly:
            v += p.anomaly_magnitude * (1 if self.rng.random() < 0.5 else -1)
        return v, is_anomaly

    def _payload(self, token: str, value: float) -> bytes:
        p = self.profile
        if p.wire == "binary":
            return encode_measurement_binary(token, p.measurement, value)
        return json.dumps(
            {
                "type": "measurement",
                "device_token": token,
                "name": p.measurement,
                "value": value,
                "event_ts": now_ms(),
            }
        ).encode()

    async def publish_once(self, token: str, t: float, force_anomaly: bool = False) -> None:
        p = self.profile
        k = max(1, p.samples_per_message)
        if k == 1:
            value, is_anomaly = self._value(token, t, force_anomaly)
            if is_anomaly:
                self.anomalies_injected.append(
                    {"device": token, "value": value, "ts": now_ms()}
                )
            await self.broker.publish(
                self.topic_pattern.format(device=token), self._payload(token, value)
            )
            self.sent += 1
            return
        # burst form: k samples in one wire message
        await self.broker.publish(
            self.topic_pattern.format(device=token),
            self._burst_payload(token, t, force_anomaly),
        )
        self.sent += k

    def _burst_payload(self, token: str, t: float, force_anomaly: bool = False) -> bytes:
        """k buffered samples in one message: JSON ``{"device", "events"}``
        or ONE bulk binary message (the high-rate wire format)."""
        p = self.profile
        k = max(1, p.samples_per_message)
        ts = now_ms()
        values = []
        for j in range(k):
            value, is_anomaly = self._value(
                token, t + j * p.interval_s, force_anomaly and j == 0
            )
            if is_anomaly:
                self.anomalies_injected.append(
                    {"device": token, "value": value, "ts": ts}
                )
            values.append(value)
        if p.wire == "binary":
            return encode_measurements_bulk_binary(
                token, p.measurement, values, base_ts=ts, stride_ms=1
            )
        return json.dumps(
            {
                "device": token,
                "events": [
                    {"type": "measurement", "name": p.measurement,
                     "value": v, "event_ts": ts + j}
                    for j, v in enumerate(values)
                ],
            }
        ).encode()

    async def publish_round(self, t: float) -> None:
        """One sample from every device (deterministic batch mode for tests)."""
        for token in self.device_tokens():
            await self.publish_once(token, t)

    def pregenerate(self, rounds: int, t0: float = 0.0) -> list:
        """Precompute wire payloads for ``rounds`` rounds — lets a bench
        pump measure PIPELINE throughput instead of generator throughput
        (the payload bytes are identical to live generation)."""
        out = []
        for r in range(rounds):
            t = t0 + float(r)
            batch = []
            for token in self.device_tokens():
                p = self.profile
                k = max(1, p.samples_per_message)
                topic = self.topic_pattern.format(device=token)
                if k == 1:
                    value, is_anomaly = self._value(token, t)
                    if is_anomaly:
                        self.anomalies_injected.append(
                            {"device": token, "value": value, "ts": now_ms()}
                        )
                    batch.append((topic, self._payload(token, value), 1))
                else:
                    batch.append((topic, self._burst_payload(token, t), k))
            out.append(batch)
        return out

    async def publish_pregenerated(self, round_payloads: list) -> None:
        for topic, payload, k in round_payloads:
            await self.broker.publish(topic, payload)
            self.sent += k

    async def run(self, duration_s: float) -> None:
        """Free-running mode: every device publishes at its own interval."""

        async def one_device(token: str) -> None:
            p = self.profile
            t0 = asyncio.get_running_loop().time()
            while True:
                t = asyncio.get_running_loop().time() - t0
                if t >= duration_s:
                    return
                await self.publish_once(token, t)
                await asyncio.sleep(p.interval_s)

        self._tasks = [
            asyncio.create_task(one_device(tok)) for tok in self.device_tokens()
        ]
        try:
            await asyncio.gather(*self._tasks)
        finally:
            self._tasks = []

    def stop(self) -> None:
        for t in self._tasks:
            t.cancel()

    # -- device-side command loop (§3.2 ack path) ------------------------
    def listen_for_commands(self, command_pattern: str = "sitewhere/command/+") -> None:
        async def on_command(topic: str, payload: bytes) -> None:
            device = topic.rsplit("/", 1)[-1]
            try:
                frame = json.loads(payload)
            except (ValueError, UnicodeDecodeError):
                frame = {"raw": True}
            ack = {
                "type": "command_response",
                "device_token": device,
                "originating_event_id": frame.get("invocation_id", ""),
                "response": f"ack:{frame.get('command', 'unknown')}",
            }
            self.command_acks.append(ack)
            await self.broker.publish(
                self.topic_pattern.format(device=device),
                json.dumps(ack).encode(),
            )

        self.broker.subscribe(command_pattern, on_command)
