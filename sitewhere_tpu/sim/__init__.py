"""Device simulation: in-proc MQTT-style broker + synthetic device fleets.

The canonical E2E fixture (SURVEY.md §4) and the CPU-baseline benchmark
config's "MQTT temperature-sensor simulator (100 devices)"
(BASELINE.json:7).
"""

from sitewhere_tpu.sim.broker import SimBroker
from sitewhere_tpu.sim.devices import DeviceSimulator, SimProfile

__all__ = ["SimBroker", "DeviceSimulator", "SimProfile"]
