"""In-proc MQTT-style broker: topic pub/sub with wildcard subscriptions.

Stands in for the reference deployment's external MQTT broker (HiveMQ/
ActiveMQ in recipes — SURVEY.md §2.2 event-sources [U]) so the full
device→cloud→device loop runs in one process: simulated devices publish
telemetry, the ingest receiver subscribes; command delivery publishes to
per-device topics, devices subscribe back. Supports MQTT-ish ``+``/``#``
wildcards.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Dict, List, Tuple

Handler = Callable[[str, bytes], Awaitable[None]]


def _topic_matches(pattern: str, topic: str) -> bool:
    p_parts = pattern.split("/")
    t_parts = topic.split("/")
    for i, p in enumerate(p_parts):
        if p == "#":
            return True
        if i >= len(t_parts):
            return False
        if p != "+" and p != t_parts[i]:
            return False
    return len(p_parts) == len(t_parts)


class SimBroker:
    """Async topic broker with wildcard subscriptions."""

    def __init__(self) -> None:
        self._subs: List[Tuple[str, Handler]] = []
        self.published = 0
        self.delivered = 0
        # topic → matched handler tuple. Concrete topic names are a small
        # set (per-device), so wildcard matching runs once per topic, not
        # once per publish; any (un)subscribe invalidates the whole cache
        self._route_cache: Dict[str, tuple] = {}

    def subscribe(self, pattern: str, handler: Handler) -> None:
        self._subs.append((pattern, handler))
        self._route_cache.clear()

    def unsubscribe(self, handler: Handler) -> None:
        self._subs = [(p, h) for p, h in self._subs if h is not handler]
        self._route_cache.clear()

    async def publish(self, topic: str, payload: bytes) -> int:
        self.published += 1
        handlers = self._route_cache.get(topic)
        if handlers is None:
            if len(self._route_cache) > 65536:  # adversarial topic churn
                self._route_cache.clear()
            handlers = self._route_cache[topic] = tuple(
                h for p, h in self._subs if _topic_matches(p, topic)
            )
        n = 0
        for handler in handlers:
            await handler(topic, payload)
            n += 1
        self.delivered += n
        return n
