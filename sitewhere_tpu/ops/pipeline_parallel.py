"""Pipeline parallelism: GPipe microbatch scheduling over a mesh axis.

Completes the framework's parallelism alphabet (data = mesh ``data``
axis, tensor = ``model`` axis via models.common TP, sequence = ring
attention, tenant = stacked slots): deep models whose LAYERS outgrow one
chip partition blocks into stages, one stage per device along a
``stage`` axis, and microbatches stream through with activations handed
to the next stage by ``lax.ppermute`` (ICI neighbor exchange).

Schedule: classic GPipe — m microbatches, n stages, m+n-1 ticks; every
device computes every tick (branchless; inactive ticks process garbage
whose results are masked), so the bubble fraction is (n-1)/(m+n-1).
The tick loop unrolls in Python (axis size and microbatch count are
static) — XLA overlaps each tick's compute with the next ppermute.
"""

from __future__ import annotations

from typing import Callable

import jax

from sitewhere_tpu.compat import shard_map
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def pipeline_apply_local(
    stage_params,          # this device's stage params (leading dim sliced)
    x: jnp.ndarray,        # [m, B, ...] microbatched input, replicated
    stage_fn: Callable,    # (stage_params, activation [B, ...]) -> [B, ...]
    axis_name: str,
):
    """Per-device GPipe body (run under shard_map over ``axis_name``)."""
    n = lax.psum(1, axis_name)
    s = lax.axis_index(axis_name)
    m = x.shape[0]
    perm = [(j, (j + 1) % n) for j in range(n)]

    current = jnp.zeros_like(x[0])
    out = jnp.zeros_like(x)
    for t in range(m + n - 1):
        mb = t - s  # which microbatch this device works on at tick t
        # stage 0 ingests microbatch t; later stages use the handed-over
        # activation. Branchless: inactive devices compute on whatever is
        # in the buffer and the result is masked below.
        feed = x[min(t, m - 1)]
        current = jnp.where(s == 0, feed, current)
        y = stage_fn(stage_params, current)
        active = (mb >= 0) & (mb < m)
        # last stage banks its finished microbatch
        done_idx = t - (n - 1)
        if 0 <= done_idx < m:
            bank = (s == n - 1) & active
            out = out.at[done_idx].set(jnp.where(bank, y, out[done_idx]))
        if t < m + n - 2:
            current = lax.ppermute(y, axis_name, perm)
    # only the last stage banked non-zero microbatches; a psum broadcasts
    # them to every device (replicated output, sign-safe unlike pmax)
    return lax.psum(out, axis_name)


def pipeline_apply(
    stage_params_stacked,  # pytree, leading dim = n stages
    x: jnp.ndarray,        # [B, ...] full batch, replicated
    stage_fn: Callable,
    mesh,
    axis_name: str = "stage",
    microbatches: int = 4,
):
    """Run ``x`` through n pipelined stages. ``stage_params_stacked``'s
    leading dim shards one stage per device; activations stream between
    stages; output is the full batch, replicated."""
    n = mesh.shape[axis_name]
    n_stages = jax.tree_util.tree_leaves(stage_params_stacked)[0].shape[0]
    if n_stages != n:
        # a mismatch would SILENTLY drop stages (shard_map blocks the
        # leading dim and the body keeps index 0 of each block)
        raise ValueError(
            f"{n_stages} stacked stages but {n} devices on '{axis_name}'"
        )
    b = x.shape[0]
    if b % microbatches:
        raise ValueError(f"batch {b} must divide into {microbatches} microbatches")
    xm = x.reshape(microbatches, b // microbatches, *x.shape[1:])

    def body(params_local, xm_in):
        params = jax.tree_util.tree_map(lambda a: a[0], params_local)
        return pipeline_apply_local(params, xm_in, stage_fn, axis_name)

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
    )
    out = fn(stage_params_stacked, xm)
    return out.reshape(b, *out.shape[2:])
