"""TPU compute ops for the hot scoring path.

- ``windows``   on-device per-stream ring-buffer window state: the scatter/
  gather core that turns an unordered measurement micro-batch into ordered
  per-series windows for model input.
- ``attention`` fused attention used by the transformer/ViT models.
"""

from sitewhere_tpu.ops.windows import (
    WindowState,
    init_window_state,
    update_windows,
    gather_windows,
    update_and_gather,
)

__all__ = [
    "WindowState",
    "init_window_state",
    "update_windows",
    "gather_windows",
    "update_and_gather",
]
