"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context telemetry is first-class (SURVEY.md §5 long-context): when
a forecasting context exceeds one chip's HBM, the SEQUENCE axis shards
across the mesh and attention runs as a ring — each device holds one
query block resident, while K/V blocks rotate around the ring via
``lax.ppermute`` (ICI neighbor exchange, the cheapest collective
pattern), combining partial attention with running log-sum-exp
rescaling. Exact (not approximate) attention; communication overlaps
block compute; peak memory per device is O(T/n) instead of O(T).

The reference has no analog (no ML); this implements the technique from
Liu et al., "Ring Attention with Blockwise Transformers" (public
method), TPU-idiomatically: static shapes, `lax.fori_loop`, collectives
over a named mesh axis.
"""

from __future__ import annotations

import math
from functools import partial

import jax

from sitewhere_tpu.compat import shard_map
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _block_attn(q, k, v, mask):
    """One (Q-block × K-block) partial attention.

    q [B, Tq, H, D], k/v [B, Tk, H, D], mask [Tq, Tk] (True = attend) →
    (scores-max m [B, H, Tq], partial denom l, partial numerator acc).
    """
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(d)
    s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                      # [B, H, Tq]
    p = jnp.exp(s - m[..., None])
    p = jnp.where(mask[None, None], p, 0.0)      # fully-masked rows: p=0
    l = jnp.sum(p, axis=-1)                      # [B, H, Tq]
    acc = jnp.einsum("bhqk,bkhd->bhqd", p, v)    # [B, H, Tq, D]
    return m, l, acc


def ring_attention_local(q, k, v, axis_name: str, causal: bool = True):
    """The per-device body (call under ``shard_map`` with the sequence
    dim sharded over ``axis_name``). q/k/v: [B, T_local, H, D] local
    blocks; returns [B, T_local, H, D] — exact attention over the FULL
    sequence."""
    n = lax.psum(1, axis_name)                   # static: the axis size
    my = lax.axis_index(axis_name)
    b, tl, h, d = q.shape
    q_pos = my * tl + jnp.arange(tl)             # global query positions

    m = jnp.full((b, h, tl), NEG_INF, q.dtype)
    l = jnp.zeros((b, h, tl), q.dtype)
    acc = jnp.zeros((b, h, tl, d), q.dtype)
    perm = [(j, (j + 1) % n) for j in range(n)]

    # the axis size is static, so the ring unrolls as a Python loop — the
    # ppermute for the NEXT block overlaps this block's compute under
    # XLA's async collectives, and the final (discarded) rotation is
    # simply not emitted
    k_cur, v_cur = k, v
    for step in range(n):
        # the block arriving at step s originated s hops "behind" us
        src = (my - step) % n
        k_pos = src * tl + jnp.arange(tl)
        mask = (
            q_pos[:, None] >= k_pos[None, :]
            if causal
            else jnp.ones((tl, tl), bool)
        )
        bm, bl, bacc = _block_attn(q, k_cur, v_cur, mask)
        # running log-sum-exp combine
        m_new = jnp.maximum(m, bm)
        r_old = jnp.exp(m - m_new)
        r_blk = jnp.exp(bm - m_new)
        l = l * r_old + bl * r_blk
        acc = acc * r_old[..., None] + bacc * r_blk[..., None]
        m = m_new
        if step < n - 1:  # rotate K/V to the next device (ICI neighbors)
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)

    # causal first rows always attend to themselves → l > 0; guard anyway
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3))     # [B, Tl, H, D]


def ring_attention(q, k, v, mesh, axis_name: str, causal: bool = True):
    """Convenience wrapper: shard q/k/v's sequence dim over
    ``axis_name`` of ``mesh`` and run the ring. q/k/v: [B, T, H, D]
    global arrays (T divisible by the axis size)."""
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        partial(ring_attention_local, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


def full_attention_reference(q, k, v, causal: bool = True):
    """Single-device exact attention — the numerics oracle for tests."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(d)
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bhqd", p, v)
    return jnp.transpose(out, (0, 2, 1, 3))
