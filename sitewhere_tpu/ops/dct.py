"""On-device JPEG reconstruction: zigzag coefficients → RGB frames.

The device half of the compressed media wire (the host half is
``native/jpegwire.py``'s Huffman + dequant stage). What crosses
host→device is truncated int16 DCT coefficient planes — ~5-20× smaller
than raw RGB for typical camera content — and this module turns them
back into frames INSIDE the classifier's jit (``models.vit.apply_dct``),
so dezigzag, IDCT, chroma upsample, color conversion, normalization and
ViT patchify all fuse into one XLA program per (batch, layout) shape.

TPU notes (why it looks the way it does):

- **Everything is an einsum.** Dezigzag is a ``[k, 64]`` one-hot matmul,
  the 8×8 IDCT is two matmuls against the orthonormal DCT basis
  (``M^T C M``) — MXU work, not gather soup. The whole decode costs
  ≤ 12 MFLOPs per 224² frame vs the ViT-B/16's ~35 GFLOPs (< 0.04%), so
  the chip does it for free while the wire wins 5-20×.
- **Static shapes.** ``FrameLayout`` (grid dims, subsampling, truncation
  width ``k``) is hashable and rides the jit cache key; the media
  pipeline buckets the per-batch spectral extent into ``COEF_BUCKETS``
  so a handful of programs cover all traffic.
- **Zero collectives, zero per-frame Python.** Batch rides array axes
  end to end; tools/check_fusion.py traces this module and asserts the
  dot count is batch-invariant and collective-free.
- **Truncation is lossless.** jpegwire reports the max nonzero zigzag
  extent per frame; coefficients past it are exactly zero, so slicing
  the wire at the bucketed extent reproduces the full-precision decode
  bit for bit.

Parity: IDCT in f32 + the libjpeg-style triangle ("fancy") chroma
upsample lands within ~1-2/255 of PIL's fixed-point decode (property-
tested in tests/test_media_wire.py).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

# zigzag position -> natural (row-major) position inside an 8x8 block
ZIGZAG = np.array([
    0,  1,  8, 16,  9,  2,  3, 10, 17, 24, 32, 25, 18, 11,  4,  5,
    12, 19, 26, 33, 40, 48, 41, 34, 27, 20, 13,  6,  7, 14, 21, 28,
    35, 42, 49, 56, 57, 50, 43, 36, 29, 22, 15, 23, 30, 37, 44, 51,
    58, 59, 52, 45, 38, 31, 39, 46, 53, 60, 61, 54, 47, 55, 62, 63,
], np.int32)

# static truncation-width ladder: the media pipeline buckets each
# batch's max zigzag extent up to one of these, so XLA compiles at most
# len(COEF_BUCKETS) decode variants per batch shape (smooth camera
# content at q75 typically lands 8-32; 64 = full precision, worst case)
COEF_BUCKETS = (8, 16, 32, 64)


def coef_bucket(k: int) -> int:
    """Smallest ladder width holding a zigzag extent of ``k``."""
    for b in COEF_BUCKETS:
        if k <= b:
            return b
    return 64


class FrameLayout(NamedTuple):
    """Static geometry of one coefficient batch (jit cache key).

    width/height: true pixel dims (crop target); y_gw/y_gh and
    c_gw/c_gh: padded MCU-aligned block grids the coefficients cover;
    sub: 1 = 4:4:4, 2 = 4:2:0; k: zigzag truncation width on the wire.
    """

    width: int
    height: int
    y_gw: int
    y_gh: int
    c_gw: int
    c_gh: int
    sub: int
    k: int

    @property
    def y_blocks(self) -> int:
        return self.y_gw * self.y_gh

    @property
    def c_blocks(self) -> int:
        return self.c_gw * self.c_gh

    def wire_bytes(self, batch: int = 1) -> int:
        """int16 payload bytes one batch ships h2d at this layout."""
        return 2 * self.k * batch * (self.y_blocks + 2 * self.c_blocks)


def layout_for(width: int, height: int, sub: int, k: int) -> FrameLayout:
    """The layout a ``width × height`` frame decodes to at subsampling
    ``sub`` (padded MCU-aligned grids — what jpegwire reports for a
    conformant stream of those dims)."""
    mcu = 8 * sub
    mw = (width + mcu - 1) // mcu
    mh = (height + mcu - 1) // mcu
    return FrameLayout(
        width=width, height=height,
        y_gw=mw * sub, y_gh=mh * sub, c_gw=mw, c_gh=mh,
        sub=sub, k=k,
    )


def idct_basis() -> np.ndarray:
    """Orthonormal 8-point DCT-II basis ``M`` (forward: C = M X M^T,
    inverse: X = M^T C M)."""
    m = np.zeros((8, 8), np.float64)
    for u in range(8):
        a = np.sqrt(1.0 / 8.0) if u == 0 else np.sqrt(2.0 / 8.0)
        for x in range(8):
            m[u, x] = a * np.cos((2 * x + 1) * u * np.pi / 16.0)
    return m.astype(np.float32)


def dezigzag_matrix(k: int) -> np.ndarray:
    """``[k, 64]`` one-hot scatter: zigzag-truncated wire → natural
    order, as a matmul (MXU-friendly; k is static per jit variant)."""
    s = np.zeros((k, 64), np.float32)
    s[np.arange(k), ZIGZAG[:k]] = 1.0
    return s


def idct_plane(coef_z: jnp.ndarray, gh: int, gw: int, k: int) -> jnp.ndarray:
    """Zigzag coefficient blocks ``i16/f32[B, gh*gw, k]`` → pixel plane
    ``f32[B, gh*8, gw*8]`` (level-shifted to 0..255)."""
    b = coef_z.shape[0]
    x = coef_z.astype(jnp.float32)
    # dezigzag as one matmul, then the separable 2-D IDCT as two more
    nat = jnp.einsum("bnk,ko->bno", x, dezigzag_matrix(k))
    blocks = nat.reshape(b, gh, gw, 8, 8)
    m = jnp.asarray(idct_basis())
    px = jnp.einsum("ux,bgwuv,vy->bgwxy", m, blocks, m) + 128.0
    # block grid -> plane
    return px.transpose(0, 1, 3, 2, 4).reshape(b, gh * 8, gw * 8)


def _upsample2x_1d(x: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Double ``axis`` with the libjpeg "fancy" triangle filter: each
    output pair is (3·cur+prev)/4, (3·cur+next)/4 with edge replication."""
    lo = jnp.concatenate(
        [jnp.take(x, jnp.array([0]), axis=axis),
         jnp.take(x, jnp.arange(x.shape[axis] - 1), axis=axis)], axis=axis)
    hi = jnp.concatenate(
        [jnp.take(x, jnp.arange(1, x.shape[axis]), axis=axis),
         jnp.take(x, jnp.array([x.shape[axis] - 1]), axis=axis)], axis=axis)
    a = 0.75 * x + 0.25 * lo
    c = 0.75 * x + 0.25 * hi
    stacked = jnp.stack([a, c], axis=axis + 1)
    shape = list(x.shape)
    shape[axis] *= 2
    return stacked.reshape(shape)


def upsample2x(plane: jnp.ndarray) -> jnp.ndarray:
    """``f32[B, H, W]`` → ``f32[B, 2H, 2W]`` triangle upsample (the
    h2v2 "fancy" kernel libjpeg decodes 4:2:0 chroma with)."""
    return _upsample2x_1d(_upsample2x_1d(plane, 1), 2)


def ycbcr_to_rgb(y: jnp.ndarray, cb: jnp.ndarray, cr: jnp.ndarray) -> jnp.ndarray:
    """JFIF BT.601 full-range conversion; output ``f32[B, H, W, 3]``
    clamped to 0..255."""
    cb = cb - 128.0
    cr = cr - 128.0
    r = y + 1.402 * cr
    g = y - 0.344136 * cb - 0.714136 * cr
    b = y + 1.772 * cb
    return jnp.clip(jnp.stack([r, g, b], axis=-1), 0.0, 255.0)


def decode_frames(
    y_z: jnp.ndarray,
    cb_z: jnp.ndarray,
    cr_z: jnp.ndarray,
    layout: FrameLayout,
) -> jnp.ndarray:
    """Truncated zigzag coefficient batch → RGB frames
    ``f32[B, height, width, 3]`` in 0..255.

    ``y_z``: ``[B, y_blocks, k]``; ``cb_z``/``cr_z``: ``[B, c_blocks,
    k]`` (int16 as shipped over the wire). Pure jnp — call it inside the
    classifier jit so XLA fuses decode into preprocessing."""
    yp = idct_plane(y_z, layout.y_gh, layout.y_gw, layout.k)
    cbp = idct_plane(cb_z, layout.c_gh, layout.c_gw, layout.k)
    crp = idct_plane(cr_z, layout.c_gh, layout.c_gw, layout.k)
    if layout.sub == 2:
        cbp = upsample2x(cbp)
        crp = upsample2x(crp)
    h, w = layout.height, layout.width
    rgb = ycbcr_to_rgb(yp[:, :h, :w], cbp[:, :h, :w], crp[:, :h, :w])
    return rgb


def decode_flops_per_frame(layout: FrameLayout) -> float:
    """Analytic matmul FLOPs (2/MAC) one frame costs through the decode
    kernel — dezigzag + the two IDCT matmuls per block. Reported for
    attribution only: decode FLOPs stay OUT of the ViT model's MFU
    numerator (docs/PERFORMANCE.md "Media wire & on-chip decode")."""
    n_blocks = layout.y_blocks + 2 * layout.c_blocks
    dezig = 2.0 * layout.k * 64
    idct = 2.0 * 2 * 8 * 8 * 8  # two [8,8]x[8,8] matmuls
    return n_blocks * (dezig + idct)
