"""On-device per-stream ring-buffer windows — the scatter/gather core.

The reference has no analog (its CEP sliding windows live in Siddhi on the
JVM — SURVEY.md §5 "long-context" [U]; reference mount empty, see provenance
banner). This module is the TPU-native replacement: every (device,
measurement-name) series gets a fixed-length ring buffer that lives in device
memory, so the steady-state hot loop never ships history back and forth —
only the new micro-batch crosses host→device each step.

Design constraints (why it looks the way it does):

- **Static shapes.** State is ``[S, W]`` for a fixed stream capacity ``S``
  and window ``W``; micro-batches are padded to bucketed sizes. XLA compiles
  each bucket once.
- **Duplicate streams per batch.** One micro-batch routinely carries several
  samples of the same series. A plain scatter would be order-ambiguous, so
  we compute each row's *rank among same-stream rows* (sort + segment rank,
  all O(B log B) inside jit) and write to ``(pos[s] + rank) % W``.
- **Branchless padding.** Invalid rows get an out-of-range scatter index and
  are dropped by XLA's scatter ``mode='drop'`` — no ``cond`` in the hot loop.
- **Functional state.** ``WindowState`` is a pytree; update returns a new
  state (donate the old one under jit for in-place HBM reuse).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class WindowState(NamedTuple):
    """Per-stream ring buffers. All leaves live on device.

    values: f32[S, W]   ring storage (raw measurement values)
    pos:    i32[S]      next write slot per stream
    count:  i32[S]      total samples ever written per stream (saturating add
                        not needed: int32 @ 1M ev/s/stream ≈ 35 min to wrap is
                        fine because only ``min(count, W)`` is ever used)
    """

    values: jnp.ndarray
    pos: jnp.ndarray
    count: jnp.ndarray

    @property
    def capacity(self) -> int:
        return self.values.shape[0]

    @property
    def window(self) -> int:
        return self.values.shape[1]


def init_window_state(
    max_streams: int, window: int, dtype=jnp.float32
) -> WindowState:
    return WindowState(
        values=jnp.zeros((max_streams, window), dtype),
        pos=jnp.zeros((max_streams,), jnp.int32),
        count=jnp.zeros((max_streams,), jnp.int32),
    )


def _segment_ranks(stream_ids: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Rank of each row among rows sharing its stream id, plus per-row
    total count of rows with that id. Works on padded ids too.

    Returns (ranks i32[B], totals i32[B]) in the *original* row order.
    """
    b = stream_ids.shape[0]
    order = jnp.argsort(stream_ids, stable=True)
    sorted_ids = stream_ids[order]
    idx = jnp.arange(b, dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_ids[1:] != sorted_ids[:-1]]
    )
    # index of the start of each run, broadcast along the run via cummax
    start_idx = jax.lax.cummax(jnp.where(is_start, idx, -1))
    ranks_sorted = idx - start_idx
    # per-run totals: rank of the last row of the run + 1, broadcast backwards
    is_end = jnp.concatenate(
        [sorted_ids[1:] != sorted_ids[:-1], jnp.ones((1,), bool)]
    )
    last_rank = jax.lax.cummax(
        jnp.where(is_end, ranks_sorted, -1)[::-1]
    )[::-1]
    totals_sorted = last_rank + 1
    inv = jnp.argsort(order, stable=True)
    return ranks_sorted[inv].astype(jnp.int32), totals_sorted[inv].astype(jnp.int32)


def _apply_update(
    state: WindowState,
    stream_ids: jnp.ndarray,
    values: jnp.ndarray,
    valid: jnp.ndarray,
    ranks: jnp.ndarray,
    totals: jnp.ndarray,
) -> WindowState:
    """Scatter a ranked micro-batch into the rings (the body of
    ``update_windows``, split out so the K-step fused path can reuse one
    ``_segment_ranks`` sort for both the scatter and the per-row
    timestep resolution)."""
    s, w = state.values.shape
    write_slot = (state.pos[stream_ids] + ranks) % w
    flat_idx = stream_ids * w + write_slot
    # invalid rows → out-of-range index → dropped by scatter mode='drop'.
    # Bursts of > W same-stream rows in one batch: only the newest W rows
    # write (older ones would be overwritten in sequential order anyway;
    # without this, duplicate scatter indices pick an unspecified winner).
    newest_w = ranks >= (totals - w)
    flat_idx = jnp.where(valid & newest_w, flat_idx, s * w)
    new_values = (
        state.values.reshape(-1)
        .at[flat_idx]
        .set(values.astype(state.values.dtype), mode="drop")
        .reshape(s, w)
    )
    ones = jnp.where(valid, 1, 0).astype(jnp.int32)
    safe_ids = jnp.where(valid, stream_ids, s)  # drop row for invalid
    per_stream = jnp.zeros((s,), jnp.int32).at[safe_ids].add(ones, mode="drop")
    return WindowState(
        values=new_values,
        pos=(state.pos + per_stream) % w,
        count=state.count + per_stream,
    )


def update_windows(
    state: WindowState,
    stream_ids: jnp.ndarray,  # i32[B]
    values: jnp.ndarray,      # f32[B]
    valid: jnp.ndarray,       # bool[B]
) -> WindowState:
    """Append a micro-batch into the ring buffers (order-preserving within
    a stream). Pure, jit-friendly, static-shaped."""
    ranks, totals = _segment_ranks(jnp.where(valid, stream_ids, -1))
    return _apply_update(state, stream_ids, values, valid, ranks, totals)


def gather_windows(
    state: WindowState,
    stream_ids: jnp.ndarray,  # i32[B]
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Materialize time-ordered windows for each requested stream.

    Returns (windows f32[B, W] oldest→newest, n_valid i32[B] clamped to W).
    Streams with fewer than W samples are left-padded with their oldest
    value (constant padding keeps models shift-robust without NaNs).
    """
    s, w = state.values.shape
    raw = state.values[stream_ids]            # [B, W] ring order
    pos = state.pos[stream_ids]               # [B]
    # roll each row so oldest..newest; slot (pos) is the oldest entry
    col = jnp.arange(w, dtype=jnp.int32)[None, :]
    src = (pos[:, None] + col) % w
    ordered = jnp.take_along_axis(raw, src, axis=1)
    n = jnp.minimum(state.count[stream_ids], w)  # [B]
    # left-pad short windows with their first valid sample
    first_valid_col = w - n
    first_val = jnp.take_along_axis(
        ordered, jnp.minimum(first_valid_col, w - 1)[:, None], axis=1
    )
    windows = jnp.where(col < first_valid_col[:, None], first_val, ordered)
    return windows, n


def update_and_gather(
    state: WindowState,
    stream_ids: jnp.ndarray,
    values: jnp.ndarray,
    valid: jnp.ndarray,
) -> Tuple[WindowState, jnp.ndarray, jnp.ndarray]:
    """Fused hot-path step: append batch, then gather each row's window
    *including* the row itself as the newest element."""
    new_state = update_windows(state, stream_ids, values, valid)
    windows, n = gather_windows(new_state, stream_ids)
    return new_state, windows, n


def update_gather_ranked(
    state: WindowState,
    stream_ids: jnp.ndarray,
    values: jnp.ndarray,
    valid: jnp.ndarray,
) -> Tuple[WindowState, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """``update_and_gather`` plus per-row recency: also returns ``later``
    i32[B] — how many valid same-stream rows come AFTER row b in this
    batch (0 = the stream's newest sample). A K-step fused scorer uses
    it to resolve each row at its OWN window position: a row with
    ``later = j`` sits at position W-1-j of the post-batch window, so it
    takes the K-step score at index K-1-j instead of the newest one.
    One ``_segment_ranks`` sort serves both the ring scatter and this."""
    ranks, totals = _segment_ranks(jnp.where(valid, stream_ids, -1))
    new_state = _apply_update(state, stream_ids, values, valid, ranks, totals)
    windows, n = gather_windows(new_state, stream_ids)
    later = jnp.where(valid, totals - 1 - ranks, 0).astype(jnp.int32)
    return new_state, windows, n, later
