"""Minimal admin console — the L7 layer (reference: sitewhere-admin-ui,
a SEPARATE Vue repo upstream — SURVEY.md:71 [U]; reference mount empty,
see provenance banner).

One static, dependency-free HTML page served at ``/admin`` over the
existing REST + WebSocket surface: JWT login, tenant switcher, instance
topology, device/assignment tables, the live persisted-event feed, and a
north-star metrics strip scraped from /metrics. Everything is plain
fetch()/WebSocket against the documented API — the console holds no
privileged path into the instance.
"""

CONSOLE_HTML = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>SiteWhere-TPU Console</title>
<meta name="viewport" content="width=device-width, initial-scale=1">
<style>
:root{--bg:#111418;--panel:#1a1f26;--line:#2a313b;--fg:#e6e8eb;
--dim:#8b949e;--acc:#4f8cc9;--ok:#4fa56b;--warn:#c9804f}
*{box-sizing:border-box}
body{margin:0;background:var(--bg);color:var(--fg);
font:14px/1.45 system-ui,sans-serif}
header{display:flex;align-items:center;gap:12px;padding:10px 16px;
border-bottom:1px solid var(--line)}
header h1{font-size:15px;margin:0;font-weight:600}
header .dim{color:var(--dim)}
main{display:grid;grid-template-columns:1fr 1fr;gap:12px;padding:12px}
section{background:var(--panel);border:1px solid var(--line);
border-radius:8px;padding:12px;min-height:120px}
section h2{margin:0 0 8px;font-size:13px;color:var(--dim);
text-transform:uppercase;letter-spacing:.06em}
table{width:100%;border-collapse:collapse;font-size:13px}
th{color:var(--dim);text-align:left;font-weight:500}
th,td{padding:3px 8px 3px 0;border-bottom:1px solid var(--line)}
#feed{font-family:ui-monospace,monospace;font-size:12px;max-height:320px;
overflow-y:auto;white-space:pre}
#feed .alert{color:var(--warn)}
#login{max-width:320px;margin:80px auto;display:flex;flex-direction:column;
gap:8px}
input,select,button{background:var(--bg);color:var(--fg);
border:1px solid var(--line);border-radius:5px;padding:6px 9px;font:inherit}
button{cursor:pointer;border-color:var(--acc)}
.stat{display:inline-block;margin-right:18px}
.stat b{display:block;font-size:18px}
.stat span{color:var(--dim);font-size:12px}
#err{color:var(--warn)}
.full{grid-column:1/-1}
</style>
</head>
<body>
<div id="login">
  <h1>SiteWhere-TPU</h1>
  <input id="user" placeholder="username" value="admin">
  <input id="pass" type="password" placeholder="password">
  <button onclick="login()">Sign in</button>
  <div id="err"></div>
</div>
<div id="app" style="display:none">
<header>
  <h1>SiteWhere-TPU</h1>
  <span class="dim">tenant</span>
  <select id="tenant" onchange="switchTenant()"></select>
  <span class="dim" id="whoami"></span>
</header>
<main>
  <section class="full"><h2>North star</h2><div id="stats"></div></section>
  <section><h2>Topology</h2><div id="topo"></div></section>
  <section><h2>Devices</h2><div id="devices"></div></section>
  <section class="full"><h2>Live events</h2><div id="feed"></div></section>
</main>
</div>
<script>
let jwt = "", tenant = "default", ws = null;
const $ = id => document.getElementById(id);
const esc = v => String(v ?? "").replace(/[&<>"']/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
const api = (path, opts={}) => fetch(path, {...opts, headers: {
  "Authorization": "Bearer " + jwt, "X-SiteWhere-Tenant": tenant,
  "Content-Type": "application/json", ...(opts.headers||{})}})
  .then(r => { if (!r.ok) throw new Error(path+": "+r.status); return r; });

async function login() {
  try {
    const r = await fetch("/api/authapi/jwt", {method: "POST",
      body: JSON.stringify({username: $("user").value,
                            password: $("pass").value})});
    if (!r.ok) throw new Error("bad credentials");
    jwt = (await r.json()).token;
    $("login").style.display = "none";
    $("app").style.display = "";
    $("whoami").textContent = $("user").value;
    await loadTenants();
    openFeed();
    refresh();
    setInterval(refresh, 5000);
  } catch (e) { $("err").textContent = e.message; }
}

async function loadTenants() {
  const body = await (await api("/api/tenants")).json();
  const ts = body.results || body;
  $("tenant").innerHTML = ts.map(t =>
    `<option value="${esc(t.token)}">${esc(t.token)}</option>`).join("");
  if (ts.length) tenant = ts[0].token;
  $("tenant").value = tenant;
}

function switchTenant() {
  tenant = $("tenant").value;
  if (ws) ws.close();
  openFeed();
  refresh();
}

async function refresh() {
  try {
    const topo = await (await api("/api/instance/topology")).json();
    const t = topo.tenants[tenant] || {};
    $("topo").innerHTML =
      "<table><tr><th>component</th><th>state</th></tr>" +
      Object.entries(t.components || {}).map(([k, v]) =>
        `<tr><td>${esc(k)}</td><td style="color:${
          v === "started" ? "var(--ok)" : "var(--warn)"}">${esc(v)}</td></tr>`
      ).join("") + "</table>";
    const devs = await (await api("/api/devices?page_size=12")).json();
    $("devices").innerHTML =
      `<div class="dim">${devs.total} devices</div>` +
      "<table><tr><th>token</th><th>type</th><th>status</th></tr>" +
      devs.results.map(d =>
        `<tr><td>${esc(d.token)}</td><td>${esc(d.device_type_token)}</td>` +
        `<td>${esc(d.status)}</td></tr>`).join("") + "</table>";
    const m = await (await fetch("/metrics")).text();
    const pick = name => {
      const row = m.split("\\n").find(l => l.startsWith(name + " "));
      return row ? Number(row.split(" ")[1]) : 0;
    };
    const stats = [
      ["scored", pick("tpu_inference_scored_total")],
      ["persisted", pick("event_management_persisted")],
      ["rules fired", pick("rules_fired")],
      ["commands", pick("command_delivery_delivered")],
      ["failovers", pick("tpu_inference_failovers")],
    ];
    $("stats").innerHTML = stats.map(([k, v]) =>
      `<span class="stat"><b>${v.toLocaleString()}</b>` +
      `<span>${esc(k)}</span></span>`).join("");
  } catch (e) { console.error(e); }
}

function openFeed() {
  const proto = location.protocol === "https:" ? "wss" : "ws";
  ws = new WebSocket(`${proto}://${location.host}/api/ws/events` +
    `?access_token=${encodeURIComponent(jwt)}` +
    `&tenant=${encodeURIComponent(tenant)}`);
  ws.onmessage = ev => {
    const e = JSON.parse(ev.data);
    const line = document.createElement("div");
    if (e.type === "alert") line.className = "alert";
    line.textContent = `${new Date(e.event_ts).toISOString()}  ` +
      `${(e.type || "?").padEnd(12)} ${(e.device_token || "").padEnd(12)}` +
      ` ${e.name || e.alert_type || ""} ${e.value ?? e.message ?? ""}` +
      (e.score != null ? `  score=${Number(e.score).toFixed(3)}` : "");
    const feed = $("feed");
    feed.prepend(line);
    while (feed.childNodes.length > 200) feed.removeChild(feed.lastChild);
  };
}
</script>
</body>
</html>
"""
