"""REST API gateway over the instance (aiohttp).

Capability parity with the reference's service-web-rest (SURVEY.md §2.2
[U]: one Spring MVC controller per resource area — devices, device types,
assignments, events, areas, zones, assets, users, tenants, schedules,
batch, labels — behind a JWT auth filter, plus Swagger docs; reference
mount empty, see provenance banner).

Redesign: aiohttp handlers calling the in-proc services directly (the
reference pays a gRPC hop per request here). Auth: ``Authorization:
Bearer <jwt>`` validated by ``services.user_management``; ``/api/openapi.json``
serves a generated OpenAPI sketch (the Swagger-docs analog);
``/metrics`` is the Prometheus scrape endpoint (SURVEY.md §5).

Tenant scoping: ``X-SiteWhere-Tenant`` header (default "default"), matching
the reference's tenant auth headers [U].
"""

from __future__ import annotations

import asyncio
import base64
import json
from typing import Any, Callable, Optional

from aiohttp import web

from sitewhere_tpu.core.events import EventType
from sitewhere_tpu.core.model import (
    Area,
    Asset,
    AssetType,
    Customer,
    Device,
    DeviceAssignment,
    DeviceCommand,
    DeviceType,
    Zone,
)
from sitewhere_tpu.instance import SiteWhereInstance, TenantRuntime
from sitewhere_tpu.services.batch_operations import BatchOpStatus
from sitewhere_tpu.services.event_store import EventQuery
from sitewhere_tpu.services.schedule_management import Schedule
from sitewhere_tpu.services.user_management import (
    AUTH_ADMIN,
    AUTH_DEVICE_MANAGE,
    AUTH_TENANT_ADMIN,
    AuthError,
)
from sitewhere_tpu.core.events import DeviceCommandInvocation

JSON = "application/json"


def _entity(e) -> dict:
    return e.to_dict() if hasattr(e, "to_dict") else dict(e)


def _paged(items, total, page, page_size) -> dict:
    return {
        "results": [_entity(i) for i in items],
        "total": total,
        "page": page,
        "page_size": page_size,
    }


class RestApi:
    """aiohttp application exposing the platform."""

    def __init__(self, instance: SiteWhereInstance) -> None:
        self.instance = instance
        self.app = web.Application(middlewares=[self._auth_middleware])
        self._routes()

    # -- auth ------------------------------------------------------------
    PUBLIC = {("POST", "/api/authapi/jwt"), ("GET", "/api/health"),
              ("GET", "/metrics"), ("GET", "/api/openapi.json"),
              # static console shell: holds no data — every data call it
              # makes authenticates through the normal JWT middleware
              ("GET", "/admin"),
              # device-facing ingest authenticates with the TENANT auth
              # token (devices don't hold user JWTs) — see http_ingest
              ("POST", "/api/input"), ("GET", "/api/ws/input")}

    @web.middleware
    async def _auth_middleware(self, request: web.Request, handler):
        key = (request.method, request.path)
        if key in self.PUBLIC:
            return await handler(request)
        auth = request.headers.get("Authorization", "")
        if not auth.startswith("Bearer ") and request.path.startswith("/api/ws/"):
            # browsers cannot set headers on WebSocket upgrades — the
            # admin console's live feed passes the SAME jwt as a query
            # param instead (validated identically below). Scoped to the
            # WS routes ONLY: tokens in ordinary request URLs would leak
            # into access logs / history / Referer headers
            qt = request.query.get("access_token", "")
            if qt:
                auth = f"Bearer {qt}"
        if not auth.startswith("Bearer "):
            return web.json_response({"error": "missing bearer token"}, status=401)
        try:
            claims = self.instance.users.validate_token(auth[7:])
        except AuthError as exc:
            return web.json_response({"error": str(exc)}, status=401)
        request["claims"] = claims
        try:
            return await handler(request)
        except AuthError as exc:
            return web.json_response({"error": str(exc)}, status=403)
        except (KeyError, ValueError) as exc:
            return web.json_response({"error": str(exc)}, status=400)

    def _tenant(self, request: web.Request) -> TenantRuntime:
        token = request.headers.get(
            "X-SiteWhere-Tenant", request.query.get("tenant", "default")
        )
        rt = self.instance.tenants.get(token)
        if rt is None:
            raise web.HTTPNotFound(
                text=json.dumps({"error": f"tenant '{token}' not found"}),
                content_type=JSON,
            )
        return rt

    @staticmethod
    def _page(request: web.Request) -> tuple:
        return (
            int(request.query.get("page", 1)),
            int(request.query.get("page_size", 100)),
        )

    # -- routes ----------------------------------------------------------
    def _routes(self) -> None:
        r = self.app.router
        r.add_post("/api/authapi/jwt", self.login)
        r.add_post("/api/input", self.http_ingest)
        r.add_get("/api/ws/input", self.ws_ingest)
        r.add_get("/api/ws/events", self.ws_events)
        r.add_get("/api/health", self.health)
        r.add_get("/admin", self.admin_console)
        r.add_get("/metrics", self.metrics)
        r.add_get("/api/openapi.json", self.openapi)
        r.add_get("/api/instance/topology", self.topology)

        r.add_get("/api/devicetypes", self.list_device_types)
        r.add_post("/api/devicetypes", self.create_device_type)
        r.add_get("/api/devicetypes/{token}", self.get_device_type)
        r.add_post("/api/devicetypes/{token}/commands", self.add_command)

        r.add_get("/api/devices", self.list_devices)
        r.add_post("/api/devices", self.create_device)
        r.add_get("/api/devices/{token}", self.get_device)
        r.add_delete("/api/devices/{token}", self.delete_device)
        r.add_get("/api/devices/{token}/state", self.device_state)
        r.add_get("/api/devices/{token}/label", self.device_label)

        r.add_get("/api/assignments", self.list_assignments)
        r.add_post("/api/assignments", self.create_assignment)
        r.add_get("/api/assignments/{token}/measurements", self.assignment_measurements)
        r.add_post("/api/assignments/{token}/invocations", self.invoke_command)
        r.add_delete("/api/assignments/{token}", self.release_assignment)

        r.add_get("/api/events", self.list_events)
        r.add_get("/api/events/search", self.search_events)
        r.add_get("/api/devicegroups", self.list_device_groups)
        r.add_post("/api/devicegroups", self.create_device_group)
        r.add_get("/api/devicegroups/{token}", self.get_device_group)
        r.add_delete("/api/devicegroups/{token}", self.delete_device_group)
        r.add_get("/api/devicegroups/{token}/devices",
                  self.device_group_devices)
        r.add_get("/api/areas", self.list_areas)
        r.add_post("/api/areas", self.create_area)
        r.add_get("/api/zones", self.list_zones)
        r.add_post("/api/zones", self.create_zone)

        r.add_get("/api/assets", self.list_assets)
        r.add_post("/api/assets", self.create_asset)
        r.add_post("/api/assettypes", self.create_asset_type)

        r.add_get("/api/users", self.list_users)
        r.add_post("/api/users", self.create_user)

        r.add_get("/api/tenants", self.list_tenants)
        r.add_post("/api/tenants", self.create_tenant)
        r.add_post("/api/tenants/{token}/restart", self.restart_tenant)
        r.add_delete("/api/tenants/{token}", self.delete_tenant)
        r.add_get("/api/tenants/{token}/deadletter", self.deadletter_list)
        r.add_post(
            "/api/tenants/{token}/deadletter/requeue", self.deadletter_requeue
        )
        r.add_get("/api/tenants/{token}/slo", self.tenant_slo)
        r.add_get("/api/tenants/{token}/overload", self.tenant_overload)
        r.add_get("/api/tenants/{token}/health", self.tenant_health)
        r.add_get("/api/tenants/{token}/scores/dist", self.tenant_scores_dist)
        r.add_post("/api/tenants/{token}/replay", self.replay_start)
        r.add_get("/api/tenants/{token}/replay", self.replay_list)
        r.add_get("/api/tenants/{token}/replay/{job}", self.replay_status)
        r.add_get("/api/tenants/{token}/storage", self.tenant_storage)

        r.add_get("/api/traces", self.list_traces)
        r.add_get("/api/traces/{id}", self.get_trace)
        r.add_get("/api/flightrec", self.flightrec)
        r.add_get("/api/flightrec/snapshots", self.flightrec_snapshots)
        r.add_get("/api/metrics/history", self.metrics_history)
        r.add_get("/api/latency", self.latency_fleet)
        r.add_get("/api/tenants/{token}/latency", self.tenant_latency)

        r.add_get("/api/schedules", self.list_schedules)
        r.add_post("/api/schedules", self.create_schedule)

        r.add_post("/api/batch", self.create_batch)
        r.add_get("/api/batch/{token}", self.get_batch)

        r.add_post("/api/streams", self.create_stream)
        r.add_put("/api/streams/{id}/chunks/{seq}", self.put_chunk)
        r.add_get("/api/streams/{id}/chunks/{seq}", self.get_chunk)

    # -- auth/infra handlers --------------------------------------------
    async def login(self, request: web.Request) -> web.Response:
        body = await request.json()
        try:
            token = self.instance.users.issue_token(
                body.get("username", ""), body.get("password", "")
            )
        except AuthError as exc:
            return web.json_response({"error": str(exc)}, status=401)
        return web.json_response({"token": token})

    async def http_ingest(self, request: web.Request) -> web.Response:
        """HTTP transport termination (reference: HTTP/WebSocket event
        receivers [U]): raw wire payload (the tenant's configured decoder
        format — JSON or binary) enters the tenant's event source exactly
        like an MQTT message. Devices authenticate with the TENANT auth
        token, not a user JWT."""
        rt = self._authenticate_device(request)
        if rt is None:
            return web.json_response({"error": "unauthorized"}, status=401)
        payload = await request.read()
        if not payload:
            return web.json_response({"error": "empty payload"}, status=400)
        await rt.source.receiver.submit(
            payload, topic=f"http/{rt.tenant}/input"
        )
        return web.json_response({"accepted": True}, status=202)

    def _authenticate_device(self, request: web.Request):
        """Header adapter over the ONE device-facing auth check
        (SiteWhereInstance.authenticate_device — shared with CoAP)."""
        return self.instance.authenticate_device(
            request.headers.get("X-SiteWhere-Tenant", "default"),
            request.headers.get("X-SiteWhere-Tenant-Auth", ""),
        )

    async def ws_ingest(self, request: web.Request) -> web.StreamResponse:
        """WebSocket transport termination (reference: WebSocket event
        receivers in service-event-sources [U]): each binary/text frame is
        one wire payload for the tenant's decoder, exactly like an MQTT
        message; the socket stays open for the device's session."""
        rt = self._authenticate_device(request)
        if rt is None:
            return web.json_response({"error": "unauthorized"}, status=401)
        ws = web.WebSocketResponse(heartbeat=30.0)
        await ws.prepare(request)
        tenant = rt.tenant
        frames = self.instance.metrics.counter("ingest.ws_frames")
        async for msg in ws:
            if msg.type == web.WSMsgType.BINARY:
                payload = msg.data
            elif msg.type == web.WSMsgType.TEXT:
                payload = msg.data.encode()
            else:
                continue
            await rt.source.receiver.submit(
                payload, topic=f"ws/{tenant}/input"
            )
            frames.inc()
        return ws

    async def ws_events(self, request: web.Request) -> web.StreamResponse:
        """Live event feed (reference: web-rest WebSocket topics [U]): a
        JWT-authenticated client streams the tenant's persisted events as
        JSON frames. JWT auth rides the standard middleware (the route is
        NOT public). Each connection is its own consumer group starting at
        the topic tail, so feeds don't disturb pipeline cursors and two
        dashboards each see every event."""
        import asyncio
        import uuid

        from sitewhere_tpu.core.batch import MeasurementBatch

        rt = self._tenant(request)
        ws = web.WebSocketResponse(heartbeat=30.0)
        await ws.prepare(request)
        bus = self.instance.bus
        topic = bus.naming.persisted_events(rt.tenant)
        group = f"ws-feed-{uuid.uuid4().hex[:8]}"
        bus.subscribe(topic, group, at="latest")
        sent = self.instance.metrics.counter("ws_feed.events")

        async def drain_client() -> None:
            # aiohttp only processes heartbeat PONGs (and CLOSE frames)
            # inside receive() — without this concurrent reader every
            # healthy connection would be force-closed after ~1.5
            # heartbeats
            async for _msg in ws:
                pass

        drainer = asyncio.create_task(drain_client())
        try:
            while not ws.closed:
                items = await bus.consume(topic, group, 256, timeout_s=1.0)
                for item in items:
                    events = (
                        item.to_events()
                        if isinstance(item, MeasurementBatch)
                        else [item]
                    )
                    for e in events:
                        await ws.send_json(
                            e.to_dict() if hasattr(e, "to_dict") else e
                        )
                        sent.inc()
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            drainer.cancel()
            try:
                await drainer
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            # deregister THROUGH the bus seam (works for the in-proc bus
            # and the TCP broker alike) so a departed feed never
            # backpressures the pipeline
            bus.unsubscribe(topic, group)
        return ws

    async def health(self, request) -> web.Response:
        return web.json_response(
            {"status": "ok", "state": self.instance.state.value}
        )

    async def admin_console(self, request) -> web.Response:
        """The L7 admin console: one static page over REST + WS (see
        api/console.py)."""
        from sitewhere_tpu.api.console import CONSOLE_HTML

        return web.Response(text=CONSOLE_HTML, content_type="text/html")

    async def metrics(self, request) -> web.Response:
        # refresh scrape-time gauges (per-topic depth, consumer lag,
        # receiver queue depth) so labels are current at scrape time
        self.instance.collect_bus_gauges()
        bus = self.instance.bus
        from sitewhere_tpu.runtime.bus import EventBus as _InProcBus

        if not isinstance(bus, _InProcBus) and hasattr(bus, "lags"):
            # remote backend (netbus RemoteEventBus): lags() is a wire
            # round trip, awaited here; a broker outage must not break
            # the scrape — the rest of the metrics still render
            try:
                self.instance.apply_lag_gauges(await bus.lags())
            except Exception as exc:  # noqa: BLE001
                self.instance._record_error("lags-scrape", exc)
        return web.Response(
            text=self.instance.metrics.prometheus_text(),
            content_type="text/plain",
        )

    # -- tracing ---------------------------------------------------------
    async def list_traces(self, request) -> web.Response:
        """Retained traces, newest first (tail-based sampling decides
        retention — docs/OBSERVABILITY.md). ``?tenant=`` filters,
        ``?active=1`` includes in-flight traces, ``?flush=1`` forces every
        in-flight trace through its tail decision now (diagnostics)."""
        tracer = self.instance.tracer
        if request.query.get("flush", "") in ("1", "true"):
            tracer.gc(force=True)
        else:
            tracer.gc()
        limit = min(int(request.query.get("limit", 100)), 1000)
        include_active = request.query.get("active", "") in ("1", "true")
        traces = tracer.store.list(
            tenant=request.query.get("tenant", ""),
            limit=limit,
            include_active=include_active,
        )
        return web.json_response({
            "results": [t.summary() for t in traces],
            "active": tracer.store.active_count(),
            "retained": tracer.store.retained_count(),
        })

    async def get_trace(self, request) -> web.Response:
        """One trace: span list plus a Chrome trace-event export
        (``chrome://tracing`` / Perfetto — load ``.traceEvents``)."""
        from sitewhere_tpu.runtime.tracing import chrome_trace_events

        tracer = self.instance.tracer
        tracer.gc()
        tr = tracer.store.peek(request.match_info["id"])
        if tr is None:
            return web.json_response({"error": "unknown trace"}, status=404)
        d = tr.to_dict()
        d["traceEvents"] = chrome_trace_events(tr)
        return web.json_response(d)

    async def flightrec(self, request) -> web.Response:
        """The flight recorder's live rings (per-flush + per-stage
        blackbox records, oldest→newest) plus snapshot summaries;
        ``?chrome=1`` adds a Chrome trace-event export joining the host
        spans with the device dispatch windows (load ``.traceEvents``
        into Perfetto beside a GET /api/traces/{id} export)."""
        from sitewhere_tpu.runtime.flightrec import chrome_flush_events

        body = self.instance.flightrec.describe()
        if request.query.get("chrome", "") in ("1", "true"):
            body["traceEvents"] = chrome_flush_events(body["rings"])
        return web.json_response(body)

    async def flightrec_snapshots(self, request) -> web.Response:
        """Dump-on-incident snapshots (breaker trip / SLO breach /
        watchdog alert froze the rings). ``?id=N`` returns one snapshot
        in full, with its Chrome trace-event export; without it, a
        summary row (id/reason/meta) per retained snapshot newest-last —
        full rings stay per-id so the listing can't serialize tens of MB
        on the event loop mid-incident."""
        from sitewhere_tpu.runtime.flightrec import chrome_flush_events

        fr = self.instance.flightrec
        snap_id = request.query.get("id", "")
        if snap_id:
            try:
                wanted = int(snap_id)
            except ValueError:
                return web.json_response(
                    {"error": f"bad snapshot id {snap_id!r}"}, status=400
                )
            snap = fr.get_snapshot(wanted)
            if snap is None:
                return web.json_response(
                    {"error": f"unknown snapshot {snap_id}"}, status=404
                )
            body = dict(snap)
            body["traceEvents"] = chrome_flush_events(snap["rings"])
            return web.json_response(body)
        return web.json_response({
            "snapshots": fr.snapshot_summaries(),
            "taken": fr.snapshots_taken,
            "suppressed": fr.snapshots_suppressed,
        })

    async def metrics_history(self, request) -> web.Response:
        """The in-process metrics history ring: ``?name=`` (repeatable)
        filters series, ``?since_s=`` trims to the recent window,
        ``?step=N`` max-pools N-sample buckets server-side (spikes
        survive downsampling). Watchdog alerts ride along."""
        q = request.query
        names = q.getall("name", []) or None
        try:
            since_s = float(q["since_s"]) if "since_s" in q else None
            step = max(1, int(q.get("step", 1)))
        except ValueError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        body = self.instance.history.series(
            names=names, since_s=since_s, step=step
        )
        wd = self.instance.watchdog
        body["alerts"] = list(wd.alerts) if wd is not None else []
        return web.json_response(body)

    async def tenant_slo(self, request) -> web.Response:
        """Per-tenant SLO report: stage latency summaries + tail-sampling
        retention counts against the tenant's configured slo_ms."""
        token = request.match_info["token"]
        if token not in self.instance.tenants:
            return web.json_response({"error": "unknown tenant"}, status=404)
        return web.json_response(self.instance.tenant_slo_report(token))

    async def latency_fleet(self, request) -> web.Response:
        """The fleet latency waterfall (runtime.latency): one merged
        additive p99 decomposition over every ledger window, per-(tenant,
        priority) cohort summaries sorted hottest-first, per-tenant SLO
        burn rates, and the attribution engine's own measured overhead.
        ``?flush=1`` forces pending tail decisions first so a freshly
        driven instance reports current traffic, not the previous
        window's."""
        if request.query.get("flush", "") in ("1", "true"):
            self.instance.tracer.gc(force=True)
        else:
            self.instance.tracer.gc()
        return web.json_response(self.instance.latency.fleet_report())

    async def tenant_latency(self, request) -> web.Response:
        """One tenant's latency decomposition per priority class, its
        5 min / 1 h SLO burn rates, and the worst-N SLO-breach traces
        grouped by dominant stage (each row links its Chrome export).
        ``?worst=N`` sizes the breach list; ``?flush=1`` forces pending
        tail decisions first."""
        token = request.match_info["token"]
        if token not in self.instance.tenants:
            return web.json_response({"error": "unknown tenant"}, status=404)
        if request.query.get("flush", "") in ("1", "true"):
            self.instance.tracer.gc(force=True)
        else:
            self.instance.tracer.gc()
        try:
            worst_n = min(int(request.query.get("worst", 5)), 50)
        except ValueError:
            return web.json_response(
                {"error": "bad worst= value"}, status=400
            )
        return web.json_response(
            self.instance.latency.tenant_report(token, worst_n=worst_n)
        )

    async def tenant_overload(self, request) -> web.Response:
        """Per-tenant overload-control state: credit, degradation ladder
        level + active features, fair-queue standing, per-stage
        expired/late/shed accounting (docs/ROBUSTNESS.md)."""
        token = request.match_info["token"]
        rep = self.instance.tenant_overload_report(token)
        if rep is None:
            return web.json_response({"error": "unknown tenant"}, status=404)
        return web.json_response(rep)

    async def tenant_health(self, request) -> web.Response:
        """Per-tenant model-health report: drift verdict (PSI/KS vs the
        frozen reference), score quantiles, NaN/unscored/expired delivery
        rates, active kernel variant, and the family's shadow-canary
        status (docs/OBSERVABILITY.md "Score health & canaries")."""
        token = request.match_info["token"]
        rep = self.instance.tenant_health_report(token)
        if rep is None:
            return web.json_response({"error": "unknown tenant"}, status=404)
        return web.json_response(rep)

    async def tenant_scores_dist(self, request) -> web.Response:
        """The tenant's score distribution: log-spaced bin edges plus the
        current rolling window and the frozen reference histograms (the
        raw material behind the drift verdict)."""
        token = request.match_info["token"]
        rep = self.instance.tenant_scores_dist(token)
        if rep is None:
            return web.json_response({"error": "unknown tenant"}, status=404)
        return web.json_response(rep)

    async def replay_start(self, request) -> web.Response:
        """Launch a replay job over the tenant's segment store (docs/
        STORAGE.md "Replay"): body ``{"target": "rescore"|"rules"|"train",
        "t0"/"t1"`` (event-time ms), ``"seq0"/"seq1"`` (store seqs),
        ``"device"``, ``"force"``}`` — all optional; default rescores the
        whole store, skipping already-scored rows. Planning happens via
        zone maps; the response reports segments planned vs pruned."""
        token = request.match_info["token"]
        rt = self.instance.tenants.get(token)
        if rt is None:
            return web.json_response({"error": "unknown tenant"}, status=404)
        try:
            body = await request.json() if request.can_read_body else {}
        except ValueError:
            return web.json_response({"error": "malformed JSON"}, status=400)
        if not isinstance(body, dict):
            return web.json_response({"error": "body must be an object"},
                                     status=400)
        target = str(body.get("target", "rescore"))
        try:
            seq_hi = body.get("seq1")
            job = self.instance.replay.start_job(
                token, rt.event_store,
                ts0=int(body.get("t0", 0)),
                ts1=int(body.get("t1", 0)),
                seq_lo=int(body.get("seq0", 0)),
                seq_hi=None if seq_hi is None else int(seq_hi),
                # `or ""`: a JSON null must mean "no device filter", not
                # the literal filter string "None"
                device=str(body.get("device") or ""),
                target=target,
                force=bool(body.get("force", False)),
            )
        except (ValueError, TypeError) as exc:
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response({"job": job.job_id, **job.report()})

    async def replay_list(self, request) -> web.Response:
        """All replay jobs of one tenant (progress, ev/s, zone pruning)."""
        token = request.match_info["token"]
        if token not in self.instance.tenants:
            return web.json_response({"error": "unknown tenant"}, status=404)
        return web.json_response(
            {"jobs": self.instance.replay.list_jobs(token)}
        )

    async def replay_status(self, request) -> web.Response:
        """One replay job's live report: status, cursor, replayed ∪
        skipped-dedupe accounting, throttle ticks, ev/s, segments
        planned/pruned by the zone maps, lag ratio."""
        token = request.match_info["token"]
        rep = self.instance.replay.report(request.match_info["job"])
        if rep is None or rep["tenant"] != token:
            return web.json_response({"error": "unknown job"}, status=404)
        return web.json_response(rep)

    async def tenant_storage(self, request) -> web.Response:
        """The tenant's segment-store shape: segments, zone maps, rows,
        retention/compaction accounting (docs/STORAGE.md)."""
        token = request.match_info["token"]
        rt = self.instance.tenants.get(token)
        if rt is None:
            return web.json_response({"error": "unknown tenant"}, status=404)
        return web.json_response(rt.event_store.measurements.describe())

    async def topology(self, request) -> web.Response:
        return web.json_response(self.instance.topology())

    async def openapi(self, request) -> web.Response:
        paths: dict = {}
        for route in self.app.router.routes():
            info = route.resource.get_info() if route.resource else {}
            path = info.get("path") or info.get("formatter")
            if not path:
                continue
            paths.setdefault(path, {})[route.method.lower()] = {
                "summary": (route.handler.__doc__ or route.handler.__name__).strip()
            }
        return web.json_response(
            {
                "openapi": "3.0.0",
                "info": {"title": "sitewhere-tpu", "version": "0.1.0"},
                "paths": paths,
            }
        )

    # -- device types ----------------------------------------------------
    async def list_device_types(self, request) -> web.Response:
        rt = self._tenant(request)
        page, size = self._page(request)
        items, total = rt.device_management.device_types.page(page, size)
        return web.json_response(_paged(items, total, page, size))

    async def create_device_type(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_DEVICE_MANAGE)
        rt = self._tenant(request)
        b = await request.json()
        dt = DeviceType(
            token=b.get("token") or DeviceType().token,
            name=b.get("name", ""),
            description=b.get("description", ""),
        )
        rt.device_management.create_device_type(dt)
        return web.json_response(_entity(dt), status=201)

    async def get_device_type(self, request) -> web.Response:
        rt = self._tenant(request)
        dt = rt.device_management.get_device_type(request.match_info["token"])
        if dt is None:
            return web.json_response({"error": "not found"}, status=404)
        d = _entity(dt)
        d["commands"] = [_entity(c) for c in dt.commands]
        return web.json_response(d)

    async def add_command(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_DEVICE_MANAGE)
        rt = self._tenant(request)
        b = await request.json()
        cmd = DeviceCommand(
            token=b.get("token") or DeviceCommand().token,
            name=b.get("name", ""),
            namespace=b.get("namespace", "default"),
            parameters=b.get("parameters", []),
        )
        rt.device_management.add_command(request.match_info["token"], cmd)
        return web.json_response(_entity(cmd), status=201)

    # -- devices ---------------------------------------------------------
    async def list_devices(self, request) -> web.Response:
        rt = self._tenant(request)
        page, size = self._page(request)
        items, total = rt.device_management.list_devices(
            page, size, request.query.get("device_type", "")
        )
        return web.json_response(_paged(items, total, page, size))

    async def create_device(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_DEVICE_MANAGE)
        rt = self._tenant(request)
        b = await request.json()
        d = Device(
            token=b.get("token") or Device().token,
            name=b.get("name", ""),
            device_type_token=b.get("device_type_token", ""),
            comments=b.get("comments", ""),
        )
        rt.device_management.create_device(d)
        if b.get("assign", True):
            rt.device_management.create_assignment(
                DeviceAssignment(
                    device_token=d.token,
                    area_token=b.get("area_token", ""),
                    asset_token=b.get("asset_token", ""),
                    customer_token=b.get("customer_token", ""),
                )
            )
        return web.json_response(_entity(d), status=201)

    async def get_device(self, request) -> web.Response:
        rt = self._tenant(request)
        d = rt.device_management.get_device(request.match_info["token"])
        if d is None:
            return web.json_response({"error": "not found"}, status=404)
        out = _entity(d)
        a = rt.device_management.active_assignment_for(d.token)
        if a is not None:
            out["active_assignment"] = _entity(a)
        return web.json_response(out)

    async def delete_device(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_DEVICE_MANAGE)
        rt = self._tenant(request)
        rt.device_management.delete_device(request.match_info["token"])
        return web.json_response({"deleted": request.match_info["token"]})

    async def device_state(self, request) -> web.Response:
        rt = self._tenant(request)
        st = rt.state.get_state(request.match_info["token"])
        if st is None:
            return web.json_response({"error": "no state"}, status=404)
        return web.json_response(st.to_dict())

    async def device_label(self, request) -> web.Response:
        rt = self._tenant(request)
        png = rt.labels.qr_png("device", request.match_info["token"])
        return web.Response(body=png, content_type="image/png")

    # -- assignments + events -------------------------------------------
    async def list_assignments(self, request) -> web.Response:
        rt = self._tenant(request)
        page, size = self._page(request)
        items, total = rt.device_management.list_assignments(
            page, size, request.query.get("device", "")
        )
        return web.json_response(_paged(items, total, page, size))

    async def create_assignment(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_DEVICE_MANAGE)
        rt = self._tenant(request)
        b = await request.json()
        a = DeviceAssignment(
            device_token=b["device_token"],
            area_token=b.get("area_token", ""),
            asset_token=b.get("asset_token", ""),
            customer_token=b.get("customer_token", ""),
        )
        rt.device_management.create_assignment(a)
        return web.json_response(_entity(a), status=201)

    async def release_assignment(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_DEVICE_MANAGE)
        rt = self._tenant(request)
        a = rt.device_management.release_assignment(request.match_info["token"])
        return web.json_response(_entity(a))

    def _event_query(self, request, **extra) -> EventQuery:
        q = request.query
        et = q.get("type", extra.pop("type", ""))
        return EventQuery(
            assignment_token=extra.get("assignment_token", q.get("assignment", "")),
            device_token=q.get("device", ""),
            area_token=q.get("area", ""),
            name=q.get("name", ""),
            event_type=EventType(et) if et else None,
            start_ts=int(q.get("start", 0)),
            end_ts=int(q.get("end", 0)),
            page=int(q.get("page", 1)),
            page_size=int(q.get("page_size", 100)),
        )

    async def assignment_measurements(self, request) -> web.Response:
        """The §3.4 read path: paged measurements for an assignment."""
        rt = self._tenant(request)
        q = self._event_query(
            request, assignment_token=request.match_info["token"]
        )
        evs, total = rt.event_store.list_measurements(q)
        return web.json_response(_paged(evs, total, q.page, q.page_size))

    async def list_events(self, request) -> web.Response:
        rt = self._tenant(request)
        q = self._event_query(request)
        evs, total = rt.event_store.list_events(q)
        return web.json_response(_paged(evs, total, q.page, q.page_size))

    async def invoke_command(self, request) -> web.Response:
        """The §3.2 write path: create + dispatch a command invocation."""
        self.instance.users.require_authority(request["claims"], AUTH_DEVICE_MANAGE)
        rt = self._tenant(request)
        b = await request.json()
        a = rt.device_management.get_assignment(request.match_info["token"])
        if a is None:
            return web.json_response({"error": "unknown assignment"}, status=404)
        inv = DeviceCommandInvocation(
            device_token=a.device_token,
            assignment_token=a.token,
            tenant=rt.tenant,
            command_token=b["command_token"],
            initiator="rest",
            initiator_id=request["claims"].get("sub", ""),
            parameters={k: str(v) for k, v in b.get("parameters", {}).items()},
        )
        rt.event_store.add_event(inv)
        await self.instance.bus.publish(
            self.instance.bus.naming.command_invocations(rt.tenant), inv
        )
        return web.json_response(inv.to_dict(), status=201)

    # -- areas / zones ---------------------------------------------------
    async def list_areas(self, request) -> web.Response:
        rt = self._tenant(request)
        page, size = self._page(request)
        items, total = rt.device_management.list_areas(page, size)
        return web.json_response(_paged(items, total, page, size))

    async def create_area(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_DEVICE_MANAGE)
        rt = self._tenant(request)
        b = await request.json()
        area = Area(
            token=b.get("token") or Area().token,
            name=b.get("name", ""),
            bounds=[tuple(p) for p in b.get("bounds", [])],
        )
        rt.device_management.create_area(area)
        return web.json_response(_entity(area), status=201)

    async def list_zones(self, request) -> web.Response:
        rt = self._tenant(request)
        page, size = self._page(request)
        items, total = rt.device_management.list_zones(
            request.query.get("area", ""), page, size
        )
        return web.json_response(_paged(items, total, page, size))

    async def create_zone(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_DEVICE_MANAGE)
        rt = self._tenant(request)
        b = await request.json()
        z = Zone(
            token=b.get("token") or Zone().token,
            area_token=b["area_token"],
            bounds=[tuple(p) for p in b.get("bounds", [])],
        )
        rt.device_management.create_zone(z)
        return web.json_response(_entity(z), status=201)

    async def search_events(self, request) -> web.Response:
        """Term search over recent events (the Solr-indexer analog):
        AND-semantics tokens over device/name/alert/area fields. Needs
        the tenant's ``search_index`` config flag."""
        rt = self._tenant(request)
        if rt.search is None:
            return web.json_response(
                {"error": "search_index not enabled for this tenant"},
                status=400,
            )
        q = request.query.get("q", "").strip()
        if not q:
            return web.json_response({"error": "missing ?q="}, status=400)
        limit = min(int(request.query.get("limit", 100)), 1000)
        hits = rt.search.search(q, limit=limit)
        return web.json_response({
            "results": [e.to_dict() for e in hits],
            "query": q,
            "indexed": rt.search.indexed,
        })

    # -- device groups ---------------------------------------------------
    @staticmethod
    def _group_dict(g) -> dict:
        return {
            "token": g.token, "name": g.name, "description": g.description,
            "roles": list(g.roles),
            "elements": [
                {"device_token": el.device_token,
                 "nested_group_token": el.nested_group_token,
                 "roles": list(el.roles)}
                for el in g.elements
            ],
        }

    async def list_device_groups(self, request) -> web.Response:
        rt = self._tenant(request)
        page, size = self._page(request)
        items, total = rt.device_management.list_groups(page, size)
        return web.json_response({
            "results": [self._group_dict(g) for g in items],
            "total": total, "page": page, "page_size": size,
        })

    async def create_device_group(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_DEVICE_MANAGE)
        rt = self._tenant(request)
        b = await request.json()
        from sitewhere_tpu.core.model import DeviceGroup, DeviceGroupElement

        g = DeviceGroup(
            name=b.get("name", ""),
            description=b.get("description", ""),
            roles=list(b.get("roles", [])),
            elements=[
                DeviceGroupElement(
                    device_token=el.get("device_token", ""),
                    nested_group_token=el.get("nested_group_token", ""),
                    roles=list(el.get("roles", [])),
                )
                for el in b.get("elements", [])
            ],
            **({"token": b["token"]} if b.get("token") else {}),
        )
        rt.device_management.create_group(g)
        return web.json_response(self._group_dict(g), status=201)

    async def get_device_group(self, request) -> web.Response:
        rt = self._tenant(request)
        g = rt.device_management.get_group(request.match_info["token"])
        if g is None:
            return web.json_response({"error": "unknown group"}, status=404)
        return web.json_response(self._group_dict(g))

    async def delete_device_group(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_DEVICE_MANAGE)
        rt = self._tenant(request)
        rt.device_management.delete_group(request.match_info["token"])
        return web.json_response({"deleted": True})

    async def device_group_devices(self, request) -> web.Response:
        """Flattened device tokens (nested groups walked, ?role= filter)."""
        rt = self._tenant(request)
        try:
            tokens = rt.device_management.group_device_tokens(
                request.match_info["token"], request.query.get("role", "")
            )
        except KeyError:
            return web.json_response({"error": "unknown group"}, status=404)
        return web.json_response({"device_tokens": tokens})

    # -- assets ----------------------------------------------------------
    async def list_assets(self, request) -> web.Response:
        rt = self._tenant(request)
        page, size = self._page(request)
        items, total = rt.asset_management.list_assets(page, size)
        return web.json_response(_paged(items, total, page, size))

    async def create_asset_type(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_DEVICE_MANAGE)
        rt = self._tenant(request)
        b = await request.json()
        at = AssetType(
            token=b.get("token") or AssetType().token,
            name=b.get("name", ""),
            asset_category=b.get("asset_category", "device"),
        )
        rt.asset_management.create_asset_type(at)
        return web.json_response(_entity(at), status=201)

    async def create_asset(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_DEVICE_MANAGE)
        rt = self._tenant(request)
        b = await request.json()
        a = Asset(
            token=b.get("token") or Asset().token,
            name=b.get("name", ""),
            asset_type_token=b["asset_type_token"],
        )
        rt.asset_management.create_asset(a)
        return web.json_response(_entity(a), status=201)

    # -- users -----------------------------------------------------------
    async def list_users(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_ADMIN)
        return web.json_response(
            {"results": [u.to_dict() for u in self.instance.users.list_users()]}
        )

    async def create_user(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_ADMIN)
        b = await request.json()
        u = self.instance.users.create_user(
            b["username"], b["password"], b.get("authorities"),
            b.get("first_name", ""), b.get("last_name", ""),
        )
        return web.json_response(u.to_dict(), status=201)

    # -- tenants ---------------------------------------------------------
    async def list_tenants(self, request) -> web.Response:
        return web.json_response(
            {
                "results": [
                    _entity(t) for t in self.instance.tenant_management.list_tenants()
                ],
                "templates": self.instance.tenant_management.list_templates(),
            }
        )

    async def create_tenant(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_TENANT_ADMIN)
        b = await request.json()
        t = await self.instance.tenant_management.create_tenant(
            b["token"], b.get("name", ""), b.get("template", "default"),
        )
        await self.instance.drain_tenant_updates()
        return web.json_response(_entity(t), status=201)

    async def restart_tenant(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_TENANT_ADMIN)
        await self.instance.tenant_management.restart_tenant(
            request.match_info["token"]
        )
        return web.json_response({"restarting": request.match_info["token"]})

    async def delete_tenant(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_TENANT_ADMIN)
        await self.instance.tenant_management.delete_tenant(
            request.match_info["token"]
        )
        return web.json_response({"deleted": request.match_info["token"]})

    # -- dead-letter inspection / requeue --------------------------------
    async def _bus_topics(self) -> list:
        res = self.instance.bus.topics()
        return await res if asyncio.iscoroutine(res) else res

    async def _bus_peek(self, topic: str, max_items: int) -> dict:
        res = self.instance.bus.peek(topic, max_items)
        return await res if asyncio.iscoroutine(res) else res

    def _dlq_stage_topics(self, tenant: str, topics: list) -> dict:
        """stage name → topic for every dead-letter topic this tenant has
        (the decode stage's failed-decode topic is surfaced beside them)."""
        naming = self.instance.bus.naming
        prefix = naming.dead_letter_prefix(tenant)
        stages = {
            t[len(prefix):]: t for t in topics if t.startswith(prefix)
        }
        failed = naming.failed_decode(tenant)
        if failed in topics:
            stages.setdefault("decode", failed)
        return stages

    @staticmethod
    def _dlq_entry_summary(offset: int, entry) -> dict:
        if not isinstance(entry, dict):
            return {"offset": offset, "payload_type": type(entry).__name__}
        out = {
            k: entry.get(k)
            for k in ("stage", "attempts", "error", "source_topic", "ts",
                      "trace_id")  # trace_id links to GET /api/traces/{id}
            if k in entry
        }
        out["offset"] = offset
        payload = entry.get("payload")
        if payload is not None:
            out["payload_type"] = type(payload).__name__
            rows = getattr(payload, "n", None)
            if rows is not None:
                out["rows"] = int(rows)
        elif "payload_b64" in entry:
            out["payload_type"] = "bytes"
            out["source"] = entry.get("source", "")
        return out

    async def deadletter_list(self, request) -> web.Response:
        """Dead-letter inspection: per-stage depth + newest entries
        (stage / attempts / error / source topic metadata). Cursor-less —
        listing never disturbs the requeue position."""
        token = request.match_info["token"]
        if token not in self.instance.tenants:
            return web.json_response({"error": "unknown tenant"}, status=404)
        limit = min(int(request.query.get("limit", 50)), 500)
        stages = self._dlq_stage_topics(token, await self._bus_topics())
        out = {}
        depth_total = 0
        for stage, topic in sorted(stages.items()):
            view = await self._bus_peek(topic, limit)
            out[stage] = {
                "topic": topic,
                "depth": view["depth"],
                "entries": [
                    self._dlq_entry_summary(o, e) for o, e in view["entries"]
                ],
            }
            depth_total += view["depth"]
        # DLQ depth rides the normal metrics surface too
        self.instance.metrics.gauge(f"dlq.depth.{token}").set(depth_total)
        return web.json_response(
            {"tenant": token, "depth": depth_total, "stages": out}
        )

    async def deadletter_requeue(self, request) -> web.Response:
        """Operator-driven redelivery: drain DLQ entries (optionally one
        stage, body ``{"stage": ...}``) and re-publish each entry's
        payload to its source topic — events re-enter the NORMAL pipeline
        path; decode failures resubmit their raw payload to the tenant's
        event source."""
        self.instance.users.require_authority(
            request["claims"], AUTH_TENANT_ADMIN
        )
        token = request.match_info["token"]
        rt = self.instance.tenants.get(token)
        if rt is None:
            return web.json_response({"error": "unknown tenant"}, status=404)
        stage_filter = ""
        if request.can_read_body:
            try:
                stage_filter = (await request.json()).get("stage", "")
            except (ValueError, json.JSONDecodeError):
                pass
        bus = self.instance.bus
        stages = self._dlq_stage_topics(token, await self._bus_topics())
        requeued: dict = {}
        for stage, topic in sorted(stages.items()):
            if stage_filter and stage != stage_filter:
                continue
            bus.subscribe(topic, "dlq-requeue")
            n = 0
            while True:
                entries = await bus.consume(
                    topic, "dlq-requeue", 256, timeout_s=0
                )
                if not entries:
                    break
                for entry in entries:
                    n += await self._requeue_entry(rt, entry)
            if n:
                requeued[stage] = n
        total = sum(requeued.values())
        self.instance.metrics.counter("dlq.requeued").inc(total)
        return web.json_response({"tenant": token, "requeued": requeued,
                                  "total": total})

    async def _requeue_entry(self, rt: TenantRuntime, entry) -> int:
        if not isinstance(entry, dict):
            return 0
        if "payload_b64" in entry:
            # decode-failure entry: the raw wire payload re-enters through
            # the tenant's event source (same decoder, same dedup)
            await rt.source.receiver.submit(
                base64.b64decode(entry["payload_b64"]), topic="dlq-requeue"
            )
            return 1
        payload = entry.get("payload")
        stage = entry.get("stage", "")
        if payload is None:
            return 0
        # requeue is a RE-admission: an entry that sat parked for minutes
        # must not be expired-dropped the instant it re-enters
        from sitewhere_tpu.runtime.overload import clear_deadline

        clear_deadline(payload)
        if stage.startswith("outbound."):
            # targeted redelivery: replay into the ONE connector that
            # failed — republishing to persisted-events would fan the
            # event into every healthy connector and the rules engine a
            # second time
            cid = stage[len("outbound."):]
            for c in rt.outbound.connectors:
                if c.connector_id == cid:
                    from sitewhere_tpu.core.batch import MeasurementBatch

                    if isinstance(payload, MeasurementBatch):
                        await c.process_batch(payload)
                    else:
                        await c.process(payload)
                    return 1
            return 0  # connector gone: leave accounted in the DLQ counters
        topic = entry.get("source_topic", "")
        if not topic:
            return 0
        self._commit_requeue(topic, payload)
        return 1

    def _commit_requeue(self, topic: str, payload) -> None:
        """Cancellation-atomic DLQ → source-topic move (registered
        commit section, tools/registries.py): the republish and its
        counter land with NO await between them, so a client disconnect
        cancelling the requeue request — or a broker restart racing it —
        cannot strand an entry between "taken from the DLQ poll" and
        "counted as requeued". ``publish_nowait`` is sync on both bus
        flavors; on a remote bus mid-outage the frame rides the bounded
        reconnect buffer (flushed on reconnect/failover, overflow
        counted ``netbus_frames_lost_total`` — never silent)."""
        self.instance.bus.publish_nowait(topic, payload)
        self.instance.metrics.counter("dlq.requeued_entries").inc()

    # -- schedules / batch ----------------------------------------------
    async def list_schedules(self, request) -> web.Response:
        rt = self._tenant(request)
        return web.json_response(
            {"results": [s.to_dict() for s in rt.schedules.list_schedules()]}
        )

    async def create_schedule(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_DEVICE_MANAGE)
        rt = self._tenant(request)
        b = await request.json()
        s = Schedule(
            name=b.get("name", ""),
            at_ts=float(b.get("at_ts", 0)),
            every_s=float(b.get("every_s", 0)),
            cron=b.get("cron", ""),
            command_token=b.get("command_token", ""),
            device_tokens=b.get("device_tokens", []),
            parameters=b.get("parameters", {}),
        )
        rt.schedules.create_schedule(s)
        return web.json_response(s.to_dict(), status=201)

    async def create_batch(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_DEVICE_MANAGE)
        rt = self._tenant(request)
        b = await request.json()
        op = rt.batch.create_operation(
            b["command_token"],
            device_tokens=b.get("device_tokens"),
            group_token=b.get("group_token", ""),
            role=b.get("role", ""),
            parameters=b.get("parameters", {}),
        )
        await rt.batch.submit(op.token)
        return web.json_response(op.summary(), status=201)

    async def get_batch(self, request) -> web.Response:
        rt = self._tenant(request)
        op = rt.batch.get_operation(request.match_info["token"])
        if op is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(op.summary())

    # -- streaming media -------------------------------------------------
    async def create_stream(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_DEVICE_MANAGE)
        rt = self._tenant(request)
        b = await request.json()
        s = rt.media.create_stream(
            b.get("assignment_token", ""),
            b.get("stream_id"),
            b.get("content_type", "application/octet-stream"),
        )
        return web.json_response(
            {"stream_id": s.stream_id, "content_type": s.content_type}, status=201
        )

    async def put_chunk(self, request) -> web.Response:
        self.instance.users.require_authority(request["claims"], AUTH_DEVICE_MANAGE)
        rt = self._tenant(request)
        data = await request.read()
        rt.media.append_chunk(
            request.match_info["id"], int(request.match_info["seq"]), data
        )
        return web.json_response({"ok": True})

    async def get_chunk(self, request) -> web.Response:
        rt = self._tenant(request)
        data = rt.media.get_chunk(
            request.match_info["id"], int(request.match_info["seq"])
        )
        if data is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.Response(body=data)


def make_app(instance: SiteWhereInstance) -> web.Application:
    return RestApi(instance).app


async def serve(instance: SiteWhereInstance, host: str = "127.0.0.1", port: int = 8080):
    """Run the REST gateway (returns the aiohttp AppRunner)."""
    runner = web.AppRunner(make_app(instance))
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    return runner
