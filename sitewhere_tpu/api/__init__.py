"""L6 external APIs: REST gateway (aiohttp) + gRPC service surface.

Capability parity with the reference's service-web-rest (Spring MVC
controllers per resource + JWT auth filter + Swagger docs) and per-service
gRPC endpoints (SURVEY.md §1 L6 / §2.2 [U]).
"""
