"""TCP bus backend: a socket broker + remote client behind the EventBus
seam — the second BusBackend implementation the pluggable-bus contract
demands (SURVEY.md §5 distributed backend: "Kafka-shaped bus for
host-side transport"; the reference's Kafka is exactly this role [U];
reference mount empty, see provenance banner).

Topology: ``BusBrokerServer`` wraps a real in-proc ``EventBus`` (so all
log/cursor/backpressure semantics are literally the same code) behind a
length-prefixed asyncio TCP protocol; ``RemoteEventBus`` implements the
EventBus surface over one multiplexed connection, so a
``SiteWhereInstance`` runs unchanged against either backend.

Wire format: 4-byte big-endian length + pickle. Pickle is acceptable
HERE because broker and clients are the same trust domain (one
deployment's processes — the broker is ours, not an open port protocol);
payloads are arbitrary Python objects (columnar ``MeasurementBatch`` on
the hot path) exactly as on the in-proc bus.

Protocol: requests ``(req_id, op, args)``; responses ``(req_id, ok,
value)``. ``req_id is None`` marks fire-and-forget (no response) — used
by the sync-callable API points (subscribe/seek/publish_nowait/...)
whose in-proc counterparts are synchronous: the frame is written
immediately on the socket, so ordering against later awaited calls on
the same connection is preserved.
"""

from __future__ import annotations

import asyncio
import itertools
import pickle
import struct
from typing import Any, Dict, List, Optional, Tuple

from sitewhere_tpu.runtime.bus import EventBus, FaultPlan, TopicNaming
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent, cancel_and_wait

_LEN = struct.Struct(">I")
MAX_FRAME = 256 * 1024 * 1024


def _dump(obj: Any) -> bytes:
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    return _LEN.pack(len(data)) + data


async def _read_frame(reader: asyncio.StreamReader) -> Any:
    head = await reader.readexactly(_LEN.size)
    (n,) = _LEN.unpack(head)
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    return pickle.loads(await reader.readexactly(n))


class BusBrokerServer(LifecycleComponent):
    """Socket broker fronting an in-proc EventBus."""

    def __init__(
        self,
        naming: Optional[TopicNaming] = None,
        retention: int = 65536,
        host: str = "127.0.0.1",
        port: int = 0,
    ) -> None:
        super().__init__("bus-broker")
        self.bus = EventBus(naming, retention)
        self.host = host
        self.port = port
        self.bound_port: Optional[int] = None
        self._server: Optional[asyncio.base_events.Server] = None
        self._conn_tasks: set = set()

    async def on_start(self) -> None:
        self._server = await asyncio.start_server(
            self._serve_conn, self.host, self.port
        )
        self.bound_port = self._server.sockets[0].getsockname()[1]

    async def on_stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for t in list(self._conn_tasks):
            await cancel_and_wait(t)

    async def _serve_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        write_lock = asyncio.Lock()
        pending: set = set()
        try:
            while True:
                try:
                    req_id, op, args = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                # each request runs in its own task so a long-poll can't
                # block other ops multiplexed on this connection
                t = asyncio.create_task(
                    self._handle(req_id, op, args, writer, write_lock)
                )
                pending.add(t)
                t.add_done_callback(pending.discard)
        finally:
            for t in list(pending):
                await cancel_and_wait(t)
            writer.close()
            self._conn_tasks.discard(task)

    async def _handle(self, req_id, op, args, writer, write_lock) -> None:
        try:
            value = await self._dispatch(op, args)
            ok = True
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 - errors cross the wire
            value = f"{type(exc).__name__}: {exc}"
            ok = False
            self._record_error(op, exc)
        if req_id is None:
            return
        async with write_lock:
            writer.write(_dump((req_id, ok, value)))
            await writer.drain()

    async def _dispatch(self, op: str, args: tuple) -> Any:
        bus = self.bus
        if op == "publish":
            return await bus.publish(*args)
        if op == "publish_nowait":
            return bus.publish_nowait(*args)
        if op == "consume":
            # cap server-side waits so a vanished client can't pin a poll
            # forever; the client re-issues long polls. A dropped
            # (tombstoned) topic returns None so the client can stop
            # re-issuing instead of hot-looping on instant empty replies
            topic, group, max_items, timeout_s = args
            if bus.topic(topic).dropped:
                return None
            if timeout_s is None or timeout_s > 30.0:
                timeout_s = 30.0
            return await bus.consume(topic, group, max_items, timeout_s)
        if op == "subscribe":
            return bus.subscribe(*args)
        if op == "unsubscribe":
            return bus.unsubscribe(*args)
        if op == "seek":
            return bus.seek(*args)
        if op == "topics":
            return bus.topics()
        if op == "drop_topics":
            return bus.drop_topics(*args)
        if op == "undrop":
            return bus.undrop(*args)
        if op == "snapshot_offsets":
            return bus.snapshot_offsets()
        if op == "restore_offsets":
            return bus.restore_offsets(*args)
        if op == "snapshot_state":
            return bus.snapshot_state()
        if op == "restore_state":
            return bus.restore_state(*args)
        if op == "inject_faults":
            drop_p, dup_p, delay_s, topic = args
            return bus.inject_faults(
                topic, FaultPlan(drop_p=drop_p, dup_p=dup_p, delay_s=delay_s)
            )
        if op == "clear_faults":
            return bus.clear_faults(*args)
        raise ValueError(f"unknown op '{op}'")


class RemoteEventBus:
    """EventBus surface over a broker connection. Drop-in for
    SiteWhereInstance(bus=...): same methods, same semantics (the broker
    runs the very same EventBus code)."""

    def __init__(
        self,
        host: str,
        port: int,
        naming: Optional[TopicNaming] = None,
        retention: int = 65536,
    ) -> None:
        self.naming = naming or TopicNaming()
        self.retention = retention
        self.host, self.port = host, port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reply_task: Optional[asyncio.Task] = None
        self._futures: Dict[int, asyncio.Future] = {}
        self._ids = itertools.count(1)

    # -- connection -------------------------------------------------------
    async def connect(self) -> "RemoteEventBus":
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port
        )
        self._reply_task = asyncio.create_task(
            self._reply_loop(), name="netbus-replies"
        )
        return self

    async def close(self) -> None:
        await cancel_and_wait(self._reply_task)
        self._reply_task = None
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        for fut in self._futures.values():
            if not fut.done():
                fut.set_exception(ConnectionError("bus connection closed"))
        self._futures.clear()

    async def _reply_loop(self) -> None:
        assert self._reader is not None
        while True:
            try:
                req_id, ok, value = await _read_frame(self._reader)
            except (asyncio.IncompleteReadError, ConnectionResetError):
                for fut in self._futures.values():
                    if not fut.done():
                        fut.set_exception(
                            ConnectionError("bus connection lost")
                        )
                self._futures.clear()
                return
            fut = self._futures.pop(req_id, None)
            if fut is not None and not fut.done():
                if ok:
                    fut.set_result(value)
                else:
                    fut.set_exception(RuntimeError(value))

    async def _call(self, op: str, *args) -> Any:
        assert self._writer is not None, "RemoteEventBus not connected"
        req_id = next(self._ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._futures[req_id] = fut
        self._writer.write(_dump((req_id, op, args)))
        await self._writer.drain()
        return await fut

    def _send_nowait(self, op: str, *args) -> None:
        """Fire-and-forget for the sync API points; StreamWriter.write is
        synchronous, so ordering vs later calls is preserved."""
        assert self._writer is not None, "RemoteEventBus not connected"
        self._writer.write(_dump((None, op, args)))

    # -- EventBus surface -------------------------------------------------
    async def publish(self, topic: str, payload: Any) -> int:
        return await self._call("publish", topic, payload)

    def publish_nowait(self, topic: str, payload: Any) -> int:
        self._send_nowait("publish_nowait", topic, payload)
        return -1  # offset unknowable without a round trip

    async def consume(
        self,
        topic: str,
        group: str,
        max_items: int = 256,
        timeout_s: Optional[float] = None,
    ) -> List[Any]:
        # the broker caps one server-side poll at 30s; preserve the
        # in-proc semantics for ANY timeout by re-issuing capped polls
        # against a client-side deadline (None = wait forever)
        loop = asyncio.get_running_loop()
        deadline = None if timeout_s is None else loop.time() + timeout_s
        while True:
            remaining = (
                None if deadline is None else max(0.0, deadline - loop.time())
            )
            # always poll at least once: timeout 0 means "non-blocking
            # fetch of whatever is available", exactly like the in-proc bus
            items = await self._call(
                "consume", topic, group, max_items, remaining
            )
            if items is None:
                return []  # topic dropped (tenant teardown) — stop polling
            if items:
                return items
            if remaining is not None and remaining <= 30.0:
                return items  # the broker honored the full remaining wait

    def subscribe(self, topic: str, group: str, at: str = "earliest") -> None:
        self._send_nowait("subscribe", topic, group, at)

    def unsubscribe(self, topic: str, group: str) -> None:
        self._send_nowait("unsubscribe", topic, group)

    def seek(self, topic: str, group: str, offset: int) -> None:
        self._send_nowait("seek", topic, group, offset)

    def drop_topics(self, prefix: str) -> List[str]:
        self._send_nowait("drop_topics", prefix)
        return []

    def undrop(self, prefix: str) -> None:
        self._send_nowait("undrop", prefix)

    async def topics(self) -> List[str]:
        return await self._call("topics")

    def inject_faults(self, topic: str, plan: FaultPlan) -> None:
        # the plan's rng doesn't pickle usefully; send the knobs
        self._send_nowait(
            "inject_faults", plan.drop_p, plan.dup_p, plan.delay_s, topic
        )

    def clear_faults(self, topic: str) -> None:
        self._send_nowait("clear_faults", topic)

    # checkpoint seam — async here (network), awaited by CheckpointManager
    # callers that support remote buses
    async def snapshot_state(self) -> Dict[str, dict]:
        return await self._call("snapshot_state")

    async def restore_state(self, state: Dict[str, dict]) -> None:
        await self._call("restore_state", state)

    async def snapshot_offsets(self) -> Dict[str, Dict[str, int]]:
        return await self._call("snapshot_offsets")

    async def restore_offsets(self, snap: Dict[str, Dict[str, int]]) -> None:
        await self._call("restore_offsets", snap)
