"""TCP bus backend: a socket broker + remote client behind the EventBus
seam — the second BusBackend implementation the pluggable-bus contract
demands (SURVEY.md §5 distributed backend: "Kafka-shaped bus for
host-side transport"; the reference's Kafka is exactly this role [U];
reference mount empty, see provenance banner).

Topology: ``BusBrokerServer`` wraps a real in-proc ``EventBus`` (so all
log/cursor/backpressure semantics are literally the same code) behind a
length-prefixed asyncio TCP protocol; ``RemoteEventBus`` implements the
EventBus surface over one multiplexed connection, so a
``SiteWhereInstance`` runs unchanged against either backend.

Wire format: 4-byte big-endian length + pickle, deserialized through
the RESTRICTED unpickler (``runtime.safepickle``): only stdlib
containers, numpy reconstruction, and ``sitewhere_tpu.*`` classes load —
a compromised peer or tampered frame cannot smuggle an
arbitrary-constructor gadget. Payloads are arbitrary framework objects
(columnar ``MeasurementBatch`` on the hot path) exactly as in-proc.
Batches inside the pickle stream ride the raw-buffer wire codec
(``core.batch``): numeric columns as dtype-tagged raw buffers, token
columns as (vocab, int32 inverse) — so the consumer decodes a batch with
one buffer copy, inherits the group indexes for free, and never pays
per-row pickle ops (docs/PERFORMANCE.md "Raw-buffer wire codec").

Protocol: requests ``(req_id, op, args)``; responses ``(req_id, ok,
value)``. ``req_id is None`` marks fire-and-forget (no response) — used
by the sync-callable API points (subscribe/seek/publish_nowait/...)
whose in-proc counterparts are synchronous: the frame is written
immediately on the socket, so ordering against later awaited calls on
the same connection is preserved.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import os
import pickle
import random
import struct
from collections import deque
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from sitewhere_tpu.runtime import safepickle
from sitewhere_tpu.runtime.bus import EventBus, FaultPlan, TopicNaming
from sitewhere_tpu.runtime.dlog import LeaseJournal
from sitewhere_tpu.runtime.hostlease import LeaseTable
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent, cancel_and_wait
from sitewhere_tpu.runtime.metrics import MetricsRegistry

logger = logging.getLogger("sitewhere.netbus")

# server-side cap on one blocking consume poll (seconds): a vanished
# client must not pin a poll forever. Clients preserve longer timeouts
# by re-issuing capped polls (RemoteEventBus.consume); a caller going
# through ``BusBrokerServer`` directly has its longer timeout TRUNCATED
# to this — logged + counted (netbus_consume_timeout_clamped_total)
# instead of silently, since a single poll returning early looks
# exactly like an empty topic to the caller.
CONSUME_TIMEOUT_CAP_S = 30.0

_LEN = struct.Struct(">I")
MAX_FRAME = 256 * 1024 * 1024


class FrameTooLargeError(ValueError):
    """A frame that would exceed MAX_FRAME, rejected on the WRITE path.

    The read path always enforced the cap; without the write-path check an
    oversized payload reached the peer, which dropped the whole connection
    — poisoning every topic multiplexed on it. Rejecting at the producer
    turns that into a per-call error naming the offending topic."""


def _dump(obj: Any, topic: Optional[str] = None) -> Tuple[bytes, bytes]:
    """Serialize one frame as ``(length-header, payload)``.

    ``MeasurementBatch`` payloads ride the raw-buffer wire codec
    (``core.batch.MeasurementBatch.__reduce__``): numeric columns are
    dtype-tagged raw buffers inside the pickle stream instead of
    per-element pickle ops. The two parts go out via ``writelines`` so a
    large payload is never re-copied into one contiguous
    header+payload bytes object."""
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    if len(data) > MAX_FRAME:
        where = f" for topic '{topic}'" if topic else ""
        raise FrameTooLargeError(
            f"refusing to send a {len(data)}-byte frame{where}: exceeds "
            f"MAX_FRAME ({MAX_FRAME} bytes); the peer would drop the "
            f"connection"
        )
    return _LEN.pack(len(data)), data


class BrokerNotPrimaryError(RuntimeError):
    """A data-plane op reached a warm STANDBY broker. Standbys serve
    only the replication/handshake plane until promoted; a failover-
    aware client treats this (and the handshake's role field) as "try
    the next endpoint", never as a caller-visible failure."""


class BrokerGenerationFencedError(RuntimeError):
    """An append reached a broker whose generation was superseded (a
    standby promoted past it). The payload is still caller-side, so the
    awaited paths ERROR — the client fails over and retries against the
    live primary; nothing is double-served from the zombie."""


class BrokerGeneration:
    """Durable broker generation + fenced flag — the host-epoch fencing
    pattern one level up (docs/ROBUSTNESS.md "Broker fault domain").

    Promotion bumps the generation DURABLY (tmp + fsync + atomic
    replace, the same commit-point pattern as the journals); every
    client handshake (``hello``) carries the highest generation its
    sender has seen, so a zombie primary learns it was superseded from
    the FIRST informed peer and fences itself durably — its appends
    divert from that instant, and stay diverted across its own
    restarts. With no path the state is process-local (in-proc test
    brokers, memory buses)."""

    def __init__(self, path: Optional[Path] = None) -> None:
        self.path = path
        self.generation = 1
        self.fenced_by: Optional[int] = None
        # highest peer generation observed (hellos + replication polls);
        # promotion bumps past it so "newer generation wins" stays
        # decidable even when the old primary was never reachable
        self.seen = 0
        if path is not None and path.exists():
            try:
                st = json.loads(path.read_text())
                self.generation = int(st.get("generation", 1))
                fb = st.get("fenced_by")
                self.fenced_by = int(fb) if fb is not None else None
            except (ValueError, OSError):
                logger.warning("unreadable broker generation file %s — "
                               "starting at generation 1", path)

    @property
    def fenced(self) -> bool:
        return self.fenced_by is not None

    def _persist(self) -> None:
        if self.path is None:
            return
        tmp = self.path.with_suffix(".tmp")
        with open(tmp, "w") as f:
            json.dump({"generation": self.generation,
                       "fenced_by": self.fenced_by}, f)
            f.flush()
            os.fsync(f.fileno())
        tmp.replace(self.path)

    def bump_to(self, generation: int) -> None:
        self.generation = int(generation)
        self.fenced_by = None
        self._persist()

    def fence(self, peer_generation: int) -> None:
        self.seen = max(self.seen, int(peer_generation))
        self.fenced_by = int(peer_generation)
        self._persist()


class _ReplRing:
    """Bounded in-memory replication ring: every mutation the primary
    applies (WAL appends, journaled cursor commits, lease ops, control
    ops) is appended as a seq-numbered record; the warm standby drains
    it via the ``repl_poll`` long-poll. Bounded like every other queue
    in the system (tools/check_queues.py): when a standby lags more
    than ``capacity`` records, the OLDEST are evicted (counted
    ``netbus_repl_evicted_total``) and the poller is told to RESYNC
    from a full snapshot — bounded broker memory beats an unbounded
    backlog held hostage by a slow standby."""

    def __init__(
        self,
        capacity: int = 8192,
        metrics: Optional[MetricsRegistry] = None,
    ) -> None:
        self.capacity = int(capacity)
        self.metrics = metrics or MetricsRegistry()
        self._buf: deque = deque()
        self.base_seq = 0   # seq of _buf[0]
        self.head_seq = 0   # next seq to assign
        self.data_event = asyncio.Event()

    def append(self, rec: tuple) -> int:
        seq = self.head_seq
        self.head_seq += 1
        self._buf.append(rec)
        if len(self._buf) > self.capacity:
            self._buf.popleft()
            self.base_seq += 1
            self.metrics.counter("netbus_repl_evicted_total").inc()
        self.metrics.gauge("netbus_repl_ring_depth").set(len(self._buf))
        self.data_event.set()
        return seq

    def read(
        self, from_seq: int, max_records: int = 1024
    ) -> Tuple[List[tuple], int, bool]:
        """→ (records, next_seq, resync). ``resync`` means ``from_seq``
        was already evicted: the poller must snapshot instead."""
        if from_seq < self.base_seq:
            return [], self.head_seq, True
        start = from_seq - self.base_seq
        recs = list(itertools.islice(self._buf, start, start + max_records))
        return recs, from_seq + len(recs), False


def _publish_topic(op: str, args: tuple) -> Optional[str]:
    """The topic a payload-bearing op targets (for write-path errors)."""
    if op in ("publish", "publish_nowait", "publish_fenced") and args:
        return str(args[0])
    return None


async def _read_frame(reader: asyncio.StreamReader) -> Any:
    head = await reader.readexactly(_LEN.size)
    (n,) = _LEN.unpack(head)
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    return safepickle.loads(await reader.readexactly(n))


class _ConnCtx:
    """Per-connection broker state: the reply writer + its lock, the
    pending consume polls by req_id (cancellable — by the client via
    ``consume_cancel``, or by a lease fence revoking the host's group
    membership), and the host ids whose lease ops arrived on this
    connection (a serving host multiplexes its lease client and its
    consumers over ONE socket, which is what makes fence-time poll
    revocation possible)."""

    __slots__ = ("writer", "write_lock", "consumes", "hosts")

    def __init__(self, writer: asyncio.StreamWriter) -> None:
        self.writer = writer
        self.write_lock = asyncio.Lock()
        self.consumes: Dict[Any, asyncio.Task] = {}
        self.hosts: set = set()


class BusBrokerServer(LifecycleComponent):
    """Socket broker fronting an in-proc EventBus."""

    def __init__(
        self,
        naming: Optional[TopicNaming] = None,
        retention: int = 65536,
        host: str = "127.0.0.1",
        port: int = 0,
        bus: Optional[EventBus] = None,
        metrics: Optional[MetricsRegistry] = None,
        role: str = "primary",
        lease_grace_s: float = 10.0,
        repl_capacity: int = 8192,
    ) -> None:
        super().__init__("bus-broker")
        # pluggable backing bus: pass a dlog.DurableEventBus for a broker
        # whose logs + cursors survive kill -9 (round-4 verdict item 4)
        self.bus = bus if bus is not None else EventBus(naming, retention)
        self.metrics = metrics or MetricsRegistry()
        # broker fault domain (docs/ROBUSTNESS.md "Broker fault
        # domain"): role gates the data plane (standbys only serve the
        # replication/handshake plane until promoted); the durable
        # generation fences a superseded primary's appends; the repl
        # ring feeds the warm standby's WAL/cursor/lease tail
        self.role = role
        self.lease_grace_s = float(lease_grace_s)
        root = getattr(self.bus, "root", None)
        self.generation = BrokerGeneration(
            Path(root) / "generation.json" if root is not None else None)
        lease_journal = None
        if root is not None:
            lease_dir = Path(root) / "leases"
            lease_dir.mkdir(parents=True, exist_ok=True)
            lease_journal = LeaseJournal(lease_dir / "leases.log")
        self.repl_ring = _ReplRing(capacity=repl_capacity,
                                   metrics=self.metrics)
        if hasattr(self.bus, "set_repl_listener"):
            # WAL-level tap: fires synchronously inside append AFTER the
            # flush, so ring order == offset order per partition and a
            # replicated record is never ahead of the primary's own
            # durability point
            self.bus.set_repl_listener(
                lambda t, p, off, payload: self.repl_ring.append(
                    ("wal", t, p, off, payload)))
            # journal-level cursor tap (NOT eager in-memory cursors):
            # replicating only journaled commits preserves at-least-once
            # across failover — the standby's cursors trail, never lead
            self.bus.set_cursor_listener(
                lambda t, g, cur: self.repl_ring.append(("cur", t, g, cur)))
        # host fault domain (docs/ROBUSTNESS.md "Host fault domains"):
        # the broker is the authority on which process holds which
        # slice-set lease, at which epoch — the single place a zombie
        # host's stale-epoch writes can be fenced atomically with the
        # publish they ride on. The journal makes epoch high-water +
        # fences survive broker restart (a restart must not un-fence).
        self.leases = LeaseTable(metrics=self.metrics, journal=lease_journal)
        self._host_conns: Dict[str, set] = {}  # host id → {_ConnCtx}
        self._clamp_logged: set = set()
        self.host = host
        self.port = port
        self.bound_port: Optional[int] = None
        self._server: Optional[asyncio.base_events.Server] = None
        self._conn_tasks: set = set()

    async def on_start(self) -> None:
        self._server = await asyncio.start_server(
            self._serve_conn, self.host, self.port
        )
        self.bound_port = self._server.sockets[0].getsockname()[1]

    async def on_stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for t in list(self._conn_tasks):
            await cancel_and_wait(t)

    async def _serve_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        conn = _ConnCtx(writer)
        pending: set = set()
        try:
            while True:
                try:
                    req_id, op, args = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                except (safepickle.UnpicklingError, ValueError) as exc:
                    # hostile/corrupt frame (gadget class, oversize, bad
                    # shape): drop THIS connection, quietly — the broker
                    # and every other client stay up
                    self._record_error("frame", exc)
                    return
                if op == "consume_cancel":
                    # the client-side consumer task was cancelled (tenant
                    # teardown, handoff): kill its pending long-poll NOW,
                    # before a future publish gets delivered into the void
                    # — the in-proc poll commits the group cursor at
                    # delivery, so a stale poll that outlives its caller
                    # silently eats the next item. Cancelling while the
                    # poll waits is loss-free: nothing is taken until
                    # delivery.
                    t = conn.consumes.get(args[0]) if args else None
                    if t is not None:
                        t.cancel()
                    self.metrics.counter("netbus_consume_cancels_total").inc()
                    continue
                # each request runs in its own task so a long-poll can't
                # block other ops multiplexed on this connection
                t = asyncio.create_task(
                    self._handle(req_id, op, args, conn)
                )
                pending.add(t)
                t.add_done_callback(pending.discard)
                if op == "consume" and req_id is not None:
                    conn.consumes[req_id] = t
                    t.add_done_callback(
                        lambda _t, r=req_id: conn.consumes.pop(r, None)
                    )
        finally:
            for t in list(pending):
                await cancel_and_wait(t)
            for h in conn.hosts:
                conns = self._host_conns.get(h)
                if conns is not None:
                    conns.discard(conn)
                    if not conns:
                        self._host_conns.pop(h, None)
            writer.close()
            self._conn_tasks.discard(task)

    async def _handle(self, req_id, op, args, conn: _ConnCtx) -> None:
        writer, write_lock = conn.writer, conn.write_lock
        try:
            value = await self._dispatch(op, args, conn,
                                         noreply=req_id is None)
            ok = True
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 - errors cross the wire
            value = f"{type(exc).__name__}: {exc}"
            ok = False
            self._record_error(op, exc)
        if req_id is None:
            return
        try:
            frame = _dump((req_id, ok, value))
        except FrameTooLargeError as exc:
            # an oversized RESPONSE (e.g. a giant consume batch) must not
            # poison the connection either — surface it as a call error
            frame = _dump((req_id, False, f"{type(exc).__name__}: {exc}"))
            self._record_error(op, exc)
        try:
            async with write_lock:
                writer.writelines(frame)
                await writer.drain()
        except asyncio.CancelledError:
            if op == "consume" and ok and isinstance(value, list) and value:
                # a consume_cancel (or connection teardown) raced an
                # in-flight delivery: the cursor is already past these
                # items and the reply will never land — at-most-once
                # loses them. Count loudly; the wide stale-poll window
                # is closed by consume_cancel, this is the residual
                # delivery-already-taken instant.
                self.metrics.counter(
                    "netbus_cancelled_delivery_dropped_total"
                ).inc(len(value))
                logger.warning(
                    "consume delivery of %d item(s) dropped by "
                    "cancellation before the reply was written",
                    len(value),
                )
            raise

    def _bind_host_conn(self, host_id: str, conn: Optional[_ConnCtx]) -> None:
        """Remember which connection a host's lease ops ride on — the
        same multiplexed socket carries its consumers, so a fence can
        find (and revoke) the host's parked polls."""
        if conn is None:
            return
        conn.hosts.add(host_id)
        self._host_conns.setdefault(host_id, set()).add(conn)

    def _revoke_host_polls(self, host_id: str) -> None:
        """Fence-time group-membership revocation: cancel every parked
        consume poll on the fenced host's connection(s) and reply ``[]``
        so the client's consumer (if it ever thaws) sees an empty poll,
        not a hang. Cancelling a parked poll is loss-free — the in-proc
        poll takes nothing until delivery. The replies skip ``drain()``
        on purpose: a frozen host isn't reading, and the fence dispatch
        must not block on its socket buffer."""
        for conn in self._host_conns.get(host_id, ()):
            for req_id, t in list(conn.consumes.items()):
                if t.done():
                    continue
                t.cancel()
                self.metrics.counter(
                    "netbus_fence_revoked_polls_total", host=host_id
                ).inc()
                try:
                    conn.writer.writelines(_dump((req_id, True, [])))
                except (ConnectionError, OSError, RuntimeError):
                    pass  # connection already tearing down

    # ops a warm standby still serves: the observability + replication
    # + handshake plane. Everything else raises BrokerNotPrimaryError so
    # a failover-aware client rotates to the real primary.
    STANDBY_OPS = frozenset({
        "metrics_snapshot", "topics", "lags", "peek", "lease_table",
        "snapshot_offsets", "snapshot_state",
    })
    # append ops diverted once this broker's generation is fenced
    APPEND_OPS = frozenset({"publish", "publish_nowait", "publish_fenced"})
    # control-plane mutations streamed to the standby after they apply.
    # "seek" is absent on purpose: on a durable bus its journaled cursor
    # write already reaches the ring via the cursor listener.
    REPLICATED_CTL_OPS = frozenset({
        "subscribe", "unsubscribe", "drop_topics", "undrop",
        "restore_offsets", "restore_state",
    })

    async def _dispatch(
        self, op: str, args: tuple, conn: Optional[_ConnCtx] = None,
        noreply: bool = False,
    ) -> Any:
        # -- broker fault domain (docs/ROBUSTNESS.md "Broker fault
        # domain"): handshake/replication plane first, then role + the
        # generation fence gate the data plane ------------------------
        if op == "hello":
            return self._hello(int(args[0]) if args else 0)
        if op == "repl_poll":
            return await self._repl_poll(*args)
        if op == "repl_snapshot":
            return self._repl_snapshot()
        if op == "promote":
            return self.promote(str(args[0]) if args else "op")
        if self.role != "primary" and op not in self.STANDBY_OPS:
            raise BrokerNotPrimaryError(
                f"standby broker (generation "
                f"{self.generation.generation}) does not serve '{op}'"
            )
        if self.generation.fenced and op in self.APPEND_OPS:
            return self._divert_fenced_append(op, args, noreply)
        value = await self._dispatch_op(op, args, conn)
        # stream the mutation to the standby tail AFTER it applied —
        # never replicate an op that errored. WAL appends + journaled
        # cursors ride their own listeners; this covers the lease and
        # control planes.
        if op.startswith("lease_") and op != "lease_table":
            self.repl_ring.append(("lease", op, args))
        elif op in self.REPLICATED_CTL_OPS:
            self.repl_ring.append(("ctl", op, args))
        return value

    def _hello(self, client_generation: int) -> Dict[str, Any]:
        """Generation-gossip handshake, answered inline by clients
        before their reply loop starts. A peer asserting a NEWER
        generation than ours proves a standby promoted past us while we
        were dead or partitioned: self-fence durably, right here, so
        every later append diverts instead of double-serving."""
        g = self.generation
        if client_generation > g.generation and not g.fenced:
            self._commit_fence_generation(client_generation)
        g.seen = max(g.seen, client_generation)
        return {"generation": g.generation, "role": self.role,
                "fenced": g.fenced}

    def _commit_fence_generation(self, peer_generation: int) -> None:
        """Zombie self-fencing commit point (sync — registered in
        tools/registries.py COMMIT_SECTIONS): the durable fence and its
        counter land together; appends divert from the next dispatch."""
        self.generation.fence(peer_generation)
        self.metrics.counter("broker_generation_fenced_total").inc()
        logger.warning(
            "broker generation %d fenced by peer generation %d — "
            "appends divert to the broker-fenced dead-letter topic",
            self.generation.generation, peer_generation,
        )

    def promote(self, reason: str = "manual") -> Dict[str, Any]:
        """Standby → primary takeover (idempotent on a live primary).
        The new generation is strictly above everything this broker has
        ever seen — its own, any peer's hello, and whoever fenced it —
        so the superseded primary loses every future generation
        comparison, even if it never heard about intermediate hops."""
        g = self.generation
        if self.role == "primary" and not g.fenced:
            return {"generation": g.generation, "role": self.role,
                    "promoted": False}
        new_gen = max(g.generation, g.seen, g.fenced_by or 0) + 1
        self._commit_promotion(new_gen, reason)
        return {"generation": g.generation, "role": self.role,
                "promoted": True}

    def _commit_promotion(self, new_generation: int, reason: str) -> None:
        """Promotion commit point (sync — registered commit section):
        the durable generation bump, the role flip, and the lease
        grace-window extension land together, so host leases inherited
        from the dead primary's table aren't expired by the standby's
        clock before their owners have had ``lease_grace_s`` to
        re-handshake (ISSUE 18: failover must not mass-expire hosts)."""
        self.generation.bump_to(new_generation)
        self.role = "primary"
        extended = self.leases.extend_all(self.lease_grace_s)
        self.metrics.counter("broker_promotions_total").inc()
        logger.warning(
            "promoted to primary at generation %d (%s); extended %d "
            "lease(s) by %.1fs grace",
            new_generation, reason, extended, self.lease_grace_s,
        )

    async def _repl_poll(
        self,
        from_seq: int,
        max_records: int = 1024,
        timeout_s: float = 5.0,
    ) -> Dict[str, Any]:
        """Standby's long-poll against the replication ring. Empty polls
        park on the ring's data event (capped like consume polls); a
        ``from_seq`` older than the ring's base means the standby lagged
        past an eviction → tell it to resync from a full snapshot."""
        ring = self.repl_ring
        loop = asyncio.get_running_loop()
        deadline = loop.time() + max(
            0.0, min(float(timeout_s), CONSUME_TIMEOUT_CAP_S))
        while True:
            # clear BEFORE reading: an append racing the read re-sets
            # the event, so the wait below can't miss it
            ring.data_event.clear()
            recs, nxt, resync = ring.read(int(from_seq), int(max_records))
            if resync:
                self.metrics.counter("netbus_repl_resync_served_total").inc()
                return {"resync": True, "head": ring.head_seq,
                        "generation": self.generation.generation}
            if recs:
                break
            remaining = deadline - loop.time()
            if remaining <= 0:
                break
            try:
                await asyncio.wait_for(ring.data_event.wait(), remaining)
            except asyncio.TimeoutError:
                break
        # primary-side view of standby lag (the standby exports its own)
        self.metrics.gauge("netbus_replication_lag").set(
            ring.head_seq - nxt)
        return {"records": recs, "next": nxt, "head": ring.head_seq,
                "generation": self.generation.generation}

    def _repl_snapshot(self) -> Dict[str, Any]:
        """Full-state resync source for a fresh (or lagged-out) standby.
        ``seq`` is the ring head at capture: every mutation after it is
        in the ring, every one before it is in the snapshot, and the
        overlap a concurrent append could create is absorbed by
        ``replica_append`` idempotence."""
        bus = self.bus
        return {
            "seq": self.repl_ring.head_seq,
            "state": bus.snapshot_state(),
            "offsets": bus.snapshot_offsets(),
            "leases": self.leases.export(),
            "generation": self.generation.generation,
        }

    def _divert_fenced_append(
        self, op: str, args: tuple, noreply: bool
    ) -> Any:
        """A superseded (fenced) broker must not double-serve appends.
        Awaited ops ERROR — the payload is still caller-side, so the
        failover-aware client retries against the promoted primary.
        Fire-and-forget frames have no reply channel to error through:
        divert them to the broker-fenced dead-letter topic for audit
        instead of silently dropping. Both paths count
        ``netbus_fenced_appends_total`` by op."""
        self.metrics.counter("netbus_fenced_appends_total", op=op).inc()
        if not noreply:
            raise BrokerGenerationFencedError(
                f"broker generation {self.generation.generation} fenced "
                f"by generation {self.generation.fenced_by}; retry "
                f"against the promoted primary"
            )
        naming = getattr(self.bus, "naming", None) or TopicNaming()
        self.bus.publish_nowait(
            naming.global_topic("broker-fenced"),
            {
                "topic": _publish_topic(op, args),
                "payload": args[1] if len(args) > 1 else None,
                "op": op,
                "generation": self.generation.generation,
                "fenced_by": self.generation.fenced_by,
            },
        )
        return None

    async def _dispatch_op(
        self, op: str, args: tuple, conn: Optional[_ConnCtx] = None
    ) -> Any:
        bus = self.bus
        if op == "publish":
            return await bus.publish(*args)
        if op == "publish_nowait":
            return bus.publish_nowait(*args)
        if op == "consume":
            # cap server-side waits at CONSUME_TIMEOUT_CAP_S so a
            # vanished client can't pin a poll forever; RemoteEventBus
            # preserves longer timeouts by re-issuing capped polls. A
            # direct caller's longer timeout is TRUNCATED here — logged
            # once per (topic, group) + counted, never silent: a clamped
            # poll returning [] is indistinguishable from an empty topic
            # on the caller's side. A dropped (tombstoned) topic returns
            # None so the client can stop re-issuing instead of
            # hot-looping on instant empty replies.
            topic, group, max_items, timeout_s, *rest = args
            partition = rest[0] if rest else None
            if bus.topic(topic).dropped:
                return None
            if timeout_s is not None and timeout_s > CONSUME_TIMEOUT_CAP_S:
                self.metrics.counter(
                    "netbus_consume_timeout_clamped_total"
                ).inc()
                key = (topic, group)
                if key not in self._clamp_logged:
                    self._clamp_logged.add(key)
                    logger.warning(
                        "consume timeout %.1fs clamped to %.1fs for "
                        "topic=%s group=%s (re-issue polls client-side "
                        "for longer waits)",
                        timeout_s, CONSUME_TIMEOUT_CAP_S, topic, group,
                    )
                timeout_s = CONSUME_TIMEOUT_CAP_S
            elif timeout_s is None:
                timeout_s = CONSUME_TIMEOUT_CAP_S
            return await bus.consume(
                topic, group, max_items, timeout_s, partition
            )
        if op == "subscribe":
            return bus.subscribe(*args)
        if op == "unsubscribe":
            return bus.unsubscribe(*args)
        if op == "seek":
            return bus.seek(*args)
        if op == "topics":
            return bus.topics()
        if op == "drop_topics":
            return bus.drop_topics(*args)
        if op == "undrop":
            return bus.undrop(*args)
        if op == "snapshot_offsets":
            return bus.snapshot_offsets()
        if op == "restore_offsets":
            return bus.restore_offsets(*args)
        if op == "snapshot_state":
            return bus.snapshot_state()
        if op == "restore_state":
            return bus.restore_state(*args)
        if op == "peek":
            return bus.peek(*args)
        if op == "lags":
            return bus.lags()
        if op == "inject_faults":
            drop_p, dup_p, delay_s, topic, *rest = args
            fail_p = rest[0] if rest else 0.0
            return bus.inject_faults(
                topic,
                FaultPlan(
                    drop_p=drop_p, dup_p=dup_p, delay_s=delay_s, fail_p=fail_p
                ),
            )
        if op == "clear_faults":
            return bus.clear_faults(*args)
        # -- host lease control plane (runtime.hostlease) ----------------
        if op == "lease_acquire":
            host_id, slices, ttl_s, min_epoch = args
            self._bind_host_conn(str(host_id), conn)
            return self.leases.acquire(
                host_id, slices, ttl_s, min_epoch=min_epoch
            )
        if op == "lease_renew":
            host_id, epoch, ttl_s, health = args
            self._bind_host_conn(str(host_id), conn)
            return self.leases.renew(host_id, epoch, ttl_s, health)
        if op == "lease_release":
            return self.leases.release(*args)
        if op == "lease_fence":
            high = self.leases.fence(*args)
            # the lease is also the consumer-group SESSION: fencing a
            # host revokes its parked consume polls, Kafka-rebalance
            # style. Without this a hung-but-connected host (SIGSTOP)
            # keeps its long-polls parked at the broker, and every
            # publish after adoption is delivered into its frozen socket
            # buffer — the cursor advances and the adopter starves.
            self._revoke_host_polls(str(args[0]) if args else "")
            return high
        if op == "lease_table":
            return self.leases.table()
        if op == "metrics_snapshot":
            # chaos harnesses + operators read broker-side counters
            # (fenced publishes, lease churn) without a scrape endpoint
            return self.metrics.snapshot()
        if op == "publish_fenced":
            # the zombie-fencing commit point: the lease check and the
            # publish happen in ONE broker-side dispatch, so "lease lost
            # after the check" cannot interleave with the append. A
            # stale-epoch publish is rejected, counted, and DLQ'd —
            # never silently double-served, never silently dropped.
            topic, payload, key, host_id, epoch = args
            if self.leases.check(host_id, epoch):
                return {
                    "fenced": False,
                    "offset": await bus.publish(topic, payload, key),
                }
            self.metrics.counter(
                "host_fenced_publishes_total", host=str(host_id)
            ).inc()
            naming = getattr(bus, "naming", None) or TopicNaming()
            off = bus.publish_nowait(
                naming.host_fenced(str(host_id)),
                {"topic": topic, "host": host_id, "epoch": epoch,
                 "payload": payload},
            )
            return {"fenced": True, "offset": off}
        raise ValueError(f"unknown op '{op}'")


class RemoteEventBus:
    """EventBus surface over a broker connection. Drop-in for
    SiteWhereInstance(bus=...): same methods, same semantics (the broker
    runs the very same EventBus code)."""

    # bound on fire-and-forget frames buffered while disconnected: past
    # it the OLDEST buffered frame is dropped and counted
    # (netbus_frames_lost_total by op) — bounded memory, loud loss
    NOWAIT_BUFFER_MAX = 512

    def __init__(
        self,
        host: Optional[str] = None,
        port: Optional[int] = None,
        naming: Optional[TopicNaming] = None,
        retention: int = 65536,
        reconnect_window_s: float = 20.0,
        metrics: Optional[MetricsRegistry] = None,
        endpoints: Optional[List[Tuple[str, int]]] = None,
        generation: int = 0,
    ) -> None:
        self.naming = naming or TopicNaming()
        self.retention = retention
        # broker fault domain: the client holds a LIST of endpoints
        # (primary first, warm standbys after) and rotates through it on
        # connect errors and on not-primary/fenced rejections — failover
        # is a client-side concern, the brokers never redirect. A single
        # host+port is the degenerate one-endpoint list (and the
        # rollback knob: one endpoint ⇒ exactly the old behavior).
        if endpoints:
            self.endpoints: List[Tuple[str, int]] = [
                (str(h), int(p)) for h, p in endpoints
            ]
        else:
            if host is None or port is None:
                raise ValueError(
                    "RemoteEventBus needs host+port or endpoints=[...]")
            self.endpoints = [(str(host), int(port))]
        self._ep_idx = 0
        # highest broker generation this client has observed; asserted
        # in every hello so a zombie primary learns it was superseded
        # from ANY client that saw the promotion
        self.generation_seen = int(generation)
        self.metrics = metrics or MetricsRegistry()
        self._rng = random.Random()
        self._pending_nowait: deque = deque()
        # how long awaited calls retry against a down broker before the
        # error propagates (0 = fail fast). A durable broker restarted on
        # the same port within the window is transparent to the pipeline:
        # its logs + group cursors come back from disk, so re-issued polls
        # resume exactly where the dead broker left off.
        self.reconnect_window_s = reconnect_window_s
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reply_task: Optional[asyncio.Task] = None
        self._futures: Dict[int, asyncio.Future] = {}
        self._ids = itertools.count(1)
        self._subs: set = set()  # (topic, group, at) replayed on reconnect
        self._closed = False
        self._conn_lock: Optional[asyncio.Lock] = None

    # the current endpoint, kept as properties so every log line and
    # error message names where the client actually points right now
    @property
    def host(self) -> str:
        return self.endpoints[self._ep_idx][0]

    @property
    def port(self) -> int:
        return self.endpoints[self._ep_idx][1]

    def _rotate_endpoint(self) -> None:
        if len(self.endpoints) > 1:
            self._ep_idx = (self._ep_idx + 1) % len(self.endpoints)

    # -- connection -------------------------------------------------------
    async def connect(self) -> "RemoteEventBus":
        # initial connect rides the same rotate/backoff loop as
        # reconnects, so a client started against a just-killed primary
        # finds the promoted standby within the window
        self._conn_lock = asyncio.Lock()
        await self._ensure_connected()
        return self

    async def _connect_once(self) -> None:
        host, port = self.endpoints[self._ep_idx]
        reader, writer = await asyncio.open_connection(host, port)
        # generation-gossip handshake, answered inline BEFORE the reply
        # loop starts: rejects standbys and fenced zombies (raising
        # ConnectionError — an OSError — so the rotate/backoff loop
        # moves on), and tells a superseded primary about the newest
        # generation we saw (it self-fences durably on receipt).
        try:
            writer.writelines(_dump((0, "hello", (self.generation_seen,))))
            await writer.drain()
            _rid, ok, value = await asyncio.wait_for(
                _read_frame(reader), CONSUME_TIMEOUT_CAP_S
            )
        except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                ConnectionResetError, ValueError,
                safepickle.UnpicklingError):
            writer.close()
            raise ConnectionError(
                f"broker handshake failed at {host}:{port}")
        if not ok or not isinstance(value, dict):
            # pre-fault-domain broker ("unknown op 'hello'"): treat as a
            # plain primary — single-endpoint deployments stay compatible
            value = {"generation": 0, "role": "primary", "fenced": False}
        if value.get("fenced") or value.get("role") != "primary":
            writer.close()
            why = "fenced" if value.get("fenced") else str(value.get("role"))
            self.metrics.counter(
                "netbus_endpoint_rejected_total", role=why
            ).inc()
            self.generation_seen = max(
                self.generation_seen, int(value.get("generation", 0)))
            raise ConnectionError(f"broker at {host}:{port} is {why}")
        self.generation_seen = max(
            self.generation_seen, int(value.get("generation", 0)))
        self._reader, self._writer = reader, writer
        self._reply_task = asyncio.create_task(
            self._reply_loop(), name="netbus-replies"
        )
        # re-register group cursors: a durable broker already has them on
        # disk (subscribe is then a no-op), a fresh one needs them back
        for topic, group, at in self._subs:
            self._writer.writelines(
                _dump((None, "subscribe", (topic, group, at)))
            )
        self._flush_pending_nowait()

    def _flush_pending_nowait(self) -> None:
        """Replay fire-and-forget frames buffered during the outage, in
        order, ahead of any new traffic on the fresh connection."""
        while self._pending_nowait:
            _op, frame = self._pending_nowait.popleft()
            self._writer.writelines(frame)
        self.metrics.gauge("netbus_nowait_buffered").set(0)

    # reconnect backoff: first retry after RECONNECT_BASE_S, doubling to
    # RECONNECT_MAX_S, each delay jittered ±RECONNECT_JITTER — a fleet of
    # clients must not hammer a dead (or just-restarted) broker in
    # lockstep for the whole reconnect_window_s
    RECONNECT_BASE_S = 0.05
    RECONNECT_MAX_S = 2.0
    RECONNECT_JITTER = 0.25

    def _backoff(self, attempt: int) -> float:
        d = min(
            self.RECONNECT_BASE_S * (2 ** max(attempt - 1, 0)),
            self.RECONNECT_MAX_S,
        )
        return max(
            0.0, d * (1.0 + self.RECONNECT_JITTER * (2 * self._rng.random() - 1))
        )

    async def _ensure_connected(self) -> None:
        if self._closed:
            raise ConnectionError("bus client closed")
        if self._writer is not None:
            return
        assert self._conn_lock is not None, "RemoteEventBus not connected"
        async with self._conn_lock:
            if self._writer is not None or self._closed:
                return
            loop = asyncio.get_running_loop()
            deadline = loop.time() + self.reconnect_window_s
            attempt = 0
            while True:
                attempt += 1
                try:
                    await self._connect_once()
                    self.metrics.counter(
                        "netbus_reconnects_total", outcome="ok"
                    ).inc()
                    return
                except OSError:
                    self.metrics.counter(
                        "netbus_reconnects_total", outcome="error"
                    ).inc()
                    # rotate: the next attempt tries the next endpoint —
                    # with a standby configured, this IS client failover
                    self._rotate_endpoint()
                    if loop.time() >= deadline:
                        self.metrics.counter(
                            "netbus_reconnects_total", outcome="exhausted"
                        ).inc()
                        eps = ", ".join(
                            f"{h}:{p}" for h, p in self.endpoints)
                        raise ConnectionError(
                            f"bus broker unreachable at {eps}"
                        )
                    # jittered exponential backoff: no hot spinning
                    # against a dead broker inside the window
                    await asyncio.sleep(self._backoff(attempt))

    def _mark_disconnected(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        self._reader = None
        for fut in self._futures.values():
            if not fut.done():
                fut.set_exception(ConnectionError("bus connection lost"))
        self._futures.clear()

    async def close(self) -> None:
        self._closed = True
        await cancel_and_wait(self._reply_task)
        self._reply_task = None
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        # frames buffered for a reconnect that will never come are LOST
        # — count them by op on the way out, never silently
        while self._pending_nowait:
            op, _f = self._pending_nowait.popleft()
            self.metrics.counter("netbus_frames_lost_total", op=op).inc()
        self.metrics.gauge("netbus_nowait_buffered").set(0)
        for fut in self._futures.values():
            if not fut.done():
                fut.set_exception(ConnectionError("bus connection closed"))
        self._futures.clear()

    async def _reply_loop(self) -> None:
        assert self._reader is not None
        while True:
            try:
                req_id, ok, value = await _read_frame(self._reader)
            except (asyncio.IncompleteReadError, ConnectionResetError,
                    OSError):
                self._mark_disconnected()
                return
            except (safepickle.UnpicklingError, ValueError):
                # hostile/corrupt broker frame: treat like a dead link —
                # disconnect and let the reconnect path take over
                self._mark_disconnected()
                return
            fut = self._futures.pop(req_id, None)
            if fut is not None and not fut.done():
                if ok:
                    fut.set_result(value)
                else:
                    fut.set_exception(RuntimeError(value))
            elif ok and isinstance(value, list) and value:
                # a delivery beat our consume_cancel to the wire: the
                # broker committed the cursor, but no caller is awaiting.
                # Loud, not silent — this is the residual at-most-once
                # window the cancel op shrinks from seconds to an RTT.
                logger.warning(
                    "discarding %d item(s) delivered to a cancelled "
                    "consume (req_id=%s)", len(value), req_id,
                )

    async def _call(self, op: str, *args) -> Any:
        loop = asyncio.get_running_loop()
        deadline = loop.time() + max(self.reconnect_window_s, 0.0)
        attempt = 0
        while True:
            attempt += 1
            await self._ensure_connected()
            req_id = next(self._ids)
            # write-path frame cap: an oversized publish fails THIS call
            # (naming the topic) instead of poisoning the peer connection;
            # serialized before the future registers so nothing leaks
            frame = _dump((req_id, op, args), _publish_topic(op, args))
            fut: asyncio.Future = loop.create_future()
            self._futures[req_id] = fut
            try:
                self._writer.writelines(frame)
                await self._writer.drain()
                return await fut
            except asyncio.CancelledError:
                # our caller's task was cancelled (component terminate,
                # tenant handoff) while this call was in flight. For a
                # consume that leaves a live long-poll on the broker:
                # the next publish would be delivered against THIS dead
                # future and discarded — a silent row loss. Tell the
                # broker to cancel the poll (loss-free while it waits).
                self._futures.pop(req_id, None)
                if op == "consume" and self._writer is not None:
                    try:
                        self._send_nowait("consume_cancel", req_id)
                    except Exception:  # noqa: BLE001 - teardown path
                        pass
                raise
            except ConnectionError:
                # broker died mid-call. Retrying may re-apply a mutation
                # whose first attempt landed before the crash (at-least-
                # once, like any acked-after-commit bus); polls are safe
                # to re-issue by construction.
                self._futures.pop(req_id, None)
                if self._closed or loop.time() >= deadline:
                    raise
                await asyncio.sleep(self._backoff(attempt))
            except RuntimeError as exc:
                msg = str(exc)
                if not msg.startswith(("BrokerNotPrimaryError",
                                       "BrokerGenerationFencedError")):
                    raise
                # the endpoint answered, but as a standby or a fenced
                # zombie (a promotion happened mid-connection): the op
                # did NOT apply there, so rotating and retrying against
                # the real primary is duplicate-free — this is the
                # client half of fenced failover.
                self.metrics.counter(
                    "netbus_failovers_total", cause=msg.split(":", 1)[0]
                ).inc()
                self._futures.pop(req_id, None)
                self._mark_disconnected()
                self._rotate_endpoint()
                if self._closed or loop.time() >= deadline:
                    raise ConnectionError(msg)
                await asyncio.sleep(self._backoff(attempt))

    def _send_nowait(self, op: str, *args) -> None:
        """Fire-and-forget for the sync API points; StreamWriter.write is
        synchronous, so ordering vs later calls is preserved. During a
        broker outage these frames are BUFFERED (bounded at
        NOWAIT_BUFFER_MAX) and flushed in order on reconnect — a
        reconnect window no longer silently eats publish_nowait/seek
        frames. Overflow drops the OLDEST frame, counted
        netbus_frames_lost_total by op; subscriptions replay from
        ``_subs`` instead, so they are never buffered or lost."""
        if op == "subscribe":
            self._subs.add(args)
        frame = _dump((None, op, args), _publish_topic(op, args))
        if self._writer is None:
            if op == "subscribe":
                return
            if len(self._pending_nowait) >= self.NOWAIT_BUFFER_MAX:
                old_op, _f = self._pending_nowait.popleft()
                self.metrics.counter(
                    "netbus_frames_lost_total", op=old_op
                ).inc()
            self._pending_nowait.append((op, frame))
            self.metrics.gauge("netbus_nowait_buffered").set(
                len(self._pending_nowait))
            return
        self._writer.writelines(frame)

    # -- EventBus surface -------------------------------------------------
    async def publish(self, topic: str, payload: Any, key: Any = None) -> int:
        return await self._call("publish", topic, payload, key)

    def publish_nowait(self, topic: str, payload: Any, key: Any = None) -> int:
        self._send_nowait("publish_nowait", topic, payload, key)
        return -1  # offset unknowable without a round trip

    async def consume(
        self,
        topic: str,
        group: str,
        max_items: int = 256,
        timeout_s: Optional[float] = None,
        partition: Optional[int] = None,
    ) -> List[Any]:
        # the broker clamps one server-side poll at CONSUME_TIMEOUT_CAP_S
        # (30 s — longer per-poll timeouts are truncated broker-side,
        # counted in netbus_consume_timeout_clamped_total); preserve the
        # in-proc semantics for ANY timeout by re-issuing capped polls
        # against a client-side deadline (None = wait forever)
        loop = asyncio.get_running_loop()
        deadline = None if timeout_s is None else loop.time() + timeout_s
        while True:
            remaining = (
                None if deadline is None else max(0.0, deadline - loop.time())
            )
            # always poll at least once: timeout 0 means "non-blocking
            # fetch of whatever is available", exactly like the in-proc bus
            items = await self._call(
                "consume", topic, group, max_items, remaining, partition
            )
            if items is None:
                return []  # topic dropped (tenant teardown) — stop polling
            if items:
                return items
            if remaining is not None and remaining <= CONSUME_TIMEOUT_CAP_S:
                return items  # the broker honored the full remaining wait

    def subscribe(self, topic: str, group: str, at: str = "earliest") -> None:
        self._send_nowait("subscribe", topic, group, at)

    def unsubscribe(self, topic: str, group: str) -> None:
        self._subs = {s for s in self._subs if s[:2] != (topic, group)}
        self._send_nowait("unsubscribe", topic, group)

    def seek(self, topic: str, group: str, offset: int) -> None:
        self._send_nowait("seek", topic, group, offset)

    def drop_topics(self, prefix: str) -> List[str]:
        self._send_nowait("drop_topics", prefix)
        return []

    def undrop(self, prefix: str) -> None:
        self._send_nowait("undrop", prefix)

    async def topics(self) -> List[str]:
        return await self._call("topics")

    async def peek(self, topic: str, max_items: int = 100) -> dict:
        return await self._call("peek", topic, max_items)

    async def lags(self) -> Dict[str, dict]:
        """Per-topic depth + consumer lag from the broker (the remote
        half of the ``bus_consumer_lag`` gauge collection). Payload trace
        contexts (``core.trace.TraceContext``) cross this wire inside
        their payload frames — the restricted unpickler admits core
        classes, so traces survive a netbus hop with no extra protocol."""
        return await self._call("lags")

    def inject_faults(self, topic: str, plan: FaultPlan) -> None:
        # the plan's rng doesn't pickle usefully; send the knobs
        self._send_nowait(
            "inject_faults", plan.drop_p, plan.dup_p, plan.delay_s, topic,
            plan.fail_p,
        )

    def clear_faults(self, topic: str) -> None:
        self._send_nowait("clear_faults", topic)

    # -- host lease control plane ----------------------------------------
    # Lease ops ride ``_call``, i.e. the SAME jittered-backoff reconnect
    # path every awaited op gets: a renewal issued mid-reconnect retries
    # against the window and lands carrying its original epoch — the
    # epoch is an argument, not connection state, so a broker bounce
    # never resets it (tests/test_netbus.py reconnect-during-renewal).
    async def lease_acquire(
        self,
        host_id: str,
        slices: tuple = (),
        ttl_s: Optional[float] = None,
        min_epoch: int = 0,
    ) -> dict:
        return await self._call(
            "lease_acquire", host_id, tuple(slices), ttl_s, int(min_epoch)
        )

    async def lease_renew(
        self,
        host_id: str,
        epoch: int,
        ttl_s: Optional[float] = None,
        health: Optional[dict] = None,
    ) -> dict:
        try:
            return await self._call(
                "lease_renew", host_id, int(epoch), ttl_s,
                dict(health or {}),
            )
        except (ConnectionError, RuntimeError):
            # the broker stayed unreachable past the reconnect window
            # (or rejected the frame): the caller keeps its epoch and
            # retries next tick — counted, never silent, because a host
            # quietly failing renewals is exactly how a lease expires
            # out from under live traffic
            self.metrics.counter(
                "netbus_lease_renew_failures_total", host=str(host_id)
            ).inc()
            raise

    async def lease_release(self, host_id: str, epoch: int) -> bool:
        return await self._call("lease_release", host_id, int(epoch))

    async def lease_fence(self, host_id: str) -> int:
        return await self._call("lease_fence", host_id)

    async def lease_table(self) -> dict:
        return await self._call("lease_table")

    async def metrics_snapshot(self) -> dict:
        return await self._call("metrics_snapshot")

    async def publish_fenced(
        self, topic: str, payload: Any, host_id: str, epoch: int,
        key: Any = None,
    ) -> dict:
        return await self._call(
            "publish_fenced", topic, payload, key, host_id, int(epoch)
        )

    def publish_fenced_nowait(
        self, topic: str, payload: Any, host_id: str, epoch: int,
        key: Any = None,
    ) -> int:
        self._send_nowait(
            "publish_fenced", topic, payload, key, host_id, int(epoch)
        )
        return -1  # offset unknowable without a round trip

    # checkpoint seam — async here (network), awaited by CheckpointManager
    # callers that support remote buses
    async def snapshot_state(self) -> Dict[str, dict]:
        return await self._call("snapshot_state")

    async def restore_state(self, state: Dict[str, dict]) -> None:
        await self._call("restore_state", state)

    async def snapshot_offsets(self) -> Dict[str, Dict[str, int]]:
        return await self._call("snapshot_offsets")

    async def restore_offsets(self, snap: Dict[str, Dict[str, int]]) -> None:
        await self._call("restore_offsets", snap)


class StandbyReplicator(LifecycleComponent):
    """Warm-standby tail (ISSUE 18 tentpole): colocated with a STANDBY
    ``BusBrokerServer``, it drains the primary's replication ring via
    ``repl_poll`` long-polls and applies each record — WAL appends at
    the primary's offsets, journaled cursor commits, lease-table and
    control-plane ops — to the standby's own (durable) bus. When the
    primary stays unreachable past ``failover_after_s`` it PROMOTES its
    broker (durable generation bump + lease grace window), then flips
    into a fence-peer loop: hello-gossip the old endpoints forever so a
    zombie primary — even one restarted from its old data dir hours
    later — fences itself durably on first contact and diverts appends
    instead of double-serving them."""

    POLL_TIMEOUT_S = 5.0   # server-side long-poll per repl_poll
    RETRY_S = 0.25
    FENCE_PERIOD_S = 1.0
    HELLO_TIMEOUT_S = 5.0

    def __init__(
        self,
        broker: BusBrokerServer,
        primary_endpoints: List[Tuple[str, int]],
        failover_after_s: float = 5.0,
        metrics: Optional[MetricsRegistry] = None,
        faultplan: Any = None,
        promote_on_loss: bool = True,
        on_promote: Any = None,
    ) -> None:
        super().__init__("netbus-standby")
        self.broker = broker
        self.primary_endpoints = [
            (str(h), int(p)) for h, p in primary_endpoints
        ]
        self.failover_after_s = float(failover_after_s)
        # hard client-side cap per replication call: a SIGSTOP'd primary
        # hangs TCP without an RST, so every await on it must time out
        self.call_timeout_s = self.POLL_TIMEOUT_S + max(
            2.0, self.failover_after_s)
        self.metrics = metrics or broker.metrics
        self.faultplan = faultplan
        self.promote_on_loss = promote_on_loss
        self.on_promote = on_promote
        self.applied_seq = 0
        self._synced = False
        self._client: Optional[RemoteEventBus] = None
        self._task: Optional[asyncio.Task] = None
        self._fenced_peers: set = set()

    async def on_start(self) -> None:
        self._task = asyncio.create_task(
            self._tail_loop(), name="netbus-standby-tail"
        )

    async def on_stop(self) -> None:
        if self._task is not None:
            await cancel_and_wait(self._task)
            self._task = None
        await self._drop_client()

    async def _drop_client(self) -> None:
        if self._client is not None:
            c, self._client = self._client, None
            try:
                await c.close()
            except Exception:  # noqa: BLE001 - teardown path
                pass

    async def _client_or_connect(self) -> RemoteEventBus:
        if self._client is None:
            c = RemoteEventBus(
                endpoints=self.primary_endpoints,
                naming=getattr(self.broker.bus, "naming", None),
                reconnect_window_s=0.0,  # fail fast; WE own retry cadence
                metrics=self.metrics,
            )
            try:
                await asyncio.wait_for(c.connect(), self.call_timeout_s)
            except BaseException:
                await c.close()
                raise
            self._client = c
        return self._client

    async def _tail_loop(self) -> None:
        loop = asyncio.get_running_loop()
        last_contact = loop.time()
        while True:
            if self.broker.role == "primary":
                await self._fence_peer_loop()
                return
            if self.faultplan is not None:
                f = self.faultplan.match("standby", "repl")
                if f is not None and f.kind == "repl_stall":
                    # chaos knob: stall the tail so replication lag
                    # grows measurably (faultplan "repl_stall")
                    await asyncio.sleep(f.delay_s)
            try:
                await self._poll_once()
                last_contact = loop.time()
            except asyncio.CancelledError:
                raise
            except (ConnectionError, OSError, RuntimeError,
                    asyncio.TimeoutError) as exc:
                await self._drop_client()
                down_s = loop.time() - last_contact
                if self.promote_on_loss and down_s >= self.failover_after_s:
                    info = self.broker.promote(
                        f"primary unreachable {down_s:.1f}s"
                    )
                    if self.on_promote is not None:
                        self.on_promote(info)
                    continue  # next pass enters the fence-peer loop
                logger.debug("standby poll failed (%r); retrying", exc)
                await asyncio.sleep(self.RETRY_S)

    async def _poll_once(self) -> None:
        client = await self._client_or_connect()
        if not self._synced:
            snap = await asyncio.wait_for(
                client._call("repl_snapshot"), self.call_timeout_s
            )
            self._commit_snapshot(snap)
            return
        reply = await asyncio.wait_for(
            client._call(
                "repl_poll", self.applied_seq, 1024, self.POLL_TIMEOUT_S
            ),
            self.call_timeout_s,
        )
        g = self.broker.generation
        g.seen = max(g.seen, int(reply.get("generation", 0)))
        if reply.get("resync"):
            # we lagged past a ring eviction — rebuild from a snapshot
            self._synced = False
            return
        recs = reply.get("records") or []
        if recs:
            self._commit_records(recs, int(reply["next"]))
        self.metrics.gauge("netbus_replication_lag").set(
            max(0, int(reply.get("head", self.applied_seq))
                - self.applied_seq)
        )

    def _commit_snapshot(self, snap: dict) -> None:
        """Resync commit point (sync — registered commit section): logs,
        cursors, lease table, and the applied-seq watermark move to the
        snapshot as ONE unit, so a cancel mid-resync can't leave the
        watermark claiming state that never landed."""
        broker = self.broker
        broker.bus.restore_state(snap.get("state") or {})
        broker.bus.restore_offsets(snap.get("offsets") or {})
        broker.leases.load(snap.get("leases") or {})
        broker.generation.seen = max(
            broker.generation.seen, int(snap.get("generation", 0)))
        self.applied_seq = int(snap.get("seq", 0))
        self._synced = True
        self.metrics.counter("netbus_repl_resyncs_total").inc()

    def _commit_records(self, recs: List[tuple], next_seq: int) -> None:
        """Batch-apply commit point (sync — registered commit section):
        records apply in ring order and the watermark moves with them —
        never past a record that didn't apply."""
        for rec in recs:
            self._apply_record(rec)
        self.applied_seq = next_seq
        self.metrics.counter("netbus_repl_records_total").inc(len(recs))

    def _apply_record(self, rec: tuple) -> None:
        kind = rec[0]
        broker = self.broker
        if kind == "wal":
            _k, topic, part, offset, payload = rec
            broker.bus.apply_replica_append(topic, part, offset, payload)
        elif kind == "cur":
            _k, topic, group, cursor = rec
            broker.bus.seek(topic, group, cursor)
        elif kind == "lease":
            _k, op, args = rec
            getattr(broker.leases, op[len("lease_"):])(*args)
        elif kind == "ctl":
            _k, op, args = rec
            getattr(broker.bus, op)(*args)
        else:
            logger.warning("unknown replication record kind %r", kind)

    async def _fence_peer_loop(self) -> None:
        """Post-promotion: hello-gossip the old primary endpoints until
        each acknowledges our generation, and keep listening after that
        — a zombie restarted from its old data dir hours later is
        fenced on its FIRST hello, not its first double-served append."""
        while True:
            for ep in self.primary_endpoints:
                try:
                    reply = await self._hello_endpoint(ep)
                except (OSError, asyncio.TimeoutError, ValueError,
                        asyncio.IncompleteReadError,
                        safepickle.UnpicklingError):
                    # down or unreachable: fine — if it ever comes
                    # back we fence it then
                    self._fenced_peers.discard(ep)
                    continue
                if not isinstance(reply, dict):
                    continue
                # symmetric gossip: THEIR generation may outrank ours
                # (a later promotion elsewhere) — same rule applies
                self.broker._hello(int(reply.get("generation", 0)))
                if reply.get("fenced") and ep not in self._fenced_peers:
                    self._fenced_peers.add(ep)
                    self.metrics.counter("broker_peer_fences_total").inc()
                    logger.info(
                        "old primary %s:%d fenced at generation %d",
                        ep[0], ep[1], self.broker.generation.generation,
                    )
            await asyncio.sleep(self.FENCE_PERIOD_S)

    async def _hello_endpoint(self, ep: Tuple[str, int]) -> Any:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(*ep), self.HELLO_TIMEOUT_S
        )
        try:
            writer.writelines(_dump(
                (0, "hello", (self.broker.generation.generation,))
            ))
            await writer.drain()
            _rid, ok, value = await asyncio.wait_for(
                _read_frame(reader), self.HELLO_TIMEOUT_S
            )
            return value if ok else None
        finally:
            writer.close()


# ------------------------------------------------------------------ main
def main(argv: Optional[List[str]] = None) -> None:
    """Standalone broker process: ``python -m sitewhere_tpu.runtime.netbus
    --port P [--data-dir D]``. With --data-dir the broker is DURABLE
    (segmented on-disk logs + cursor journal, dlog.DurableEventBus): kill
    it -9, restart it on the same dir, and consumers resume from their
    persisted offsets with no event loss."""
    import argparse
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--instance-id", default="sw")
    ap.add_argument("--retention", type=int, default=65536)
    ap.add_argument("--data-dir", default="",
                    help="enable durability under this directory")
    ap.add_argument("--partitions", default="{}",
                    help='JSON topic-suffix → count, e.g. '
                         '{"inbound-events": 4}')
    ap.add_argument("--standby-of", default="",
                    help='run as a warm STANDBY tailing this primary: '
                         '"host:port[,host:port...]"')
    ap.add_argument("--failover-after", type=float, default=5.0,
                    help="seconds of primary unreachability before the "
                         "standby promotes itself")
    ap.add_argument("--lease-grace", type=float, default=10.0,
                    help="post-promotion grace extension for inherited "
                         "host leases")
    args = ap.parse_args(argv)
    naming = TopicNaming(args.instance_id)
    parts = {k: int(v) for k, v in json.loads(args.partitions).items()}
    if args.data_dir:
        from sitewhere_tpu.runtime.dlog import DurableEventBus

        bus = DurableEventBus(
            args.data_dir, naming, args.retention, partitions=parts
        )
    else:
        bus = EventBus(naming, args.retention, partitions=parts)

    async def run() -> None:
        role = "standby" if args.standby_of else "primary"
        broker = BusBrokerServer(
            host=args.host, port=args.port, bus=bus, role=role,
            lease_grace_s=args.lease_grace,
        )
        await broker.initialize()
        await broker.start()
        replicator = None
        if args.standby_of:
            eps = []
            for spec in args.standby_of.split(","):
                h, _, p = spec.strip().rpartition(":")
                eps.append((h or "127.0.0.1", int(p)))

            def _on_promote(info: dict) -> None:
                # parents (chaos harnesses, supervisors) watch stdout
                # for the promotion event
                print(json.dumps({"promoted": True, **info}), flush=True)

            replicator = StandbyReplicator(
                broker, eps, failover_after_s=args.failover_after,
                on_promote=_on_promote,
            )
            await replicator.initialize()
            await replicator.start()
        # READY line: parents parse the bound port from stdout
        print(json.dumps({"ready": True, "port": broker.bound_port,
                          "role": role,
                          "generation": broker.generation.generation}),
              flush=True)
        try:
            await asyncio.Event().wait()  # serve until killed
        finally:
            if replicator is not None:
                await replicator.terminate()
            await broker.terminate()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        sys.exit(0)


if __name__ == "__main__":
    main()
